"""SLO watchdog: multi-window burn-rate alerting, built-in event
rules, and user-defined threshold rules — the consumer the signal
planes (PR-1 traces, PR-4 slowlog/drivemon, PR-7 timeline/kernprof)
never had.

The stack records everything and alerts on nothing: an operator learns
about a brownout, a quarantine cascade, or a silent backend collapse
by polling endpoints after the fact, when the evidence has already
aged out of the rings.  The online-EC-on-SSD-arrays study
(arXiv:1709.05365) shows the failures that matter at scale are
queueing/tail REGRESSIONS, not codec errors — a class that needs
continuous burn-rate evaluation, not threshold spot checks.  This
module closes the loop:

- **Burn-rate rules** (``error_burn`` / ``shed_burn`` / ``slow_burn``):
  per-class fractions of 5xx / shed / over-SLO requests evaluated over
  TWO windows of the timeline ring — a fast window (default 1m) that
  reacts, and a slow window (default 15m) that confirms.  Both must
  breach: a fast-only spike is a blip, a slow-only residue is history.
  The slow-request numerator uses the PR-4 ``obs.slow_ms`` SLOs as the
  objective — reconfiguring the SLO reconfigures the alert.

- **Built-in event rules** fed by the state machines that already
  exist: drive suspect/faulty/quarantine census (drivemon), kernel
  backend DOWN (kernprof), MRF heal-backlog growth, hot-cache
  hit-ratio collapse, timeline counter-reset storms.

- **User-defined threshold rules** over any REGISTERED metrics-v2
  series, validated before the config persists (config-KV ``alerts
  rules=<JSON>``, live-reloadable).

Lifecycle per rule: ok -> pending (first breach) -> firing (breach
persists ``pending_ticks`` evaluations) -> resolved (clear for
``resolve_ticks``) — hysteresis on both edges so a flapping signal
cannot page.  Every transition emits a cause-carrying console line
(with ``alert_id``/``rule`` join keys for the JSON log mode), an
``alert`` span event on the active trace (if any), and the
``minio_tpu_v2_alerts_firing`` gauge + transitions counter; firing
additionally freezes an incident bundle (obs/incidents.py) and posts
to the optional webhook (bounded queue, bounded retry + backoff).

The engine ticks on the existing timeline sampler (obs/timeline.py
``_run``) — one thread owns all periodic observability work — and
reads its windows from the sample ring, so burn math inherits the
ring's counter-reset re-basing for free.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request
from collections import deque

OK, PENDING, FIRING = "ok", "pending", "firing"
_STATE_RANK = {OK: 0, PENDING: 1, FIRING: 2}

_CLASSES = ("read", "write", "list", "admin", "select")

# (rule name, per-class sample field, human label) for the three
# burn-rate signals. The fields are the timeline's per-sample DELTAS,
# already counter-reset re-based by the sampler.
BURN_SIGNALS = (("error_burn", "errors", "5xx"),
                ("shed_burn", "shed", "shed"),
                ("slow_burn", "slow", "over-SLO"))


class AlertRuleError(ValueError):
    """A user-submitted alert rule document is malformed."""


# -- window math ------------------------------------------------------------


def window_sums(samples: list[dict], key: str, now: float,
                window_s: float) -> dict[str, float]:
    """Per-class sums of one per-sample delta field over the samples
    stamped inside ``(now - window_s, now]``."""
    out: dict[str, float] = {}
    lo = now - window_s
    for s in samples:
        if s.get("t", 0.0) <= lo:
            continue
        for cls, v in (s.get(key) or {}).items():
            out[cls] = out.get(cls, 0.0) + (v or 0)
    return out


def window_scalar(samples: list[dict], key: str, now: float,
                  window_s: float) -> float:
    lo = now - window_s
    return sum((s.get(key, 0) or 0) for s in samples
               if s.get("t", 0.0) > lo)


def burn_fractions(samples: list[dict], num_key: str, now: float,
                   window_s: float,
                   min_requests: float) -> dict[str, float]:
    """{class: numerator/requests} for classes whose window carried at
    least ``min_requests`` — one request failing out of one is not a
    burn, it is noise."""
    num = window_sums(samples, num_key, now, window_s)
    den = window_sums(samples, "qps", now, window_s)
    return {cls: num.get(cls, 0.0) / total
            for cls, total in den.items() if total >= min_requests}


# -- rules ------------------------------------------------------------------


class _EvalCtx:
    __slots__ = ("samples", "now", "wd", "registry")

    def __init__(self, samples, now, wd, registry=None):
        self.samples = samples
        self.now = now
        self.wd = wd          # thresholds/windows live on the engine
        self.registry = registry  # metrics2 snapshot (user rules only)


class BurnRule:
    """Multi-window SLO burn rate over one per-class fraction."""

    kind = "burn"

    def __init__(self, name: str, num_key: str, what: str):
        self.name = name
        self.num_key = num_key
        self.what = what

    def evaluate(self, ctx: _EvalCtx):
        wd = ctx.wd
        fast = burn_fractions(ctx.samples, self.num_key, ctx.now,
                              wd.fast_s, wd.MIN_REQUESTS)
        slow = burn_fractions(ctx.samples, self.num_key, ctx.now,
                              wd.slow_s, wd.MIN_REQUESTS)
        worst_cls, worst = "", 0.0
        for cls, f in fast.items():
            if (f >= wd.burn_threshold
                    and slow.get(cls, 0.0) >= wd.burn_threshold
                    and f >= worst):
                worst_cls, worst = cls, f
        if not worst_cls:
            return False, "", 0.0
        cause = (f"{worst_cls} {self.what} fraction "
                 f"{worst:.3f} (fast {wd.fast_s:g}s) / "
                 f"{slow.get(worst_cls, 0.0):.3f} (slow {wd.slow_s:g}s)"
                 f" >= {wd.burn_threshold:g}")
        return True, cause, round(worst, 4)


class DriveRule:
    """Drive health census: any suspect/faulty/quarantined drive."""

    name = "drive_degraded"
    kind = "event"

    def evaluate(self, ctx: _EvalCtx):
        last = ctx.samples[-1] if ctx.samples else {}
        census = last.get("drives") or {}
        n = sum(census.get(k, 0) for k in
                ("suspect", "faulty", "quarantined"))
        if n <= 0:
            return False, "", 0.0
        # Name the drives — REDACTED identities, because the node
        # alerts surface is unauthenticated like the metrics pages
        # (admin /drive-health maps them back to full endpoints).
        from .drivemon import DRIVEMON, redacted_endpoint
        names = []
        for row in DRIVEMON.snapshot().get("drives", []):
            if row.get("state") != "ok" or row.get("quarantined"):
                tag = row.get("state", "?")
                if row.get("quarantined"):
                    tag += "+quarantined"
                names.append(
                    f"{redacted_endpoint(str(row.get('endpoint', '')))}"
                    f"={tag}")
        cause = ("degraded drives: " + ", ".join(sorted(names)[:6])
                 if names else
                 f"{n:g} drive(s) suspect/faulty/quarantined")
        return True, cause, float(n)


class BackendRule:
    """Kernel dispatch backend collapse: any backend DOWN."""

    name = "kernel_backend_down"
    kind = "event"

    def evaluate(self, ctx: _EvalCtx):
        last = ctx.samples[-1] if ctx.samples else {}
        states = last.get("backendState") or {}
        down = sorted(b for b, v in states.items() if v >= 2)
        if not down:
            return False, "", 0.0
        from .kernprof import KERNPROF
        info = KERNPROF.snapshot().get("backends", {})
        # Only the exception CLASS rides into the cause: the full
        # lastError repr can carry filesystem paths / compiler output,
        # and causes are served on the UNAUTHENTICATED /v2/alerts
        # surface (same policy as DriveRule's redacted drive ids;
        # admin /kernel-health has the verbatim error).
        bits = []
        for b in down:
            err = str(info.get(b, {}).get("lastError") or "down")
            bits.append(f"{b} ({err.split('(', 1)[0].strip() or 'down'})")
        # mtpu-lint: disable=R13 -- hand-sanitized above: only the exception CLASS (split before the first paren) rides into the cause, never the repr body; the taint engine cannot see through the split
        return (True, "kernel backend down: " + ", ".join(bits),
                float(len(down)))


class MrfRule:
    """MRF heal-queue depth growing monotonically: healing is falling
    behind the failure rate, the precursor of redundancy loss."""

    name = "mrf_backlog"
    kind = "event"
    GROW_TICKS = 5     # consecutive samples the depth must not shrink
    MIN_DEPTH = 16     # and the latest depth must reach this

    def evaluate(self, ctx: _EvalCtx):
        tail = [s.get("mrfDepth", 0) or 0
                for s in ctx.samples[-(self.GROW_TICKS + 1):]]
        if len(tail) < self.GROW_TICKS + 1 \
                or tail[-1] < self.MIN_DEPTH:
            return False, "", 0.0
        if not (all(b >= a for a, b in zip(tail, tail[1:]))
                and tail[-1] > tail[0]):
            return False, "", 0.0
        cause = (f"MRF heal backlog growing {tail[0]:g} -> {tail[-1]:g} "
                 f"over {self.GROW_TICKS} samples")
        return True, cause, float(tail[-1])


class RecoveryRule:
    """Durable MRF journal backlog growing monotonically: crash-
    journaled repairs (erasure/mrfjournal.py) are accumulating faster
    than heal retires them — replay after the NEXT crash will re-queue
    an ever-larger debt, and the sweep/journal loop is not converging.
    Extends the in-memory ``mrf_backlog`` pattern to the durable
    queue: the memory rule catches a stalled worker, this one catches
    repairs that keep FAILING (each failed heal keeps its journal
    entry; see MRFQueue._heal)."""

    name = "recovery_backlog"
    kind = "event"
    GROW_TICKS = 5    # consecutive samples the backlog must not shrink
    MIN_DEPTH = 8     # and the latest backlog must reach this

    def evaluate(self, ctx: _EvalCtx):
        tail = [s.get("mrfJournal", 0) or 0
                for s in ctx.samples[-(self.GROW_TICKS + 1):]]
        if len(tail) < self.GROW_TICKS + 1 \
                or tail[-1] < self.MIN_DEPTH:
            return False, "", 0.0
        if not (all(b >= a for a, b in zip(tail, tail[1:]))
                and tail[-1] > tail[0]):
            return False, "", 0.0
        cause = (f"durable MRF journal backlog growing "
                 f"{tail[0]:g} -> {tail[-1]:g} over "
                 f"{self.GROW_TICKS} samples (repairs journaled "
                 "faster than heal retires them)")
        return True, cause, float(tail[-1])


class CacheRule:
    """Hot-cache hit-ratio collapse: a cache that WAS serving (slow
    window healthy) suddenly missing everything — invalidation storm,
    eviction thrash, or a key-space shift the tier can't absorb."""

    name = "cache_collapse"
    kind = "event"
    MIN_LOOKUPS = 20       # fast-window volume floor
    COLLAPSE_RATIO = 0.1   # fast-window hit ratio below this...
    HEALTHY_RATIO = 0.5    # ...while the slow window shows it worked

    def evaluate(self, ctx: _EvalCtx):
        wd = ctx.wd

        def ratio(window_s):
            hits = window_scalar(ctx.samples, "cacheHits", ctx.now,
                                 window_s)
            misses = window_scalar(ctx.samples, "cacheMisses", ctx.now,
                                   window_s)
            total = hits + misses
            return (hits / total if total else None), total

        fast, fast_total = ratio(wd.fast_s)
        slow, _ = ratio(wd.slow_s)
        if (fast is None or slow is None
                or fast_total < self.MIN_LOOKUPS
                or fast >= self.COLLAPSE_RATIO
                or slow < self.HEALTHY_RATIO):
            return False, "", 0.0
        cause = (f"cache hit ratio collapsed to {fast:.2f} "
                 f"(fast {wd.fast_s:g}s) from {slow:.2f} "
                 f"(slow {wd.slow_s:g}s)")
        return True, cause, round(fast, 4)


class ResetRule:
    """Counter-reset storm: the sampler re-based this many deltas in
    the fast window — crash-looping process, racing scrapers, or a
    registry being reset under live traffic."""

    name = "counter_resets"
    kind = "event"
    STORM = 8

    def evaluate(self, ctx: _EvalCtx):
        n = window_scalar(ctx.samples, "resets", ctx.now, ctx.wd.fast_s)
        if n < self.STORM:
            return False, "", 0.0
        return (True, f"{n:g} counter resets in the fast window "
                "(restart/registry-reset storm)", float(n))


class NoisyNeighborRule:
    """Workload-attribution rule (obs/usage.py): ONE bucket or tenant
    carrying more than ``usage noisy_share`` of a QoS class's admitted
    requests — or of its sheds — over BOTH usage windows (fast reacts,
    slow confirms, same two-window discipline as the burn rules),
    while the class is actually SHEDDING and at least one other
    entity shares it (skew without contention, or a class with a
    single tenant, is a workload shape, not an incident).
    The cause names the tenant by its REDACTED identity (stable
    ``_redact_name`` digest — same policy as DriveRule's drive ids,
    because causes are served on the unauthenticated /v2/alerts
    surface); firing freezes the usage snapshot with the verbatim
    names into the incident bundle (obs/incidents.py carries a
    ``usage`` section), which is where the per-class QoS caps or a
    future per-tenant throttle look up who it actually was."""

    name = "noisy_neighbor"
    kind = "event"

    def evaluate(self, ctx: _EvalCtx):
        from .usage import USAGE, _redact_name
        if not USAGE.enabled:
            return False, "", 0.0
        fast = USAGE.class_shares(USAGE.fast_s, ctx.now)
        slow = USAGE.class_shares(USAGE.slow_s, ctx.now)
        share_min = USAGE.noisy_share
        vol_min = USAGE.noisy_min_requests
        worst = None  # (share, cause)
        for cls, fdoc in fast.items():
            sdoc = slow.get(cls) or {}
            # Two gates before any share matters: the class must be
            # SHEDDING in the fast window (a dominant tenant in an
            # uncontended class harms nobody — and healthy one-bucket
            # traffic must never page), and there must be >= 2
            # distinct entities (no neighbor, no noisy neighbor).
            if fdoc.get("shed", 0) <= 0:
                continue
            for key, denom, count_key, what in (
                    ("topBucket", "admitted", "bucketCount",
                     "admitted requests"),
                    ("topTenant", "admitted", "tenantCount",
                     "admitted requests"),
                    ("topShedBucket", "shed", "bucketCount", "sheds"),
                    ("topShedTenant", "shed", "tenantCount", "sheds")):
                f = fdoc.get(key)
                s = sdoc.get(key)
                if (f is None or s is None
                        or f.get("name") != s.get("name")
                        or fdoc.get(count_key, 0) < 2
                        or fdoc.get(denom, 0) < vol_min
                        or f.get("share", 0.0) < share_min
                        or s.get("share", 0.0) < share_min):
                    continue
                kind = "tenant" if "Tenant" in key else "bucket"
                # REDACTED identity, same policy as DriveRule's drive
                # ids: causes are served on the unauthenticated
                # /v2/alerts surface; the incident bundle (admin)
                # freezes the usage snapshot with the verbatim name.
                cause = (f"{kind} {_redact_name(f['name'])!r} carries "
                         f"{f['share']:.2f} of {cls} {what} "
                         f"(fast {USAGE.fast_s:g}s) / "
                         f"{s['share']:.2f} (slow {USAGE.slow_s:g}s)"
                         f" >= {share_min:g}")
                if worst is None or f["share"] >= worst[0]:
                    worst = (f["share"], cause)
        if worst is None:
            return False, "", 0.0
        return True, worst[1], round(worst[0], 4)


class LoopStallRule:
    """Event-loop stall (obs/loopmon.py flight recorder): a heartbeat
    missed by more than ``obs.loop_stall_ms`` produced a stack capture
    naming the frame that held the loop.  Breaches while any capture
    is younger than the recorder's recent window — a ONE-SHOT block
    (e.g. a 400ms faultinject ``loop_block``) still crosses the
    pending_ticks hysteresis on 1s sampler ticks, then resolves once
    the window drains.  The cause NAMES loop and blamed frame, and
    firing freezes the capture ring into the incident bundle
    (obs/incidents.py ``loops`` section)."""

    name = "loop_stall"
    kind = "event"

    def evaluate(self, ctx: _EvalCtx):
        from .loopmon import LOOPMON
        events = LOOPMON.recent_stalls(now=ctx.now)
        if not events:
            return False, "", 0.0
        worst = max(events, key=lambda e: e.get("overdueMs", 0.0))
        cause = (f"loop {worst['loop']} stalled "
                 f"{worst['overdueMs']:.0f}ms in {worst['topFrame']}"
                 + (f" (+{len(events) - 1} more stall(s) in the "
                    "window)" if len(events) > 1 else ""))
        return True, cause, round(float(worst["overdueMs"]), 1)


class ThresholdRule:
    """User-defined threshold over any registered metrics-v2 series
    (config-KV ``alerts rules``): sum of every series of ``metric``
    whose labels are a superset of ``labels``, compared ``op``
    ``value`` — either the current value (gauges/levels) or the rate
    per second over ``window`` (counters), with the same counter-reset
    re-basing discipline as the timeline."""

    kind = "user"

    def __init__(self, doc: dict):
        self.name = doc["name"]
        self.metric = doc["metric"]
        self.labels = dict(doc.get("labels") or {})
        self.mode = doc.get("mode", "value")
        self.op = doc.get("op", ">")
        self.threshold = float(doc["value"])
        self.window_s = float(doc.get("window_s", 60.0))
        self._last: float | None = None
        self._deltas: deque = deque()  # (t, delta)

    def _series_total(self, registry: dict) -> float:
        metric = (registry or {}).get(self.metric) or {}
        total = 0.0
        for s in metric.get("series", []):
            sl = s.get("labels", {})
            if all(sl.get(k) == v for k, v in self.labels.items()):
                total += s.get("value", s.get("count", 0)) or 0
        return total

    def evaluate(self, ctx: _EvalCtx):
        cur = self._series_total(ctx.registry)
        if self.mode == "rate":
            if self._last is None:
                self._last = cur
                return False, "", 0.0
            d = cur - self._last
            if d < 0:      # counter reset: re-base, never negative
                d = cur
            self._last = cur
            self._deltas.append((ctx.now, d))
            lo = ctx.now - self.window_s
            while self._deltas and self._deltas[0][0] <= lo:
                self._deltas.popleft()
            value = sum(d for _, d in self._deltas) / self.window_s
        else:
            value = cur
        breach = value > self.threshold if self.op == ">" \
            else value < self.threshold
        if not breach:
            return False, "", 0.0
        what = "rate/s" if self.mode == "rate" else "value"
        cause = (f"{self.metric}"
                 f"{json.dumps(self.labels) if self.labels else ''} "
                 f"{what} {value:.4g} {self.op} {self.threshold:g}")
        return True, cause, round(value, 4)


def validate_user_rules(raw: str) -> list[dict]:
    """Parse + validate the ``alerts rules`` JSON document; raises
    AlertRuleError (a ValueError, so the config validator rejects the
    write BEFORE it persists). Returns the normalized rule docs."""
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError as e:
        raise AlertRuleError(f"alerts rules: {e}")
    if not isinstance(doc, list):
        raise AlertRuleError("alerts rules: must be a JSON array")
    from .metrics2 import METRICS2
    registered = METRICS2.registered_names()
    builtin = {name for name, _, _ in BURN_SIGNALS} | {
        DriveRule.name, BackendRule.name, MrfRule.name,
        RecoveryRule.name, CacheRule.name, ResetRule.name,
        NoisyNeighborRule.name, LoopStallRule.name}
    seen: set[str] = set()
    out: list[dict] = []
    for i, r in enumerate(doc):
        if not isinstance(r, dict):
            raise AlertRuleError(f"rule {i}: not an object")
        name = r.get("name")
        if not name or not isinstance(name, str):
            raise AlertRuleError(f"rule {i}: missing name")
        if name in builtin:
            raise AlertRuleError(
                f"rule {i}: {name!r} shadows a built-in rule")
        if name in seen:
            raise AlertRuleError(f"rule {i}: duplicate name {name!r}")
        seen.add(name)
        metric = r.get("metric")
        if metric not in registered:
            raise AlertRuleError(
                f"rule {i}: metric {metric!r} is not registered in "
                "minio_tpu/obs/metrics2.py")
        if r.get("mode", "value") not in ("value", "rate"):
            raise AlertRuleError(
                f"rule {i}: mode must be value|rate")
        if r.get("op", ">") not in (">", "<"):
            raise AlertRuleError(f"rule {i}: op must be > or <")
        labels = r.get("labels") or {}
        if not isinstance(labels, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in labels.items()):
            raise AlertRuleError(
                f"rule {i}: labels must map strings to strings")
        try:
            value = float(r["value"])
            window_s = float(r.get("window_s", 60.0))
        except (KeyError, TypeError, ValueError):
            raise AlertRuleError(
                f"rule {i}: numeric value (and optional window_s) "
                "required")
        if window_s <= 0:
            raise AlertRuleError(f"rule {i}: window_s must be positive")
        unknown = set(r) - {"name", "metric", "labels", "mode", "op",
                            "value", "window_s"}
        if unknown:
            raise AlertRuleError(
                f"rule {i}: unknown fields {sorted(unknown)}")
        out.append({"name": name, "metric": metric, "labels": labels,
                    "mode": r.get("mode", "value"),
                    "op": r.get("op", ">"), "value": value,
                    "window_s": window_s})
    return out


# -- webhook delivery -------------------------------------------------------


class AlertWebhook:
    """Bounded queue + worker POSTing alert transition JSON to the
    configured target.  Delivery is async and lossy-on-overflow (the
    watchdog tick never blocks on the sink), and each item gets a
    BOUNDED retry with exponential backoff — an unreachable endpoint
    costs RETRIES posts per alert, never a retry storm (lint R6)."""

    QUEUE_MAX = 256
    RETRIES = 3
    BACKOFF_S = 0.25

    def __init__(self, endpoint: str, auth_token: str = "",
                 queue_size: int | None = None):
        self.endpoint = endpoint
        self.auth_token = auth_token
        self._q: queue.Queue = queue.Queue(
            maxsize=queue_size or self.QUEUE_MAX)
        self._closed = False
        self._stats_mu = threading.Lock()
        self.sent = 0
        self.failed = 0
        self.dropped = 0
        # mtpu-lint: disable=R1 -- alert delivery daemon: transitions from many sampler ticks share one worker
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="alert-webhook")
        self._worker.start()

    def send(self, doc: dict) -> None:
        if self._closed:
            return
        try:
            self._q.put_nowait(doc)
        except queue.Full:
            with self._stats_mu:
                self.dropped += 1
            from .metrics2 import METRICS2
            METRICS2.inc("minio_tpu_v2_alert_webhook_total",
                         {"result": "dropped"})

    def _run(self) -> None:
        from .metrics2 import METRICS2
        while True:
            item = self._q.get()
            if item is None and not self._closed:
                return
            if self._closed:
                # Replaced mid-incident (endpoint/token rotate): stop
                # delivering, but every queued alert that will never
                # be posted COUNTS as dropped — sent+failed+dropped
                # must keep summing to submissions, and notifications
                # must not vanish without a metric trace. Drain
                # without blocking, then exit (no thread parked on
                # get() forever).
                drops = 0 if item is None else 1
                while True:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is not None:
                        drops += 1
                if drops:
                    with self._stats_mu:
                        self.dropped += drops
                    METRICS2.inc("minio_tpu_v2_alert_webhook_total",
                                 {"result": "dropped"}, drops)
                return
            delivered = False
            for attempt in range(self.RETRIES):  # bounded (R6)
                try:
                    req = urllib.request.Request(
                        self.endpoint, data=json.dumps(item).encode(),
                        headers={"Content-Type": "application/json",
                                 **({"Authorization":
                                     f"Bearer {self.auth_token}"}
                                    if self.auth_token else {})})
                    urllib.request.urlopen(req, timeout=5).read()
                    delivered = True
                    break
                except Exception:  # noqa: BLE001 - endpoint's problem
                    if attempt + 1 < self.RETRIES:
                        time.sleep(self.BACKOFF_S * (2 ** attempt))
            with self._stats_mu:
                if delivered:
                    self.sent += 1
                else:
                    self.failed += 1
            METRICS2.inc("minio_tpu_v2_alert_webhook_total",
                         {"result": "sent" if delivered else "failed"})

    def stats(self) -> dict:
        # No endpoint here: this rides the UNAUTHENTICATED /v2/alerts
        # snapshot, and webhook URLs can embed credentials — the
        # admin-only config dump is where the target lives.
        with self._stats_mu:
            return {"sent": self.sent, "failed": self.failed,
                    "dropped": self.dropped,
                    "queued": self._q.qsize()}

    def close(self) -> None:
        self._closed = True  # checked per item; wake via sentinel
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # worker exits at its next item via the flag


# -- the engine -------------------------------------------------------------


class _Alert:
    __slots__ = ("rule", "state", "alert_id", "breach_streak",
                 "clear_streak", "since", "fired_at", "cause", "value")

    def __init__(self, rule: str):
        self.rule = rule
        self.state = OK
        self.alert_id = ""
        self.breach_streak = 0
        self.clear_streak = 0
        self.since = 0.0
        self.fired_at = 0.0
        self.cause = ""
        self.value = 0.0


class Watchdog:
    """Process-wide alert engine (singleton ``WATCHDOG``), ticked by
    the timeline sampler."""

    # Minimum fast-window request volume per class before a burn
    # fraction is meaningful.
    MIN_REQUESTS = 5
    # Resolved episodes stay visible on the snapshot this long.
    RESOLVED_KEEP_S = 600.0

    def __init__(self):
        self.enabled = True
        self._mu = threading.Lock()
        self.fast_s = 60.0
        self.slow_s = 900.0
        self.burn_threshold = 0.10
        self.pending_ticks = 2
        self.resolve_ticks = 3
        self._user_docs: list[dict] = []
        self._rules: dict[str, object] = self._build_rules(())
        self._alerts: dict[str, _Alert] = {}
        self._recent: deque = deque(maxlen=32)  # resolved episodes
        self._webhook: AlertWebhook | None = None
        self._seq = 0
        # Firing transitions since the last reset() — the bench's
        # per-config ``alerts_fired`` tripwire.
        self.fired_total = 0

    @staticmethod
    def _build_rules(user_docs) -> dict[str, object]:
        rules: dict[str, object] = {}
        for name, key, what in BURN_SIGNALS:
            rules[name] = BurnRule(name, key, what)
        for r in (DriveRule(), BackendRule(), MrfRule(),
                  RecoveryRule(), CacheRule(), ResetRule(),
                  NoisyNeighborRule(), LoopStallRule()):
            rules[r.name] = r
        for doc in user_docs:
            r = ThresholdRule(doc)
            rules[r.name] = r
        return rules

    # -- configuration (config-KV ``alerts`` apply hook) ---------------

    def configure(self, enable: bool = True, fast_s: float = 60.0,
                  slow_s: float = 900.0, burn_threshold: float = 0.10,
                  pending_ticks: int = 2, resolve_ticks: int = 3,
                  user_rules=(), webhook_endpoint: str = "",
                  webhook_auth_token: str = "") -> None:
        with self._mu:
            self.enabled = bool(enable)
            self.fast_s = max(1.0, float(fast_s))
            self.slow_s = max(self.fast_s, float(slow_s))
            self.burn_threshold = min(1.0, max(1e-6,
                                               float(burn_threshold)))
            self.pending_ticks = max(1, int(pending_ticks))
            self.resolve_ticks = max(1, int(resolve_ticks))
            self._user_docs = list(user_rules)
            self._rules = self._build_rules(self._user_docs)
            # Alert state for rules that no longer exist dies with
            # them — but the firing gauge must not: it is only ever
            # written on transitions, so a deleted-while-firing rule
            # would read 1 on /v2/metrics forever.
            dropped = [k for k in self._alerts if k not in self._rules]
            self._alerts = {k: v for k, v in self._alerts.items()
                            if k in self._rules}
            wh = self._webhook
        if dropped:
            from .metrics2 import METRICS2
            for name in dropped:
                METRICS2.set_gauge("minio_tpu_v2_alerts_firing",
                                   {"rule": name}, 0)
        # Webhook lifecycle OUTSIDE the engine lock: close() touches
        # the queue and a swap must never block an evaluation tick.
        if webhook_endpoint:
            if (wh is None or wh.endpoint != webhook_endpoint
                    or wh.auth_token != webhook_auth_token):
                if wh is not None:
                    wh.close()
                self._webhook = AlertWebhook(webhook_endpoint,
                                             webhook_auth_token)
        elif wh is not None:
            wh.close()
            self._webhook = None

    # -- evaluation ----------------------------------------------------

    def tick(self, now: float | None = None,
             samples: list[dict] | None = None) -> list[dict]:
        """One evaluation pass (sampler thread; tests pass synthetic
        samples).  Returns the transitions it announced."""
        if not self.enabled:
            return []
        now = time.time() if now is None else now
        if samples is None:
            from .timeline import TIMELINE
            samples = TIMELINE.samples()
        with self._mu:
            rules = list(self._rules.values())
        registry = None
        if any(getattr(r, "kind", "") == "user" for r in rules):
            from .metrics2 import METRICS2
            registry = METRICS2.snapshot()
        ctx = _EvalCtx(samples, now, self, registry)
        results = []
        for r in rules:
            try:
                results.append((r.name, *r.evaluate(ctx)))
            except Exception:  # noqa: BLE001 - one bad rule must not kill the tick
                from ..logger import Logger
                Logger.get().log_once(
                    f"watchdog: rule {r.name} evaluation failed",
                    "watchdog")
        transitions: list[dict] = []
        with self._mu:
            for name, breach, cause, value in results:
                transitions.extend(
                    self._advance(name, breach, cause, value, now))
        for tr in transitions:
            self._announce(tr)
        return transitions

    # -- lifecycle state machine (caller holds self._mu) ---------------

    def _advance(self, name: str, breach: bool, cause: str,
                 value: float, now: float) -> list[dict]:
        a = self._alerts.get(name)
        if a is None:
            a = self._alerts[name] = _Alert(name)
        out: list[dict] = []

        def tr(old: str, new: str) -> dict:
            return {"rule": name, "alertId": a.alert_id, "old": old,
                    "new": new, "cause": a.cause, "value": a.value,
                    "at": now}

        if breach:
            a.clear_streak = 0
            a.cause, a.value = cause, value
            if a.state == OK:
                self._seq += 1
                a.alert_id = f"{name}-{self._seq}"
                a.state = PENDING
                a.since = now
                a.breach_streak = 1
                out.append(tr(OK, PENDING))
            elif a.state == PENDING:
                a.breach_streak += 1
            if a.state == PENDING \
                    and a.breach_streak >= self.pending_ticks:
                a.state = FIRING
                a.fired_at = now
                self.fired_total += 1
                out.append(tr(PENDING, FIRING))
        else:
            if a.state == PENDING:
                # Cleared below the hysteresis bar: the episode ends
                # quietly — a sub-threshold flap must not page or log.
                a.state = OK
                a.breach_streak = 0
                a.alert_id = ""
            elif a.state == FIRING:
                a.clear_streak += 1
                if a.clear_streak >= self.resolve_ticks:
                    out.append(tr(FIRING, "resolved"))
                    self._recent.append({
                        "rule": name, "alertId": a.alert_id,
                        "cause": a.cause, "value": a.value,
                        "firedAt": a.fired_at, "resolvedAt": now})
                    a.state = OK
                    a.breach_streak = 0
                    a.clear_streak = 0
                    a.alert_id = ""
        return out

    # -- transition fan-out (outside the engine lock) ------------------

    def _announce(self, tr: dict) -> None:
        from ..logger import Logger
        from .metrics2 import METRICS2
        from .span import current_span
        line = (f"watchdog: alert {tr['rule']} {tr['old']} -> "
                f"{tr['new']} ({tr['cause']})")
        log = Logger.get()
        # Join keys ride as structured fields so the JSON log mode
        # correlates alert lines the way audit entries carry trace_id.
        if tr["new"] == FIRING:
            log.warn(line, "watchdog", alert_id=tr["alertId"],
                     rule=tr["rule"])
        else:
            log.info(line, "watchdog", alert_id=tr["alertId"],
                     rule=tr["rule"])
        METRICS2.set_gauge("minio_tpu_v2_alerts_firing",
                           {"rule": tr["rule"]},
                           1 if tr["new"] == FIRING else 0)
        METRICS2.inc("minio_tpu_v2_alert_transitions_total",
                     {"rule": tr["rule"], "state": tr["new"]})
        span = current_span()
        if span is not None:
            span.add_event("alert", rule=tr["rule"],
                           alert_id=tr["alertId"], old=tr["old"],
                           new=tr["new"], cause=tr["cause"][:256])
        # Capture BEFORE the webhook post so the payload can carry the
        # bundle id: an external pager needs the join key to link a
        # firing alert to its frozen diagnosis (admin /incidents).
        if tr["new"] == FIRING:
            from .incidents import INCIDENTS
            try:
                bundle = INCIDENTS.capture(tr)
                tr["bundleId"] = bundle.get("id", "")
            except Exception:  # noqa: BLE001 - diagnosis must not break alerting
                Logger.get().log_once(
                    f"watchdog: incident capture failed for "
                    f"{tr['rule']}", "watchdog")
        elif tr["new"] == "resolved" and tr.get("alertId"):
            # The bundle frozen at firing is keyed by the alert id —
            # the resolve notification joins to the same bundle.
            tr["bundleId"] = tr["alertId"]
        wh = self._webhook
        if wh is not None and tr["new"] in (FIRING, "resolved"):
            wh.send(dict(tr, node="local"))

    # -- reads ---------------------------------------------------------

    def state_of(self, rule: str) -> str:
        a = self._alerts.get(rule)
        return a.state if a is not None else OK

    def counts(self) -> tuple[int, int, str]:
        """(firing, pending, worst firing rule) — the timeline's
        per-sample alerts census."""
        with self._mu:
            firing = pending = 0
            worst, worst_v = "", -1.0
            for a in self._alerts.values():
                if a.state == FIRING:
                    firing += 1
                    if a.value >= worst_v:
                        worst, worst_v = a.rule, a.value
                elif a.state == PENDING:
                    pending += 1
            return firing, pending, worst

    def snapshot(self) -> dict:
        """JSON-ready node view (`/minio-tpu/v2/alerts`; the cluster
        endpoint fan-in merges these via merge_alerts)."""
        now = time.time()
        with self._mu:
            active = []
            for name in sorted(self._alerts):
                a = self._alerts[name]
                if a.state == OK:
                    continue
                active.append({"rule": a.rule, "state": a.state,
                               "alertId": a.alert_id,
                               "since": a.since,
                               "firedAt": a.fired_at,
                               "cause": a.cause, "value": a.value})
            resolved = [dict(ep) for ep in self._recent
                        if now - ep["resolvedAt"]
                        <= self.RESOLVED_KEEP_S]
            doc = {
                "enabled": self.enabled,
                "alerts": active,
                "resolved": resolved,
                "firing": sum(1 for x in active
                              if x["state"] == FIRING),
                "pending": sum(1 for x in active
                               if x["state"] == PENDING),
                "rules": sorted(self._rules),
                "windows": {"fastS": self.fast_s,
                            "slowS": self.slow_s,
                            "burnThreshold": self.burn_threshold},
            }
            wh = self._webhook
        if wh is not None:
            doc["webhook"] = wh.stats()
        return doc

    def reset(self) -> None:
        """Clear alert state + episode counters; configuration (and
        the webhook) survive — bench calls this per config attempt."""
        with self._mu:
            stale = [a.rule for a in self._alerts.values()
                     if a.state != OK]
            self._alerts.clear()
            self._recent.clear()
            self.fired_total = 0
            # User rules carry rate history; rebuild for a clean slate.
            self._rules = self._build_rules(self._user_docs)
        # The firing gauge is transition-written; discarded episodes
        # must not leave it stuck at 1.
        if stale:
            from .metrics2 import METRICS2
            for name in stale:
                METRICS2.set_gauge("minio_tpu_v2_alerts_firing",
                                   {"rule": name}, 0)


def merge_alerts(named_snaps: list[tuple[str, dict]]) -> dict:
    """Merge per-node alert snapshots into one cluster view: one row
    per rule, worst state across nodes, the count of nodes firing it,
    and the worst cause — with an HONEST ``nodes`` count (only nodes
    that actually answered; the endpoint reports unreachable peers
    separately, so a lost node never reads as 'no alerts')."""
    rules: dict[str, dict] = {}
    for node, snap in named_snaps:
        for a in snap.get("alerts", []):
            cur = rules.setdefault(a["rule"], {
                "rule": a["rule"], "state": OK, "nodes": [],
                "nodesFiring": 0, "cause": "", "value": 0.0})
            if _STATE_RANK.get(a.get("state", OK), 0) > \
                    _STATE_RANK.get(cur["state"], 0):
                cur["state"] = a["state"]
            if a.get("state") == FIRING:
                cur["nodesFiring"] += 1
            cur["nodes"].append(node)
            if not cur["cause"] or a.get("value", 0) >= cur["value"]:
                cur["cause"] = a.get("cause", "")
                cur["value"] = a.get("value", 0)
    alerts = [rules[k] for k in sorted(rules)]
    return {"nodes": len(named_snaps),
            "alerts": alerts,
            "firing": sum(1 for a in alerts if a["state"] == FIRING),
            "pending": sum(1 for a in alerts
                           if a["state"] == PENDING)}


# The process-wide watchdog the timeline sampler ticks.
WATCHDOG = Watchdog()
