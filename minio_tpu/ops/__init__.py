"""TPU + CPU data-plane kernels: GF(2^8), Reed-Solomon, HighwayHash."""

from . import gf256, rs_cpu, rs_matrix  # noqa: F401
