"""Self-tuning codec dispatch: a measured per-lane throughput planner.

Every dispatch decision before this module was hardwired: device when
present and the batch cleared a fixed byte threshold, host otherwise.
The bench trajectory proved that policy wrong in both directions — the
r03 device runs (16-18 GiB/s) silently collapsed to 0.016 GiB/s
XLA-CPU stand-ins when the relay died while host-native did 0.983
(BENCH_r04/r05), and the SSD-array online-EC study (arXiv:1709.05365)
shows coding throughput is strongly regime-dependent (batch size,
lane, contention): a fixed crossover is wrong on every box but the one
it was tuned on.

``AUTOTUNE`` replaces the policy with a measured model:

- **Probe ladder** (boot / on demand): one tiny REAL dispatch with a
  known-answer check per (lane, size rung) — the same plumbing as
  kernprof's recovery probes, routed through the fault-injection
  ``kernel`` hook so an active fault plan keeps a lane unmeasured —
  seeding a per-(kernel, batch-size-bucket, lane) throughput model.

- **Live refinement**: every ``KernelStats.record`` feeds its
  (kernel, backend, bytes, wall) sample back here (the PR-7 dispatch
  profiles were built exactly so a probe-and-pick autotuner could read
  them), so the model tracks the box it is actually running on.

- **Plan with hysteresis**: per (kernel, bucket) the fastest HEALTHY
  lane wins; an incumbent is only unseated by a challenger measuring
  ``hysteresis``x faster over >= ``MIN_SAMPLES`` samples, so one noisy
  sample can't flap the plan.  kernprof DOWN lanes are never chosen
  (``KERNPROF.allow`` gates at decision time, not just plan time);
  pinned backends (codec ``backend="tpu"|"cpu"``) bypass the planner
  entirely.

- **Re-planning**: ``batching.reprobe_device_present()`` reports a
  device-census change here (a bounced relay re-adopted, or devices
  lost), which re-probes the affected lanes and recomputes the plan.

Every plan transition and probe outcome publishes through three sinks
(the PR-7 pattern): a cause-carrying console line, a ``codec.plan``
span event on the active trace, and the ``codec_plan_*`` metrics the
timeline samples — so a plan flip mid-incident is joinable to traces
and the slowlog.

The ONLY hardwired threshold left in the tree lives here
(``DEFAULT_DEVICE_MIN_BYTES``, the pre-measurement static fallback);
mtpu-lint R9 keeps dispatch decisions everywhere else free of size
thresholds and lane literals.
"""

from __future__ import annotations

import threading
import time

import numpy as np

# Lane names come from the kernprof state machine — the planner and
# the health machine must agree on identity.
from ..obs.kernprof import BACKENDS, DEVICE, HOST, NATIVE, XLA_CPU

RS_ENCODE = "rs_encode"
RS_DECODE = "rs_decode"
SELECT_SCAN = "select_scan"
# Regenerating-code (REGEN storage class) GF apply — ops/rs_regen.py.
REGEN_CODE = "regen_code"
KERNELS = (RS_ENCODE, RS_DECODE, SELECT_SCAN, REGEN_CODE)
# The RS probe ladder seeds only the codec kernels — select scans get
# their OWN known-answer probe (ops/select_kernels.probe_lane): GF
# table-gather throughput says nothing about predicate-mask math.
_CODEC_KERNELS = (RS_ENCODE, RS_DECODE)
# Lanes a select scan can actually run on: there is no C++ select
# kernel, so NATIVE is not probed (decide() falling back to NATIVE is
# mapped to HOST by select_kernels.choose_lane).
_SELECT_PROBE_ROWS = 4096

# Batch-size buckets for the dispatch decision: coalesced-dispatch
# bytes, not block counts (the decision input is "how big is this
# batch", the kernprof histogram's block-bucket answers "how full").
_SIZE_BUCKETS = ((64 * 1024, "<64K"),
                 (1024 * 1024, "64K-1M"),
                 (4 * 1024 * 1024, "1-4M"),
                 (16 * 1024 * 1024, "4-16M"))
TOP_BUCKET = "16M+"
BUCKETS = tuple(name for _, name in _SIZE_BUCKETS) + (TOP_BUCKET,)

# The pre-measurement static policy: device when present and the batch
# clears this floor (the historical erasure/codec.py TPU_MIN_BYTES).
# Used only until the probe ladder has run, and when autotuning is
# disabled by config — the ONE sanctioned hardwired threshold (R9).
DEFAULT_DEVICE_MIN_BYTES = 4 * 1024 * 1024

_LANE_INDEX = {b: i for i, b in enumerate(BACKENDS)}

# No-model-data last resort, most- to least-preferred: numpy host
# ranks ABOVE jit-on-CPU — BENCH_r04/r05 measured xla-cpu ~8x slower
# than plain numpy on this class of box, and this branch by
# definition has no measurement saying otherwise.
_FALLBACK_ORDER = (DEVICE, NATIVE, HOST, XLA_CPU)

# Probe rung per bucket: (data bytes, B, k, S). B*k*S == bytes; shapes
# stay in one (B=8, k=4) family so only S varies rung to rung.  The
# top bucket is seeded from the 4-16M rung (a 32MiB probe would pay
# more wall than it buys — throughput is flat past the 8MiB knee).
_PROBE_K, _PROBE_M = 4, 2
_PROBE_RUNGS = (("<64K", 8, 1024),        # 32 KiB
                ("64K-1M", 8, 16384),     # 512 KiB
                ("1-4M", 8, 65536),       # 2 MiB
                ("4-16M", 8, 262144))     # 8 MiB


def size_bucket(nbytes: int) -> str:
    for ub, name in _SIZE_BUCKETS:
        if nbytes <= ub:
            return name
    return TOP_BUCKET


class _LaneModel:
    """EWMA throughput for one (kernel, bucket, lane)."""

    __slots__ = ("bps", "samples")

    def __init__(self):
        self.bps = 0.0
        self.samples = 0

    def feed(self, bps: float, alpha: float = 0.3) -> None:
        self.bps = bps if self.samples == 0 else (
            alpha * bps + (1.0 - alpha) * self.bps)
        self.samples += 1


class CodecAutotuner:
    """Process-wide codec dispatch planner (``AUTOTUNE``)."""

    # A challenger lane must measure this much faster than the
    # incumbent to flip the plan — one lucky sample amid scheduler
    # noise must not flap the dispatch policy (and its three sinks).
    HYSTERESIS = 1.25
    # Live samples a challenger needs before it may unseat an
    # incumbent (probe-ladder seeds count as one deliberate sample and
    # set the INITIAL plan, where there is no incumbent to protect).
    MIN_SAMPLES = 3
    # Clamp floor for measured walls: a sub-resolution timer blip on a
    # 64KiB batch computes as an absurd GiB/s and would poison the
    # EWMA.  Clamping (not rejecting) keeps the evidence — native
    # encodes 32KiB in ~10us on this box, and DROPPING those samples
    # would lock the <64K bucket out of live-only convergence and out
    # of hysteresis challenges entirely.
    MIN_WALL_S = 5e-6

    def __init__(self):
        self.enabled = True
        self.hysteresis = self.HYSTERESIS
        self._mu = threading.Lock()
        self._model: dict[tuple[str, str, str], _LaneModel] = {}
        self._plan: dict[tuple[str, str], str] = {}
        self._plan_version = 0
        self._probed = False
        self._probe_mu = threading.Lock()
        self._probe_thread: threading.Thread | None = None
        self._last_probe: dict[str, dict] = {}
        self._last_select_probe: dict[str, dict] = {}
        self._last_regen_probe: dict[str, dict] = {}
        # Transition fan-out, kernprof-style: decided under _mu,
        # published FIFO under _announce_mu so two threads replanning
        # back-to-back can't publish the sinks in swapped order.
        self._pending: list[tuple] = []
        self._announce_mu = threading.Lock()

    # -- live model -----------------------------------------------------

    def observe(self, kernel: str, backend: str, nbytes: int,
                wall_s: float) -> None:
        """One real dispatch outcome (fed by ``KERNPROF.record_dispatch``
        — the PR-7 profile layer is the autotuner's sensor)."""
        if kernel not in KERNELS or backend not in _LANE_INDEX:
            return
        if wall_s <= 0 or nbytes <= 0:
            return
        bucket = size_bucket(nbytes)
        with self._mu:
            self._feed_locked(kernel, bucket, backend,
                              nbytes / max(wall_s, self.MIN_WALL_S))
            self._replan_locked(kernel, bucket, "live samples")
            pending = bool(self._pending)
        # Flush only when this sample actually flipped the plan — the
        # no-op case must stay a couple of dict ops under one lock.
        if pending:
            self._flush_announcements()

    def _feed_locked(self, kernel: str, bucket: str, lane: str,
                     bps: float) -> None:
        key = (kernel, bucket, lane)
        m = self._model.get(key)
        if m is None:
            m = self._model[key] = _LaneModel()
        m.feed(bps)

    # -- decisions ------------------------------------------------------

    def decide(self, kernel: str, nbytes: int) -> str:
        """The dispatch decision: fastest measured healthy lane for
        this (kernel, size bucket); static pre-measurement policy until
        the ladder has run or when autotuning is off.  Never returns a
        kernprof-DOWN lane."""
        from ..obs.kernprof import KERNPROF
        lane = None
        if self.enabled:
            bucket = size_bucket(nbytes)
            with self._mu:
                lane = self._plan.get((kernel, bucket))
                if lane is not None and not self._probed:
                    # Live-only plan (probe_on_boot=off): engage only
                    # once the chosen lane has real evidence — a
                    # single early sample must not steer dispatch.
                    m = self._model.get((kernel, bucket, lane))
                    if m is None or m.samples < self.MIN_SAMPLES:
                        lane = None
        if lane is None:
            lane = self._static_lane(nbytes)
        if KERNPROF.allow(lane) and self._lane_available(lane):
            return lane
        # Planned lane is DOWN/gone: next-fastest healthy lane from
        # the model, preference order as the no-data fallback.
        bucket = size_bucket(nbytes)
        with self._mu:
            ranked = sorted(
                ((m.bps, ln) for ln, m in
                 ((ln, self._model.get((kernel, bucket, ln)))
                  for ln in BACKENDS)
                 if m is not None and m.samples > 0),
                reverse=True)
        for _, ln in ranked:
            if ln != lane and KERNPROF.allow(ln) \
                    and self._lane_available(ln):
                return ln
        for ln in _FALLBACK_ORDER:
            if ln != lane and KERNPROF.allow(ln) \
                    and self._lane_available(ln):
                return ln
        return HOST  # the floor that can never go away

    def use_jit_lane(self, kernel: str, nbytes: int) -> bool:
        """True when the plan routes this dispatch through the jitted
        rs_tpu path (which lands on the device when one answers,
        XLA-CPU otherwise — ``batching.attempt_backend``)."""
        return self.decide(kernel, nbytes) in (DEVICE, XLA_CPU)

    def host_lane(self, kernel: str, nbytes: int) -> str | None:
        """Which HOST-side lane the plan picked (NATIVE lets the C++
        kernel answer with numpy fallback; HOST forces pure numpy);
        None when the plan routed to the jit path."""
        lane = self.decide(kernel, nbytes)
        return lane if lane in (NATIVE, HOST) else None

    def coalesce_worthwhile(self) -> bool:
        """Should PUT encodes pay the cross-request coalescing window?
        Only when a real device exists AND the plan still sends some
        encode bucket to it — a window in front of host encodes adds
        latency and batches nothing the host cares about.  Mirrors
        decide()'s evidence rule (probed OR >= MIN_SAMPLES live
        samples per entry), so a probe_on_boot=off box whose
        live-built plan routed every bucket off-device stops paying
        the window too; buckets with no engaged evidence yet keep the
        static device-present answer."""
        from . import batching
        if not batching.device_present():
            return False
        if not self.enabled:
            return True  # static policy: device-present == coalesce
        with self._mu:
            engaged = 0
            for (k, b), lane in self._plan.items():
                if k != RS_ENCODE:
                    continue
                if not self._probed:
                    m = self._model.get((k, b, lane))
                    if m is None or m.samples < self.MIN_SAMPLES:
                        continue  # not engaged: static still rules it
                if lane == DEVICE:
                    return True
                engaged += 1
            # Evidence for every encode bucket and none chose the
            # device -> the window buys nothing; otherwise some
            # bucket still follows the static device policy.
            return engaged < len(BUCKETS)

    def _static_lane(self, nbytes: int) -> str:
        from . import batching
        if batching.device_present() \
                and nbytes >= DEFAULT_DEVICE_MIN_BYTES:
            return DEVICE
        # NATIVE resolves to numpy inside host_apply when the C++ lib
        # is unavailable — same ladder the serving path always had.
        return NATIVE

    @staticmethod
    def _lane_available(lane: str) -> bool:
        if lane == DEVICE:
            from . import batching
            return batching.device_present()
        if lane == XLA_CPU:
            # attempt_backend() can only land on XLA-CPU when no
            # device answers — with a device present the jit path IS
            # the device, so "xla-cpu" is unreachable (and choosing
            # its stale model entry would dispatch onto the possibly-
            # DOWN device it was meant to avoid).
            from . import batching
            return not batching.device_present()
        return True

    # -- probe ladder ---------------------------------------------------

    def ensure_probed(self, background: bool = True) -> None:
        """Run the boot probe ladder once per process.  Background by
        default: the ladder pays jit compiles (and possibly a native
        rebuild), and serving must not wait on it — the static policy
        covers the gap."""
        if self._probed:
            return
        if not background:
            self.probe_ladder()
            return
        with self._probe_mu:
            if self._probed or (self._probe_thread is not None
                                and self._probe_thread.is_alive()):
                return
            # mtpu-lint: disable=R1 -- one-shot process-wide probe worker; it serves no single request's context
            self._probe_thread = threading.Thread(
                target=self._probe_quietly, daemon=True,
                name="codec-autotune-probe")
            self._probe_thread.start()

    def _probe_quietly(self) -> None:
        try:
            self.probe_ladder()
        except Exception:  # noqa: BLE001 - boot probe must not kill anything
            from ..logger import Logger
            Logger.get().log_once("autotune: probe ladder failed",
                                  "autotune")

    def probe_ladder(self) -> dict[str, dict]:
        """Measure every reachable lane at every size rung with a
        known-answer check; seed the model and (re)compute the plan.
        Returns {lane: {bucket: GiB/s | None}} (None = probe failed)."""
        results: dict[str, dict] = {}
        for lane in BACKENDS:
            # _lane_available also excludes XLA-CPU while a device
            # answers: attempt_backend() can't reach it then — the
            # jit rung measures DEVICE instead.
            if not self._lane_available(lane):
                continue
            results[lane] = {}
            for bucket, B, S in _PROBE_RUNGS:
                bps, err = self._probe_lane(lane, B, S)
                nbytes = B * _PROBE_K * S
                self._record_probe(lane, bucket, nbytes, bps, err)
                results[lane][bucket] = (
                    round(bps / (1 << 30), 6) if bps else None)
            # Seed the top bucket from the largest rung: throughput is
            # flat past the 8MiB knee and a 32MiB probe would pay more
            # wall than the information buys.
            top = results[lane].get("4-16M")
            if top:
                with self._mu:
                    for kern in _CODEC_KERNELS:
                        self._feed_locked(kern, TOP_BUCKET, lane,
                                          top * (1 << 30))
        self._probe_select_lanes()
        self._probe_regen_lanes()
        with self._mu:
            self._last_probe = results
            for kern in KERNELS:
                for bucket in BUCKETS:
                    self._replan_locked(kern, bucket, "probe ladder")
            self._probed = True
        self._flush_announcements()
        return results

    def _record_probe(self, lane: str, bucket: str, nbytes: int,
                      bps: float | None, err: str) -> None:
        from ..logger import Logger
        from ..obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_codec_plan_probes_total",
                     {"lane": lane,
                      "result": "pass" if bps else "fail"})
        if bps:
            with self._mu:
                for kern in _CODEC_KERNELS:
                    # One ladder seeds both codec kernels: encode and
                    # reconstruct run the same GF apply machinery, and
                    # live refinement keys them apart from here on.
                    self._feed_locked(kern, bucket, lane, bps)
            Logger.get().info(
                f"autotune: probe {lane}[{bucket}] "
                f"{bps / (1 << 30):.3f} GiB/s", "autotune",
                lane=lane, bucket=bucket)
        else:
            Logger.get().info(
                f"autotune: probe {lane}[{bucket}] failed ({err})",
                "autotune", lane=lane, bucket=bucket)

    def _probe_select_lanes(self) -> None:
        """Known-answer select-scan probes per size rung: the jit lane
        (device when one answers, xla-cpu otherwise) and the numpy
        host lane — seeding the (select_scan, bucket, lane) model so
        scan dispatch probes-and-picks like RS math does."""
        from .select_kernels import probe_lane
        jit_lane = DEVICE if self._device_visible() else XLA_CPU
        results: dict[str, dict] = {}
        for lane in (jit_lane, HOST):
            results[lane] = {}
            for bucket, _B, _S in _PROBE_RUNGS:
                nbytes = _B * _PROBE_K * _S
                # two float32 columns per probe batch
                rows = max(_SELECT_PROBE_ROWS, nbytes // 8)
                bps, err = probe_lane(lane, rows)
                from ..obs.metrics2 import METRICS2
                from ..logger import Logger
                METRICS2.inc("minio_tpu_v2_codec_plan_probes_total",
                             {"lane": lane,
                              "result": "pass" if bps else "fail"})
                if bps:
                    with self._mu:
                        self._feed_locked(SELECT_SCAN, bucket, lane,
                                          bps)
                    Logger.get().info(
                        f"autotune: probe select/{lane}[{bucket}] "
                        f"{bps / (1 << 30):.3f} GiB/s", "autotune",
                        lane=lane, bucket=bucket)
                else:
                    Logger.get().info(
                        f"autotune: probe select/{lane}[{bucket}] "
                        f"failed ({err})", "autotune", lane=lane,
                        bucket=bucket)
                results[lane][bucket] = (
                    round(bps / (1 << 30), 6) if bps else None)
            top = results[lane].get("4-16M")
            if top:
                with self._mu:
                    self._feed_locked(SELECT_SCAN, TOP_BUCKET, lane,
                                      top * (1 << 30))
        with self._mu:
            self._last_select_probe = results

    def _probe_regen_lanes(self) -> None:
        """Known-answer regenerating-code probes per size rung: the jit
        lane (device when one answers, xla-cpu otherwise) and the numpy
        host lane — seeding the (regen_code, bucket, lane) model so the
        REGEN codec's dispatch is measured, never hardwired.  RS probe
        numbers don't transfer: the regen apply is a (B, ·) stripe
        matmul with B = kd - k(k-1)/2 rows, a different shape family
        from the k-row RS apply."""
        from .rs_regen import probe_lane
        jit_lane = DEVICE if self._device_visible() else XLA_CPU
        results: dict[str, dict] = {}
        for lane in (jit_lane, HOST):
            results[lane] = {}
            for bucket, _B, _S in _PROBE_RUNGS:
                nbytes = _B * _PROBE_K * _S
                # probe geometry is 4+2 (B = 14 stripe rows)
                nstripes = max(4096, nbytes // 14)
                bps, err = probe_lane(lane, nstripes)
                from ..obs.metrics2 import METRICS2
                from ..logger import Logger
                METRICS2.inc("minio_tpu_v2_codec_plan_probes_total",
                             {"lane": lane,
                              "result": "pass" if bps else "fail"})
                if bps:
                    with self._mu:
                        self._feed_locked(REGEN_CODE, bucket, lane,
                                          bps)
                    Logger.get().info(
                        f"autotune: probe regen/{lane}[{bucket}] "
                        f"{bps / (1 << 30):.3f} GiB/s", "autotune",
                        lane=lane, bucket=bucket)
                else:
                    Logger.get().info(
                        f"autotune: probe regen/{lane}[{bucket}] "
                        f"failed ({err})", "autotune", lane=lane,
                        bucket=bucket)
                results[lane][bucket] = (
                    round(bps / (1 << 30), 6) if bps else None)
            top = results[lane].get("4-16M")
            if top:
                with self._mu:
                    self._feed_locked(REGEN_CODE, TOP_BUCKET, lane,
                                      top * (1 << 30))
        with self._mu:
            self._last_regen_probe = results

    @staticmethod
    def _device_visible() -> bool:
        from . import batching
        return batching.device_present()

    def _probe_lane(self, lane: str, B: int,
                    S: int) -> tuple[float | None, str]:
        """One sized known-answer probe on `lane`: (bytes/s, "") or
        (None, cause).  A probe is a REAL dispatch — it consults the
        fault-injection `kernel` hook like kernprof's recovery probes,
        so an active fault plan keeps a lane unmeasured."""
        from .gf256 import gf_mat_vec_apply
        from .rs_matrix import parity_matrix
        k, m = _PROBE_K, _PROBE_M
        rng = np.random.default_rng(B * S)  # deterministic per rung
        data = rng.integers(0, 256, (B, k, S)).astype(np.uint8)
        pm = parity_matrix(k, m)
        want = gf_mat_vec_apply(
            pm, data.transpose(1, 0, 2).reshape(k, B * S))
        try:
            from ..faultinject import FAULTS
            FAULTS.kernel("rs_encode")
            runner = self._lane_runner(lane, pm, data, k, m)
            out = runner()  # warm: jit compile / native build / cache
            wall = min(self._timed(runner) for _ in range(2))
            got = np.asarray(out)
            # Normalize to (m, B, S): the jit lane answers batch-major
            # (B, m, S), the host lanes column-folded (m, B*S).
            if got.shape == (B, m, S):
                got = got.transpose(1, 0, 2)
            got = got.reshape(m, B, S)
            if not (got == want.reshape(m, B, S)).all():
                return None, "known-answer mismatch"
            return (data.nbytes / max(wall, 1e-9)), ""
        except Exception as exc:  # noqa: BLE001 - a probe must not raise
            return None, f"{type(exc).__name__}: {exc}"

    @staticmethod
    def _timed(fn) -> float:
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    def _lane_runner(self, lane: str, pm, data, k: int, m: int):
        """A thunk computing this lane's parity for `data` — raises
        when the lane can't run (native lib missing, device gone)."""
        B, _, S = data.shape
        cols = np.ascontiguousarray(
            data.transpose(1, 0, 2).reshape(k, B * S))
        if lane in (DEVICE, XLA_CPU):
            import jax.numpy as jnp

            from . import rs_tpu
            from .gf256 import gf_matrix_to_bitplane
            bm = jnp.asarray(
                gf_matrix_to_bitplane(pm).astype(np.float32))
            placed = jnp.asarray(data)

            def run_jit():
                out = rs_tpu.gf_apply(bm, placed)
                return np.asarray(out)  # sync: the wall must be real
            return run_jit
        if lane == NATIVE:
            from ..native import rs_apply_native

            def run_native():
                out = rs_apply_native(pm, cols)
                if out is None:
                    raise RuntimeError("native kernel unavailable")
                return out
            return run_native

        from .gf256 import gf_mat_vec_apply

        def run_host():
            return gf_mat_vec_apply(pm, cols)
        return run_host

    # -- planning -------------------------------------------------------

    def _replan_locked(self, kernel: str, bucket: str,
                       cause: str) -> None:
        """Recompute one (kernel, bucket) plan entry from the model
        (caller holds _mu).  Hysteresis: a measured incumbent is only
        unseated by a challenger `hysteresis`x faster with >=
        MIN_SAMPLES samples."""
        from ..obs.kernprof import KERNPROF
        # O(lanes) direct lookups — this runs per DISPATCH via
        # observe(), so no full-model scan (KERNPROF.allow is a
        # lock-free attribute read).
        candidates = []
        for ln in BACKENDS:
            m = self._model.get((kernel, bucket, ln))
            if m is not None and m.samples > 0 \
                    and KERNPROF.allow(ln) \
                    and self._lane_available(ln):
                candidates.append((m.bps, m.samples, ln))
        if not candidates:
            return
        candidates.sort(reverse=True)
        best_bps, best_n, best = candidates[0]
        key = (kernel, bucket)
        incumbent = self._plan.get(key)
        if incumbent == best:
            return
        inc_model = self._model.get((kernel, bucket, incumbent)) \
            if incumbent else None
        inc_healthy = (incumbent is not None
                       and KERNPROF.allow(incumbent)
                       and self._lane_available(incumbent))
        if inc_model is not None and inc_healthy:
            if best_n < self.MIN_SAMPLES:
                return
            if best_bps < inc_model.bps * self.hysteresis:
                return
            why = (f"{cause}: {best} {best_bps / (1 << 30):.3f} "
                   f"GiB/s > {incumbent} "
                   f"{inc_model.bps / (1 << 30):.3f} GiB/s "
                   f"x{self.hysteresis:.2f}")
        else:
            why = (f"{cause}: {best} "
                   f"{best_bps / (1 << 30):.3f} GiB/s"
                   + (f" (incumbent {incumbent} unhealthy)"
                      if incumbent else ""))
        self._plan[key] = best
        self._plan_version += 1
        self._pending.append((kernel, bucket, incumbent, best, why))

    def replan(self, cause: str) -> None:
        """Recompute the whole plan (device census changed, config
        flip, probe re-adoption)."""
        with self._mu:
            for kern in KERNELS:
                for bucket in BUCKETS:
                    self._replan_locked(kern, bucket, cause)
        self._flush_announcements()

    def on_device_census_change(self, old_n: int, new_n: int) -> None:
        """``batching.reprobe_device_present`` saw the device count
        change: the serving mesh was rebuilt; re-probe the jit lane
        and re-plan so dispatch follows the new hardware."""
        cause = f"device census changed ({old_n} -> {new_n} devices)"
        from ..logger import Logger
        Logger.get().info(f"autotune: {cause}; re-planning",
                          "autotune")
        if self._probed:
            # Re-measure only the jit lane (the host lanes didn't
            # change); a full ladder re-run would pay native rebuild
            # checks for nothing.
            lane = DEVICE if self._device_visible() else XLA_CPU
            for bucket, B, S in _PROBE_RUNGS:
                bps, err = self._probe_lane(lane, B, S)
                self._record_probe(lane, bucket, B * _PROBE_K * S,
                                   bps, err)
        self.replan(cause)

    # -- transition fan-out (outside _mu) -------------------------------

    def _flush_announcements(self) -> None:
        with self._announce_mu:
            while True:
                with self._mu:
                    if not self._pending:
                        return
                    item = self._pending.pop(0)
                self._announce(*item)

    def _announce(self, kernel: str, bucket: str, old: str | None,
                  new: str, cause: str) -> None:
        from ..logger import Logger
        from ..obs.metrics2 import METRICS2
        from ..obs.span import current_span
        Logger.get().info(
            f"autotune: plan {kernel}[{bucket}] "
            f"{old or 'unset'} -> {new} ({cause})", "autotune",
            kernel=kernel, bucket=bucket, lane=new)
        METRICS2.set_gauge("minio_tpu_v2_codec_plan_lane",
                           {"kernel": kernel, "bucket": bucket},
                           _LANE_INDEX[new])
        METRICS2.inc("minio_tpu_v2_codec_plan_transitions_total",
                     {"kernel": kernel, "bucket": bucket, "lane": new})
        span = current_span()
        if span is not None:
            span.add_event("codec.plan", kernel=kernel, bucket=bucket,
                           old=old or "", new=new, cause=cause[:256])

    # -- config ---------------------------------------------------------

    def configure(self, enabled: bool, hysteresis: float) -> None:
        """Live-reloadable (config-KV ``codec`` subsystem)."""
        flipped = enabled and not self.enabled
        self.enabled = enabled
        h = float(hysteresis)
        # `not (h >= 1.0)` also floors NaN (a plain max() would let a
        # NaN comparison pick either operand depending on order).
        self.hysteresis = h if h >= 1.0 else 1.0
        if flipped and self._probed:
            self.replan("autotune re-enabled")

    # -- views ----------------------------------------------------------

    def plan_indices(self) -> dict[str, int]:
        """Flat {"kernel/bucket": lane index} — the timeline's
        per-sample codec-plan series (collapse/merge take elementwise
        max, like backend states)."""
        with self._mu:
            return {f"{k}/{b}": _LANE_INDEX[lane]
                    for (k, b), lane in sorted(self._plan.items())}

    def plan_compact(self) -> dict[str, dict[str, str]]:
        """{kernel: {bucket: lane}} — the bench stamp next to
        backend_mix."""
        with self._mu:
            out: dict[str, dict[str, str]] = {}
            for (k, b), lane in sorted(self._plan.items()):
                out.setdefault(k, {})[b] = lane
            return out

    def snapshot(self) -> dict:
        """JSON-ready planner view (admin ``/codec-plan``): the live
        plan, the measured per-lane crossover table, probe results,
        and gauges the operator needs to trust a number."""
        from ..obs.kernprof import KERNPROF
        with self._mu:
            crossover: dict[str, dict[str, dict]] = {}
            for (k, b, ln), m in sorted(self._model.items()):
                crossover.setdefault(k, {}).setdefault(b, {})[ln] = {
                    "gibs": round(m.bps / (1 << 30), 6),
                    "samples": m.samples,
                }
            plan = {f"{k}/{b}": lane
                    for (k, b), lane in sorted(self._plan.items())}
            out = {
                "enabled": self.enabled,
                "probed": self._probed,
                "planVersion": self._plan_version,
                "hysteresis": self.hysteresis,
                "plan": plan,
                "crossover": crossover,
                "lastProbe": self._last_probe,
                "lastSelectProbe": self._last_select_probe,
                "lastRegenProbe": self._last_regen_probe,
            }
        out["backendStates"] = {
            b: KERNPROF.state_of(b) for b in BACKENDS}
        return out

    def reset(self) -> None:
        with self._mu:
            self._model.clear()
            self._plan.clear()
            self._plan_version = 0
            self._probed = False
            self._last_probe = {}
            self._last_select_probe = {}
            self._last_regen_probe = {}
            self._pending.clear()
        self.enabled = True
        self.hysteresis = self.HYSTERESIS


# The process-wide planner every dispatch decision shares.
AUTOTUNE = CodecAutotuner()
