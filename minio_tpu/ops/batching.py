"""Mask-grouped batching: the bridge between byte-oriented serving paths
and the TPU's batch-hungry kernels.

The TPU sits behind a relay with ~80ms fixed dispatch latency, so the
codec must never pay a device round-trip for one small block. Two
coalescing mechanisms fix that (SURVEY §7 hard parts c and f):

- ``reconstruct_blocks``: synchronous mask-grouped coalescing for
  GET-with-loss and heal. Blocks sharing an erasure signature
  ``(available, missing, shard_len)`` collapse into a single
  ``(B, n_used, S)`` `rs_tpu.gf_apply` dispatch — all blocks of a damaged
  object share one mask, so a whole read window or heal part is one
  device call. Below the device threshold the same grouping still pays
  off on the host: the batch folds into the columns of one table-gather
  apply instead of B separate ones.

- ``EncodeCoalescer``: a cross-request window that merges concurrent
  PutObject encodes into one device batch. A lone small PUT falls back
  to the host codec with only the window's latency added; under
  concurrency, many 1MiB single-block PUTs reach the MXU together.

``STATS`` counts every dispatch so tests (and the admin metrics page)
can prove which device actually did the math — the honesty counter the
round-2 verdict demanded.

Reference behavior parity: cmd/erasure-decode.go:214 (per-call
reconstruct), cmd/erasure-healing.go:224 (heal re-encode); the reference
dispatches per block per call on the CPU — coalescing is the TPU-native
redesign, not a port.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from .gf256 import gf_mat_vec_apply
from .rs_matrix import any_decode_matrix

def attempt_backend() -> str:
    """Which kernprof backend a 'device' dispatch actually lands on:
    a real accelerator when one is visible, else the XLA bit-plane
    path jitted on the CPU platform (what a pinned backend="tpu" runs
    when no device answers — the r04/r05 bench distinction)."""
    from ..obs.kernprof import DEVICE, XLA_CPU
    return DEVICE if device_present() else XLA_CPU


def device_dispatch_failed(exc: BaseException) -> None:
    """A device-lane dispatch raised: feed the per-backend health
    state machine (obs/kernprof.py).  This replaces the old
    once-per-process ``_warned_fallback`` warning — every backend
    state TRANSITION logs with its cause, so a recovered relay that
    fails again (or a second distinct failure mode) is never silent,
    while a steadily-down backend doesn't spam."""
    from ..obs.kernprof import KERNPROF
    KERNPROF.dispatch_failed(attempt_backend(), exc)


def _device_allowed(device_fallback: bool = True) -> bool:
    """State-machine gate on the device lane: a DOWN backend is
    skipped (recovery is the probe's job, real traffic stops paying
    the failure latency).  A pinned backend (device_fallback=False)
    bypasses the gate — the operator asked for errors, not silent
    rerouting."""
    if not device_fallback:
        return True
    from ..obs.kernprof import KERNPROF
    return KERNPROF.allow(attempt_backend())


class DispatchStats:
    """Thread-safe counters for codec dispatches (device vs host)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self.tpu_dispatches = 0
            self.tpu_bytes = 0
            self.cpu_dispatches = 0
            self.cpu_bytes = 0
            self.coalesced_requests = 0

    def add(self, device: bool, nbytes: int, requests: int = 1) -> None:
        with self._lock:
            if device:
                self.tpu_dispatches += 1
                self.tpu_bytes += nbytes
            else:
                self.cpu_dispatches += 1
                self.cpu_bytes += nbytes
            if requests > 1:
                self.coalesced_requests += requests

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tpu_dispatches": self.tpu_dispatches,
                "tpu_bytes": self.tpu_bytes,
                "cpu_dispatches": self.cpu_dispatches,
                "cpu_bytes": self.cpu_bytes,
                "coalesced_requests": self.coalesced_requests,
            }


STATS = DispatchStats()

# Bitrot (HighwayHash) dispatch counters — same honesty contract as the
# RS counters above, separate instance so operators can see which half
# of the data plane (coding vs hashing) actually reached the device.
HH_STATS = DispatchStats()


class ReconstructError(ValueError):
    """Not enough survivor shards to rebuild a block."""


# --- multi-device placement ---------------------------------------------------

_serving_mesh = None
_serving_mesh_built = False
_mesh_lock = threading.Lock()
# Bench/test knob: cap the serving mesh at the first n devices (the
# n_devices-aware north-star sweep measures the scaling curve 1..N).
_mesh_n_override: int | None = None


def serving_mesh():
    """The device mesh the SERVING path shards batches over (None on a
    single device). Round-3 verdict weak #3: the mesh machinery existed
    only in the dryrun demo; every engine dispatch committed to device
    0. Now any (B, R, S) batch spreads B over 'blocks' and S over
    'lanes' whenever the dims divide the mesh."""
    global _serving_mesh, _serving_mesh_built
    if not _serving_mesh_built:
        with _mesh_lock:
            if not _serving_mesh_built:
                mesh = None
                try:
                    import jax
                    n = len(jax.devices())
                    want = n if _mesh_n_override is None \
                        else min(_mesh_n_override, n)
                    if n > 1 and want > 1:
                        from ..parallel.mesh import make_mesh
                        mesh = make_mesh(want)
                except Exception:
                    mesh = None
                _serving_mesh = mesh
                _serving_mesh_built = True
    return _serving_mesh


def reset_serving_mesh() -> None:
    """Test hook: rebuild the mesh after device-count changes."""
    global _serving_mesh, _serving_mesh_built
    with _mesh_lock:
        _serving_mesh = None
        _serving_mesh_built = False


def set_mesh_devices(n: int | None) -> None:
    """Cap the serving mesh at the first n devices (None = all) and
    rebuild — the n_devices-aware north-star sweep (bench.py) measures
    the 1..N scaling curve through this."""
    global _mesh_n_override
    _mesh_n_override = n
    reset_serving_mesh()


def device_put_batch(x, affinity: int | None = None):
    """np (B, R, S) -> device array: sharded across the serving mesh
    when an axis divides it, pinned WHOLE to the owning erasure set's
    home device otherwise (parallel/mesh.batch_placement — concurrent
    sets' small dispatches spread across chips instead of all queueing
    on device 0).  Every placement lands in the MESH_AFFINITY census
    so the spread is provable."""
    import jax
    import jax.numpy as jnp
    m = serving_mesh()
    if m is None:
        return jnp.asarray(x)
    from ..parallel.mesh import MESH_AFFINITY, batch_placement
    B, _, S = x.shape
    sh, dev_indices = batch_placement(m, B, S, affinity)
    MESH_AFFINITY.record_dispatch(dev_indices, x.nbytes)
    return jax.device_put(x, sh)


def pinned_device(B: int, S: int, affinity: int | None) -> int | None:
    """Device index a (B, ·, S) batch will be pinned to under the
    current mesh placement, or None when it shards/replicates."""
    m = serving_mesh()
    if m is None or affinity is None:
        return None
    from ..parallel.mesh import batch_placement
    _, dev_indices = batch_placement(m, B, S, affinity)
    return dev_indices[0] if len(dev_indices) == 1 else None


def batch_home_device(x, affinity: int | None) -> int | None:
    """pinned_device for an actual (B, R, S) array — the GF matrix
    must be placed WHERE the batch lives (a mesh-replicated matrix
    against a single-device operand is a jit placement error)."""
    return pinned_device(x.shape[0], x.shape[-1], affinity)


def device_put_replicated(x):
    """Small operands (GF matrices) replicate to every mesh device."""
    import jax
    import jax.numpy as jnp
    m = serving_mesh()
    if m is None:
        return jnp.asarray(x)
    from ..parallel.mesh import replicated
    return jax.device_put(x, replicated(m))


def _device_reconstruct(stack: np.ndarray, k: int, m: int,
                        avail: tuple[int, ...], missing: tuple[int, ...],
                        affinity: int | None = None) -> np.ndarray:
    from . import rs_tpu
    from ..obs.kernel_stats import KERNEL, RS_DECODE, timed
    bm = rs_tpu._placed_any_decode(k, m, avail, missing, serving_mesh(),
                                   batch_home_device(stack, affinity))
    with timed() as t:
        out = np.asarray(rs_tpu.gf_apply(
            bm, device_put_batch(stack, affinity)))
    KERNEL.record(RS_DECODE, True, stack.nbytes, t.s,
                  blocks=stack.shape[0], backend=attempt_backend())
    return out


def host_apply_tagged(mat: np.ndarray, cols: np.ndarray,
                      lane: str | None = None,
                      ) -> tuple[np.ndarray, str]:
    """host_apply plus which backend actually ran (kernprof NATIVE
    when the C++ kernel answered, HOST for the numpy table-gather) —
    the per-dispatch profile must not lump them: they differ ~10x.
    ``lane`` (from the autotuner plan) pins pure-numpy when the
    measured model says so; default is native-first with numpy
    fallback, exactly as before."""
    from ..obs.kernprof import HOST, NATIVE
    if lane != HOST:
        from ..native import rs_apply_native
        out = rs_apply_native(mat, cols)
        if out is not None:
            return out, NATIVE
    return gf_mat_vec_apply(mat, cols), HOST


def host_apply(mat: np.ndarray, cols: np.ndarray) -> np.ndarray:
    """(r, k) GF matrix x (k, N) bytes on the host: the C++ nibble-
    shuffle kernel (native/rs.cc) when built, numpy table-gather
    otherwise. Byte-identical either way (tests/test_rs_native.py)."""
    return host_apply_tagged(mat, cols)[0]


def _host_reconstruct(stack: np.ndarray, mat: np.ndarray,
                      lane: str | None = None) -> np.ndarray:
    """(B, n_used, S) -> (B, n_missing, S) via one folded apply.

    RS is byte-column-independent, so the batch dim folds into the
    columns: one (n_used, B*S) apply instead of B separate ones.
    """
    from ..obs.kernel_stats import KERNEL, RS_DECODE, timed
    B, n_used, S = stack.shape
    with timed() as t:
        cols = stack.transpose(1, 0, 2).reshape(n_used, B * S)
        out, backend = host_apply_tagged(mat, cols, lane)
        out = out.reshape(mat.shape[0], B, S).transpose(1, 0, 2)
    KERNEL.record(RS_DECODE, False, stack.nbytes, t.s, blocks=B,
                  backend=backend)
    return out


def reconstruct_blocks(blocks: list[list[np.ndarray | None]], k: int,
                       m: int, *, want_all: bool, use_device,
                       device_fallback: bool = True,
                       affinity: int | None = None,
                       ) -> list[list[np.ndarray | None]]:
    """Rebuild missing shards across many blocks, one dispatch per mask.

    blocks: each entry is a k+m shard list (None = missing) for one
    stripe block; shard lengths may differ between blocks (tail blocks).
    want_all: rebuild parity too (heal) vs data only (GET).
    use_device: callable(coalesced_nbytes) -> bool.
    device_fallback: on device failure, warn loudly and use the host
    (False when the backend is pinned 'tpu': errors then propagate).

    Returns new per-block lists; input arrays are never mutated.
    Byte-identical to per-block rs_cpu reconstruct (tests/test_batching).
    """
    n = k + m
    out = [list(b) for b in blocks]
    groups: dict[tuple, list[int]] = {}
    for bi, shards in enumerate(blocks):
        if len(shards) != n:
            raise ValueError(f"block {bi}: expected {n} shard slots")
        avail = tuple(i for i, s in enumerate(shards) if s is not None)
        lim = n if want_all else k
        missing = tuple(i for i in range(lim) if shards[i] is None)
        if not missing:
            continue
        if len(avail) < k:
            raise ReconstructError(
                f"block {bi}: only {len(avail)}/{k} shards available")
        S = int(np.asarray(shards[avail[0]]).shape[-1])
        groups.setdefault((avail, missing, S), []).append(bi)

    # Priority lanes (qos/scheduler.py): a heal/crawler reconstruct
    # defers its dispatch while foreground GET/PUT work is busy; aging
    # promotes it after a bounded wait so background never starves.
    from ..qos import scheduler as qos_sched
    lane = qos_sched.current_lane()
    for (avail, missing, S), idxs in groups.items():
        mat, used = any_decode_matrix(k, m, avail, missing)
        # One flat stack + reshape: the nested per-block stack built 64
        # intermediates and copied every byte twice (~2x the assembly
        # cost of a degraded read window).
        stack = np.stack([
            np.asarray(blocks[bi][j], dtype=np.uint8)
            for bi in idxs for j in used]).reshape(
                len(idxs), len(used), S)
        with qos_sched.GATE.dispatch(lane):
            if use_device(stack.nbytes) and \
                    _device_allowed(device_fallback):
                try:
                    # Kernel-dispatch fault hook (minio_tpu/faultinject):
                    # an injected failure lands inside this try so the
                    # host-fallback lane below is what gets exercised.
                    from ..faultinject import FAULTS
                    FAULTS.kernel("rs_decode")
                    rebuilt = _device_reconstruct(stack, k, m, avail,
                                                  missing, affinity)
                    STATS.add(True, stack.nbytes, len(idxs))
                except Exception as exc:
                    if not device_fallback:
                        raise
                    device_dispatch_failed(exc)
                    rebuilt = _host_reconstruct(stack, mat)
                    STATS.add(False, stack.nbytes, len(idxs))
            else:
                from .autotune import AUTOTUNE
                from .autotune import RS_DECODE as _RSD
                rebuilt = _host_reconstruct(
                    stack, mat, lane=AUTOTUNE.host_lane(_RSD,
                                                        stack.nbytes))
                STATS.add(False, stack.nbytes, len(idxs))
        for bn, bi in enumerate(idxs):
            for mi, j in enumerate(missing):
                out[bi][j] = rebuilt[bn, mi]
    return out


# --- cross-request encode coalescing -----------------------------------------


def host_encode(blocks: np.ndarray, k: int, m: int,
                lane: str | None = None) -> np.ndarray:
    """(B, k, S) -> (B, k+m, S) on the host, counted in STATS.

    The batch folds into the columns of ONE matrix apply (native C++
    when built), matching the reference's per-block encode bytes
    exactly (ref cmd/erasure-coding.go:70)."""
    from .rs_matrix import parity_matrix
    from ..obs.kernel_stats import KERNEL, RS_ENCODE, timed
    B, _, S = blocks.shape
    with timed() as t:
        out = np.zeros((B, k + m, S), dtype=np.uint8)
        out[:, :k] = blocks
        cols = blocks.transpose(1, 0, 2).reshape(k, B * S)
        parity, backend = host_apply_tagged(parity_matrix(k, m), cols,
                                            lane)
        out[:, k:] = parity.reshape(m, B, S).transpose(1, 0, 2)
    STATS.add(False, blocks.nbytes)
    KERNEL.record(RS_ENCODE, False, blocks.nbytes, t.s, blocks=B,
                  backend=backend)
    return out


def host_encode_shardmajor(blocks: np.ndarray, k: int, m: int,
                           lane: str | None = None) -> np.ndarray:
    """(B, k, S) -> SHARD-MAJOR (k+m, B, S) contiguous, on the host.

    Same bytes as host_encode transposed, but two full-batch copies
    cheaper: the matrix apply reads the output buffer's own data rows
    as its (k, B*S) columns view (zero-copy), and the caller's bitrot
    framing wants shard-major anyway (engine._encode_batch)."""
    from .rs_matrix import parity_matrix
    from ..obs.kernel_stats import KERNEL, RS_ENCODE, timed
    B, _, S = blocks.shape
    with timed() as t:
        out = np.empty((k + m, B, S), dtype=np.uint8)
        out[:k] = blocks.transpose(1, 0, 2)
        parity, backend = host_apply_tagged(parity_matrix(k, m),
                                            out[:k].reshape(k, B * S),
                                            lane)
        out[k:] = parity.reshape(m, B, S)
    STATS.add(False, blocks.nbytes)
    KERNEL.record(RS_ENCODE, False, blocks.nbytes, t.s, blocks=B,
                  backend=backend)
    return out


@dataclass
class _EncodeRequest:
    blocks: np.ndarray  # (B, k, S) uint8 data shards
    k: int
    m: int
    # Home device of the submitting erasure set (parallel/mesh.py
    # DeviceAffinity): a coalesced window whose requests span >= 2
    # home devices fans out as parallel per-device dispatches.
    affinity: int | None = None
    done: threading.Event = field(default_factory=threading.Event)
    result: np.ndarray | None = None
    declined: bool = False
    # Enqueue stamp: the coalescer window wait this request paid,
    # reported separately from device-execute wall (obs/kernprof.py
    # queue-wait vs execute split).
    t_enq: float = field(default_factory=time.perf_counter)


class EncodeCoalescer:
    """Cross-request PUT-encode window.

    Handler threads submit ``(B, k, S)`` pre-split batches; a dispatcher
    thread gathers everything arriving within ``window_s`` of the first
    request, groups by ``(k, m, S)``, and issues one device dispatch per
    group when the coalesced bytes clear the policy threshold. Groups
    below it are DECLINED back to their callers, which host-encode in
    their own threads — the dispatcher thread never serializes host
    work, it only fronts the (inherently serial) device. Device failures
    also decline, so callers never block on a broken device.
    """

    def __init__(self, use_device, window_s: float = 0.003):
        self._use_device = use_device
        self.window_s = window_s
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._stopped = False

    def encode(self, blocks: np.ndarray, k: int, m: int,
               affinity: int | None = None) -> np.ndarray:
        """Blocking encode: (B, k, S) data -> (B, k+m, S) all shards.

        Priority lanes (qos/scheduler.py): a background caller (heal,
        crawler-driven rewrite) yields the coalescing window — it
        defers submission while foreground PUT encodes are busy, so the
        window batches client traffic, not repair traffic; aging
        promotes it after a bounded wait."""
        from ..qos import scheduler as qos_sched
        with qos_sched.GATE.dispatch(qos_sched.current_lane()):
            req = _EncodeRequest(
                np.ascontiguousarray(blocks, dtype=np.uint8), k, m,
                affinity)
            self._ensure_thread()
            self._q.put(req)
            # Liveness-checked wait: if the dispatcher dies (or a
            # stop() race eats the queue), fall back to host encode
            # rather than hanging the PUT handler forever.
            while not req.done.wait(0.25):
                t = self._thread
                if t is None or not t.is_alive():
                    req.declined = True
                    break
            if req.declined or req.result is None:
                return host_encode(req.blocks, k, m)
            return req.result

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stopped = False
                # mtpu-lint: disable=R1 -- coalescer daemon serves MANY requests; lane/deadline are read per item at enqueue
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="encode-coalescer")
                self._thread.start()

    def stop(self) -> None:
        self._stopped = True
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- dispatcher ----------------------------------------------------

    def _run(self) -> None:
        while not self._stopped:
            req = self._q.get()
            if req is None:
                break
            batch = [req]
            # Fast path: a lone sub-threshold request has nothing to
            # coalesce with — decline immediately instead of taxing the
            # PUT with the full window latency (round-3 verdict weak #6).
            # A concurrent burst still coalesces: the queue is non-empty
            # when the next request is already waiting.
            if self._q.empty() and not (
                    self._use_device(req.blocks.nbytes)
                    and _device_allowed()):
                self._dispatch(batch)
                continue
            deadline = time.monotonic() + self.window_s
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=left)
                except queue.Empty:
                    break
                if nxt is None:
                    self._stopped = True
                    break
                batch.append(nxt)
            self._dispatch(batch)

    def _dispatch(self, batch: list[_EncodeRequest]) -> None:
        from ..obs.kernprof import KERNPROF
        from ..obs.kernel_stats import RS_ENCODE as _RS_ENC
        now = time.perf_counter()
        for r in batch:
            # Window wait, whatever the outcome: a declined request
            # still paid it on top of its own host encode.
            KERNPROF.record_queue_wait(_RS_ENC,
                                       (now - r.t_enq) * 1e3)
        groups: dict[tuple, list[_EncodeRequest]] = {}
        for r in batch:
            key = (r.k, r.m, r.blocks.shape[-1])
            groups.setdefault(key, []).append(r)
        for (k, m, S), reqs in groups.items():
            total = sum(r.blocks.nbytes for r in reqs)
            if not self._use_device(total) or \
                    not KERNPROF.allow(attempt_backend()):
                for r in reqs:
                    r.declined = True
                    r.done.set()
                continue
            try:
                from . import rs_tpu
                # Kernel-dispatch fault hook (minio_tpu/faultinject):
                # raising here declines the batch back to the callers'
                # host-encode lane — the failover under test.
                from ..faultinject import FAULTS
                FAULTS.kernel("rs_encode")
                by_dev = self._fanout_split(reqs)
                if by_dev is not None:
                    self._fanout_encode(by_dev, k, m)
                else:
                    stack = (reqs[0].blocks if len(reqs) == 1 else
                             np.concatenate([r.blocks for r in reqs],
                                            axis=0))
                    encoded = rs_tpu.encode_batch(
                        stack, k, m, affinity=reqs[0].affinity)
                    off = 0
                    for r in reqs:
                        B = r.blocks.shape[0]
                        r.result = encoded[off:off + B]
                        off += B
                STATS.add(True, total, len(reqs))
                if len(reqs) > 1:
                    # rs_tpu.encode_batch counted the dispatch itself;
                    # the coalescing win (requests merged per window)
                    # is only visible here.
                    from ..obs.kernel_stats import KERNEL, RS_ENCODE
                    KERNEL.record_coalesced(RS_ENCODE, len(reqs))
            except BaseException as exc:
                device_dispatch_failed(exc)
                for r in reqs:
                    r.declined = True
            finally:
                for r in reqs:
                    r.done.set()

    @staticmethod
    def _fanout_split(reqs: list[_EncodeRequest],
                      ) -> dict[int, list[_EncodeRequest]] | None:
        """Group a coalesced window's requests by home device.

        >= 2 distinct home devices on a live serving mesh, AND every
        sub-batch actually PINS to its home device -> the window fans
        out as parallel per-device dispatches (one encode per chip,
        request boundaries split the batch cleanly by construction).
        A sub-batch an axis of which divides the mesh would shard
        across ALL chips instead — fanning those out turns one
        combined mesh dispatch into N contending ones, so the split
        is declined.  None = no clean split: single request, shared
        or absent affinity, no mesh, or mesh-divisible sub-batches —
        the caller falls back to one dispatch, mesh-sharded by
        device_put_batch when B divides."""
        if len(reqs) < 2 or serving_mesh() is None:
            return None
        from ..parallel.mesh import MESH_AFFINITY
        n_dev = MESH_AFFINITY.n_devices()
        by: dict[int, list[_EncodeRequest]] = {}
        for r in reqs:
            if r.affinity is None:
                return None
            # Group by EFFECTIVE device: after a device-count shrink,
            # two sets' stale raw indices can alias (mod n) onto one
            # chip — "fanning out" those as separate dispatches would
            # serialize them on the same device while the metric
            # claimed a spread.
            by.setdefault(r.affinity % max(1, n_dev), []).append(r)
        if len(by) < 2:
            return None
        for dev, sub in by.items():
            B = sum(r.blocks.shape[0] for r in sub)
            S = sub[0].blocks.shape[-1]
            if pinned_device(B, S, dev) is None:
                return None
        return by

    @staticmethod
    def _fanout_encode(by_dev: dict[int, list[_EncodeRequest]],
                       k: int, m: int) -> None:
        """Parallel per-device encode of a fanned-out window; each
        request's result lands byte-identical to the single-dispatch
        path (encode is per-block independent — proven by the
        8-virtual-device merge tests).  Any sub-dispatch failure
        propagates so the whole window declines to host encode."""
        from . import rs_tpu
        from ..parallel.quorum import parallel_map

        def enc(dev: int, sub: list[_EncodeRequest]) -> None:
            stack = (sub[0].blocks if len(sub) == 1 else
                     np.concatenate([r.blocks for r in sub], axis=0))
            encoded = rs_tpu.encode_batch(stack, k, m, affinity=dev)
            off = 0
            for r in sub:
                B = r.blocks.shape[0]
                r.result = encoded[off:off + B]
                off += B

        subs = sorted(by_dev.items())
        _, errs = parallel_map(
            [lambda d=dev, s=sub: enc(d, s) for dev, sub in subs])
        for e in errs:
            if e is not None:
                raise e
        from ..obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_codec_plan_fanout_total",
                     {"devices": str(len(subs))})


_global_coalescer: EncodeCoalescer | None = None
_global_lock = threading.Lock()


def default_device_policy(nbytes: int) -> bool:
    """Jit-lane policy for the shared coalescer: the MEASURED plan
    (ops/autotune.py) — static device-first fallback until the probe
    ladder has run.  The hardwired TPU_MIN_BYTES comparison that used
    to live here is gone (mtpu-lint R9 keeps it gone)."""
    from .autotune import AUTOTUNE, RS_ENCODE
    return AUTOTUNE.use_jit_lane(RS_ENCODE, nbytes)


_device_present: bool | None = None
_device_count: int | None = None


def device_present() -> bool:
    global _device_present, _device_count
    if _device_present is None:
        try:
            import jax
            devs = jax.devices()
            _device_present = any(d.platform != "cpu" for d in devs)
            _device_count = len(devs)
        except Exception:
            _device_present = False
            _device_count = 1
    return _device_present


def reprobe_device_present() -> bool:
    """Drop the cached device census and re-ask jax — the kernprof
    DEVICE recovery probe's entry point, so a relay that bounced back
    mid-process is re-adopted without a restart.  A relay that comes
    back with a DIFFERENT device count must not keep dispatching over
    the stale mesh: the serving mesh is rebuilt and the autotuner
    re-probes + re-plans on a census change."""
    global _device_present
    old_count = _device_count
    _device_present = None
    present = device_present()
    if old_count is not None and _device_count != old_count:
        reset_serving_mesh()
        from .autotune import AUTOTUNE
        AUTOTUNE.on_device_census_change(old_count,
                                         _device_count or 1)
    return present


def get_coalescer() -> EncodeCoalescer:
    """Process-wide coalescer shared by every codec instance."""
    global _global_coalescer
    with _global_lock:
        if _global_coalescer is None:
            _global_coalescer = EncodeCoalescer(default_device_policy)
        return _global_coalescer
