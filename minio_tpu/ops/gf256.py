"""GF(2^8) arithmetic (host side, numpy).

Field: GF(2^8) with the reducing polynomial x^8 + x^4 + x^3 + x^2 + 1
(0x11D), generator 2 — the same field the reference's codec dependency
(klauspost/reedsolomon galois tables; see /root/reference/go.mod:41 and
cmd/erasure-coding.go:23) uses, so encoded shards are byte-identical.

Everything here is table-driven numpy for host-side matrix construction and
the golden CPU reference codec. The TPU kernels (rs_tpu.py) do not use these
tables at runtime — they lower GF(2^8) linear maps to GF(2) bit-plane
matmuls — but their matrices are built from this module.
"""

from __future__ import annotations

import numpy as np

FIELD_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(255, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= FIELD_POLY
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()

# Full 256x256 multiplication table: MUL_TABLE[a, b] = a*b in GF(2^8).
# 64 KiB; the workhorse for the vectorized CPU reference encoder.
_a = np.arange(256)
_la = LOG_TABLE[_a][:, None]
_lb = LOG_TABLE[_a][None, :]
MUL_TABLE = EXP_TABLE[(_la + _lb) % 255].copy()
MUL_TABLE[0, :] = 0
MUL_TABLE[:, 0] = 0
del _a, _la, _lb


def gf_mul(a: int, b: int) -> int:
    """Multiply two field elements."""
    return int(MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    """Divide a by b. b must be nonzero."""
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % 255])


def gf_inv(a: int) -> int:
    """Multiplicative inverse."""
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(EXP_TABLE[(255 - LOG_TABLE[a]) % 255])


def gf_exp(a: int, n: int) -> int:
    """a raised to the n-th power (klauspost galExp semantics).

    galExp(a, 0) == 1 for any a, galExp(0, n) == 0 for n > 0 — this exact
    convention determines the Vandermonde matrix and therefore shard bytes.
    """
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % 255])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix multiply over GF(2^8). a: (r, n) uint8, b: (n, c) uint8."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    # products[i, k, j] = a[i, k] * b[k, j]; XOR-reduce over k.
    prods = MUL_TABLE[a[:, :, None], b[None, :, :]]
    return np.bitwise_xor.reduce(prods, axis=1)


def gf_mat_vec_apply(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Apply (r, k) GF matrix to (k, n) byte rows -> (r, n).

    This is the CPU reference hot loop: out[i] = XOR_j mat[i,j] * data[j,:],
    each scalar-vector product a table gather.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    out = np.zeros((mat.shape[0], data.shape[1]), dtype=np.uint8)
    for i in range(mat.shape[0]):
        acc = out[i]
        for j in range(mat.shape[1]):
            c = mat[i, j]
            if c == 0:
                continue
            acc ^= MUL_TABLE[c][data[j]]
        out[i] = acc
    return out


def gf_mat_invert(m: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(2^8) via Gauss-Jordan.

    Raises ValueError if singular. The inverse is unique, so any correct
    elimination order yields the same bytes as the reference's.
    """
    m = np.array(m, dtype=np.uint8)
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError("matrix must be square")
    aug = np.concatenate([m, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        # Find pivot.
        pivot = -1
        for r in range(col, n):
            if aug[r, col] != 0:
                pivot = r
                break
        if pivot < 0:
            raise ValueError("singular matrix over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        # Scale pivot row to 1.
        inv = gf_inv(int(aug[col, col]))
        aug[col] = MUL_TABLE[inv][aug[col]]
        # Eliminate all other rows.
        for r in range(n):
            if r != col and aug[r, col] != 0:
                aug[r] ^= MUL_TABLE[int(aug[r, col])][aug[col]]
    return aug[:, n:].copy()


# --- GF(2) bit-plane lowering -------------------------------------------------
#
# Multiplication by a constant c in GF(2^8) is linear over GF(2): there is an
# 8x8 0/1 matrix M_c with y_bits = M_c @ x_bits (mod 2). Column a of M_c is
# the bit pattern of c * 2^a. A whole (r, k) GF(2^8) matrix therefore lowers
# to an (8r, 8k) GF(2) matrix, and applying it to byte streams becomes a
# plain integer matmul followed by mod-2 — which is exactly what the TPU MXU
# is good at. This is the core idea of the TPU-native codec.


def gf_matrix_to_bitplane(mat: np.ndarray) -> np.ndarray:
    """Lower an (r, k) GF(2^8) matrix to its (8r, 8k) GF(2) bit matrix.

    Layout: output bit row i*8+b is bit b (LSB-first) of output byte i;
    input bit column j*8+a is bit a of input byte j.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    r, k = mat.shape
    out = np.zeros((8 * r, 8 * k), dtype=np.uint8)
    shifts = np.arange(8)
    for i in range(r):
        for j in range(k):
            c = int(mat[i, j])
            if c == 0:
                continue
            # prods[a] = c * 2^a in GF(2^8)
            prods = MUL_TABLE[c][np.left_shift(1, shifts)]
            # block[b, a] = bit b of prods[a]
            block = (prods[None, :] >> shifts[:, None]) & 1
            out[i * 8:(i + 1) * 8, j * 8:(j + 1) * 8] = block
    return out
