"""HighwayHash-256 — the default bitrot checksum algorithm.

The reference hashes every shard sub-block with HighwayHash-256 keyed by a
magic 256-bit key (ref cmd/bitrot.go:31,35-46; minio/highwayhash go.mod:48).
Checksums must be byte-identical, so this module implements the HighwayHash
algorithm (SipHash-style 4x64-bit lane mixer with 32x32->64 multiplies,
zipper-merge byte shuffles, and mod-(2^61-like) finalization) from the
published specification.

Self-verification: the reference documents its magic key as "HH-256 hash of
the first 100 decimals of pi as utf-8 string with a zero key" — that is a
golden test vector, checked in tests/test_hh256.py and asserted at import
via MAGIC_KEY_SELF_TEST.

This file is the *reference* implementation (python ints — slow). Bulk
hashing uses hh256_numpy (vectorized across independent chunks) and the
TPU path in later kernels.
"""

from __future__ import annotations

import struct

M64 = (1 << 64) - 1
M32 = 0xFFFFFFFF

_INIT0 = (0xDBE6D5D5FE4CCE2F, 0xA4093822299F31D0,
          0x13198A2E03707344, 0x243F6A8885A308D3)
_INIT1 = (0x3BD39E10CB0EF593, 0xC0ACF169B5F18A8C,
          0xBE5466CF34E90C6C, 0x452821E638D01377)

# ref cmd/bitrot.go:31 — HH-256 of the first 100 decimals of pi, zero key.
MAGIC_KEY = bytes.fromhex(
    "4be734fa8e238acd263e83e6bb968552040f935da39f441497e09d1322de36a0")

PI_100_DECIMALS = (
    "1415926535897932384626433832795028841971"
    "6939937510582097494459230781640628620899"
    "86280348253421170679")


def _rot32_halves(x: int, count: int) -> int:
    """Rotate each 32-bit half of x left by count."""
    lo = x & M32
    hi = (x >> 32) & M32
    lo = ((lo << count) | (lo >> ((32 - count) & 31))) & M32 if count else lo
    hi = ((hi << count) | (hi >> ((32 - count) & 31))) & M32 if count else hi
    return (hi << 32) | lo


def _swap32(x: int) -> int:
    return ((x & M32) << 32) | (x >> 32)


class HighwayHash256:
    """Streaming HighwayHash-256 (hashlib-like: update()/digest())."""

    digest_size = 32
    block_size = 32

    def __init__(self, key: bytes = MAGIC_KEY):
        if len(key) != 32:
            raise ValueError("HighwayHash key must be 32 bytes")
        self._key = struct.unpack("<4Q", key)
        self._buf = b""
        self._reset()

    def _reset(self) -> None:
        key = self._key
        self.mul0 = list(_INIT0)
        self.mul1 = list(_INIT1)
        self.v0 = [_INIT0[i] ^ key[i] for i in range(4)]
        self.v1 = [_INIT1[i] ^ _swap32(key[i]) for i in range(4)]
        self._buf = b""

    def reset(self) -> None:
        self._reset()

    def _zipper_merge_and_add(self, v1: int, v0: int, add: list[int],
                              i1: int, i0: int) -> None:
        add[i0] = (add[i0] + (
            (((v0 & 0xFF000000) | (v1 & 0xFF00000000)) >> 24) |
            (((v0 & 0xFF0000000000) | (v1 & 0xFF000000000000)) >> 16) |
            (v0 & 0xFF0000) | ((v0 & 0xFF00) << 32) |
            ((v1 & 0xFF00000000000000) >> 8) | ((v0 << 56) & M64)
        )) & M64
        add[i1] = (add[i1] + (
            (((v1 & 0xFF000000) | (v0 & 0xFF00000000)) >> 24) |
            (v1 & 0xFF0000) | ((v1 & 0xFF0000000000) >> 16) |
            ((v1 & 0xFF00) << 24) | ((v0 & 0xFF000000000000) >> 8) |
            ((v1 & 0xFF) << 48) | (v0 & 0xFF00000000000000)
        )) & M64

    def _update_lanes(self, lanes: tuple[int, int, int, int]) -> None:
        v0, v1, mul0, mul1 = self.v0, self.v1, self.mul0, self.mul1
        for i in range(4):
            v1[i] = (v1[i] + mul0[i] + lanes[i]) & M64
            mul0[i] ^= ((v1[i] & M32) * (v0[i] >> 32)) & M64
            v0[i] = (v0[i] + mul1[i]) & M64
            mul1[i] ^= ((v0[i] & M32) * (v1[i] >> 32)) & M64
        self._zipper_merge_and_add(v1[1], v1[0], v0, 1, 0)
        self._zipper_merge_and_add(v1[3], v1[2], v0, 3, 2)
        self._zipper_merge_and_add(v0[1], v0[0], v1, 1, 0)
        self._zipper_merge_and_add(v0[3], v0[2], v1, 3, 2)

    def _update_packet(self, packet: bytes) -> None:
        self._update_lanes(struct.unpack("<4Q", packet))

    def update(self, data: bytes) -> "HighwayHash256":
        buf = self._buf + bytes(data)
        n = len(buf) - (len(buf) % 32)
        for off in range(0, n, 32):
            self._update_packet(buf[off:off + 32])
        self._buf = buf[n:]
        return self

    def _update_remainder(self, bytes_: bytes) -> None:
        size_mod32 = len(bytes_)
        size_mod4 = size_mod32 & 3
        remainder_off = size_mod32 & ~3
        packet = bytearray(32)
        for i in range(4):
            self.v0[i] = (self.v0[i] +
                          ((size_mod32 << 32) + size_mod32)) & M64
        for i in range(4):
            self.v1[i] = _rot32_halves(self.v1[i], size_mod32 & 31)
        packet[:remainder_off] = bytes_[:remainder_off]
        if size_mod32 & 16:
            for i in range(4):
                packet[28 + i] = bytes_[remainder_off + i + size_mod4 - 4]
        elif size_mod4:
            packet[16 + 0] = bytes_[remainder_off]
            packet[16 + 1] = bytes_[remainder_off + (size_mod4 >> 1)]
            packet[16 + 2] = bytes_[remainder_off + size_mod4 - 1]
        self._update_packet(bytes(packet))

    def _permute_and_update(self) -> None:
        v0 = self.v0
        self._update_lanes((_swap32(v0[2]), _swap32(v0[3]),
                            _swap32(v0[0]), _swap32(v0[1])))

    @staticmethod
    def _modular_reduction(a3u: int, a2: int, a1: int, a0: int,
                           ) -> tuple[int, int]:
        """Returns (m1, m0)."""
        a3 = a3u & 0x3FFFFFFFFFFFFFFF
        m1 = a1 ^ (((a3 << 1) | (a2 >> 63)) & M64) ^ (((a3 << 2) |
                                                       (a2 >> 62)) & M64)
        m0 = a0 ^ ((a2 << 1) & M64) ^ ((a2 << 2) & M64)
        return m1, m0

    def digest(self) -> bytes:
        # Work on a copy so digest() is idempotent (hash.Hash Sum contract).
        st = HighwayHash256.__new__(HighwayHash256)
        st.v0, st.v1 = list(self.v0), list(self.v1)
        st.mul0, st.mul1 = list(self.mul0), list(self.mul1)
        st._buf = b""
        if self._buf:
            st._update_remainder(self._buf)
        for _ in range(10):
            st._permute_and_update()
        h1, h0 = self._modular_reduction(
            (st.v1[1] + st.mul1[1]) & M64, (st.v1[0] + st.mul1[0]) & M64,
            (st.v0[1] + st.mul0[1]) & M64, (st.v0[0] + st.mul0[0]) & M64)
        h3, h2 = self._modular_reduction(
            (st.v1[3] + st.mul1[3]) & M64, (st.v1[2] + st.mul1[2]) & M64,
            (st.v0[3] + st.mul0[3]) & M64, (st.v0[2] + st.mul0[2]) & M64)
        return struct.pack("<4Q", h0, h1, h2, h3)

    def hexdigest(self) -> str:
        return self.digest().hex()


def hh256(data: bytes, key: bytes = MAGIC_KEY) -> bytes:
    """One-shot HighwayHash-256."""
    h = HighwayHash256(key)
    h.update(data)
    return h.digest()


def _self_test() -> bool:
    return hh256(PI_100_DECIMALS.encode(), b"\x00" * 32) == MAGIC_KEY


MAGIC_KEY_SELF_TEST = _self_test()
assert MAGIC_KEY_SELF_TEST, (
    "HighwayHash-256 implementation no longer reproduces the reference "
    "magic bitrot key (cmd/bitrot.go:31) — bitrot checksums would be wrong")
