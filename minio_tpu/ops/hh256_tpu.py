"""TPU HighwayHash-256: the bitrot checksum as a device kernel.

The reference hashes every shard sub-block with HighwayHash-256 on the
CPU (ref cmd/bitrot-streaming.go:46,115; cmd/bitrot.go:35-46). Here the
hash runs on the TPU, batched across independent sub-blocks — the
TPU-native redesign is *batch* parallelism (one chunk per batch row, the
packet loop sequential in a `lax.fori_loop`), because the hash itself is
a serial chain per chunk.

TPU-first representation: HighwayHash state is 4 lanes x 64-bit x 4
vectors (v0, v1, mul0, mul1). TPUs have no fast u64, so every 64-bit
lane is a (lo, hi) pair of uint32 arrays of shape (B, 4) — B independent
chunks hashed in lockstep on the VPU. All 64-bit ops (wrapping add, xor,
32x32->64 multiply, constant shifts, byte shuffles) are emulated with
exact u32 arithmetic, so digests are byte-identical to ops/hh256.py
(asserted in tests/test_hh256_tpu.py against the magic-key vector and
random chunk patterns).

Chunks of ANY equal length hash on device: full 32-byte packets run in
the fori_loop, and the remainder step runs in-kernel too — its
irregular byte-packing depends only on len % 32, which is constant
across the batch (shard sub-blocks are equal-sized; ref
cmd/erasure-coding.go:115 ShardSize), so the remainder packet is
pre-packed on the host with static layout. Only the ragged FINAL
sub-block of a stream differs per stream; it hashes on the host.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .hh256 import _INIT0, _INIT1, MAGIC_KEY

_M16 = 0xFFFF


def _swap32_int(x: int) -> int:
    return ((x & 0xFFFFFFFF) << 32) | (x >> 32)


@lru_cache(maxsize=8)
def _init_state_np(key: bytes) -> tuple[np.ndarray, ...]:
    """(v0lo, v0hi, v1lo, v1hi, mul0lo, mul0hi, mul1lo, mul1hi), each
    (4,) uint32 — the per-lane init vectors for this key."""
    import struct
    kw = struct.unpack("<4Q", key)
    v0 = [_INIT0[i] ^ kw[i] for i in range(4)]
    v1 = [_INIT1[i] ^ _swap32_int(kw[i]) for i in range(4)]
    mul0, mul1 = list(_INIT0), list(_INIT1)

    def split(vals):
        lo = np.array([v & 0xFFFFFFFF for v in vals], dtype=np.uint32)
        hi = np.array([v >> 32 for v in vals], dtype=np.uint32)
        return lo, hi

    return (*split(v0), *split(v1), *split(mul0), *split(mul1))


# --- u64-as-u32-pair primitives (all exact, wrapping) ------------------------


def _add64(alo, ahi, blo, bhi):
    rlo = alo + blo
    carry = (rlo < alo).astype(jnp.uint32)
    return rlo, ahi + bhi + carry


def _mul32x32(a, b):
    """Full 64-bit product of two u32 arrays -> (lo, hi) u32."""
    a0 = a & _M16
    a1 = a >> 16
    b0 = b & _M16
    b1 = b >> 16
    m00 = a0 * b0
    m01 = a0 * b1
    m10 = a1 * b0
    m11 = a1 * b1
    t = (m00 >> 16) + (m01 & _M16) + (m10 & _M16)
    lo = (m00 & _M16) | (t << 16)
    hi = m11 + (m01 >> 16) + (m10 >> 16) + (t >> 16)
    return lo, hi


def _shl64(lo, hi, k: int):
    if k == 0:
        return lo, hi
    if k >= 32:
        return lo * 0, lo << (k - 32) if k > 32 else lo
    return lo << k, (hi << k) | (lo >> (32 - k))


def _shr64(lo, hi, k: int):
    if k == 0:
        return lo, hi
    if k >= 32:
        return hi >> (k - 32) if k > 32 else hi, hi * 0
    return (lo >> k) | (hi << (32 - k)), hi >> k


def _byte64(lo, hi, idx: int):
    """Byte `idx` (0 = least significant) of each 64-bit lane, as u32."""
    w = lo if idx < 4 else hi
    return (w >> (8 * (idx % 4))) & 0xFF


def _from_bytes64(byte_map: list[tuple[int, object]]):
    """Assemble (lo, hi) from [(dest_byte_idx, u32_byte_array), ...]."""
    lo = None
    hi = None
    for dest, b in byte_map:
        w = b << (8 * (dest % 4))
        if dest < 4:
            lo = w if lo is None else lo | w
        else:
            hi = w if hi is None else hi | w
    z = (byte_map[0][1] * 0)
    return (z if lo is None else lo), (z if hi is None else hi)


def _zipper_lo(xlo, xhi, ylo, yhi):
    """First zipper-merge output: formula of hh256._zipper_merge_and_add
    for add[i0], with x = the `v0` param, y = the `v1` param.

    Byte-level reading of the reference masks (dest <- source byte):
      0<-x3? no: ((x & 0xFF000000)|(y & 0xFF00000000)) >> 24 places
      x byte3 at byte0 and y byte4 at byte1, etc.
    """
    return _from_bytes64([
        (0, _byte64(xlo, xhi, 3)), (1, _byte64(ylo, yhi, 4)),
        (3, _byte64(xlo, xhi, 5)), (4, _byte64(ylo, yhi, 6)),
        (2, _byte64(xlo, xhi, 2)), (5, _byte64(xlo, xhi, 1)),
        (6, _byte64(ylo, yhi, 7)), (7, _byte64(xlo, xhi, 0)),
    ])


def _zipper_hi(xlo, xhi, ylo, yhi):
    """Second zipper-merge output (add[i1]), same parameter convention."""
    return _from_bytes64([
        (0, _byte64(ylo, yhi, 3)), (1, _byte64(xlo, xhi, 4)),
        (2, _byte64(ylo, yhi, 2)), (3, _byte64(ylo, yhi, 5)),
        (4, _byte64(ylo, yhi, 1)), (5, _byte64(xlo, xhi, 6)),
        (6, _byte64(ylo, yhi, 0)), (7, _byte64(xlo, xhi, 7)),
    ])


# --- the kernel ---------------------------------------------------------------


def _update_lanes(state, plo, phi):
    """One 32-byte packet for all B chunks.

    state: dict of (4, B) u32 arrays; plo/phi: (4, B) packet words.

    Layout note (TPU): the BATCH dim is the minor (lane) axis. With the
    natural (B, 4) layout the 4-wide lane dim pads to the 128-lane VPU
    register — 3% lane utilization; transposed, every elementwise op in
    the packet chain runs min(B, 128)/128 of the VPU.
    """
    v0lo, v0hi = state["v0lo"], state["v0hi"]
    v1lo, v1hi = state["v1lo"], state["v1hi"]
    m0lo, m0hi = state["m0lo"], state["m0hi"]
    m1lo, m1hi = state["m1lo"], state["m1hi"]

    # v1 += mul0 + lanes
    tlo, thi = _add64(m0lo, m0hi, plo, phi)
    v1lo, v1hi = _add64(v1lo, v1hi, tlo, thi)
    # mul0 ^= lo32(v1) * hi32(v0)
    qlo, qhi = _mul32x32(v1lo, v0hi)
    m0lo, m0hi = m0lo ^ qlo, m0hi ^ qhi
    # v0 += mul1
    v0lo, v0hi = _add64(v0lo, v0hi, m1lo, m1hi)
    # mul1 ^= lo32(v0) * hi32(v1)
    qlo, qhi = _mul32x32(v0lo, v1hi)
    m1lo, m1hi = m1lo ^ qlo, m1hi ^ qhi

    # Zipper merges. Lane pairing: calls are (v1[1],v1[0])->v0[1],v0[0]
    # and (v1[3],v1[2])->v0[3],v0[2]; then the same with v0 as source
    # and v1 as target. Source "x" = even lanes, "y" = odd lanes.
    def zip_add(src_lo, src_hi, dst_lo, dst_hi):
        xlo, xhi = src_lo[0::2], src_hi[0::2]         # lanes 0, 2
        ylo, yhi = src_lo[1::2], src_hi[1::2]         # lanes 1, 3
        e_lo, e_hi = _zipper_lo(xlo, xhi, ylo, yhi)   # -> dst lanes 0, 2
        o_lo, o_hi = _zipper_hi(xlo, xhi, ylo, yhi)   # -> dst lanes 1, 3
        add_lo = jnp.stack([e_lo, o_lo], axis=1).reshape(dst_lo.shape)
        add_hi = jnp.stack([e_hi, o_hi], axis=1).reshape(dst_hi.shape)
        return _add64(dst_lo, dst_hi, add_lo, add_hi)

    v0lo, v0hi = zip_add(v1lo, v1hi, v0lo, v0hi)
    v1lo, v1hi = zip_add(v0lo, v0hi, v1lo, v1hi)

    return {"v0lo": v0lo, "v0hi": v0hi, "v1lo": v1lo, "v1hi": v1hi,
            "m0lo": m0lo, "m0hi": m0hi, "m1lo": m1lo, "m1hi": m1hi}


def _permute_and_update(state):
    """update with permuted v0: lanes (2,3,0,1), 32-bit halves swapped.
    swap32 in pair representation is just (lo, hi) -> (hi, lo)."""
    perm = jnp.array([2, 3, 0, 1])
    plo = state["v0hi"][perm]      # swapped halves: lo <- hi
    phi = state["v0lo"][perm]
    return _update_lanes(state, plo, phi)


def _modular_reduction(a3lo, a3hi, a2lo, a2hi, a1lo, a1hi, a0lo, a0hi):
    """(m1, m0) pairs per hh256._modular_reduction."""
    a3hi = a3hi & 0x3FFFFFFF           # a3 &= 2^62-1 (top 2 bits of hi)
    s1lo, s1hi = _shl64(a3lo, a3hi, 1)
    r1lo, r1hi = _shr64(a2lo, a2hi, 63)
    s2lo, s2hi = _shl64(a3lo, a3hi, 2)
    r2lo, r2hi = _shr64(a2lo, a2hi, 62)
    m1lo = a1lo ^ (s1lo | r1lo) ^ (s2lo | r2lo)
    m1hi = a1hi ^ (s1hi | r1hi) ^ (s2hi | r2hi)
    t1lo, t1hi = _shl64(a2lo, a2hi, 1)
    t2lo, t2hi = _shl64(a2lo, a2hi, 2)
    m0lo = a0lo ^ t1lo ^ t2lo
    m0hi = a0hi ^ t1hi ^ t2hi
    return m1lo, m1hi, m0lo, m0hi


def _rot32_halves(w, c: int):
    """Rotate each 32-bit word left by c (the u64 halves rotate
    independently, so pair representation needs no cross-word bits)."""
    if c == 0:
        return w
    return (w << c) | (w >> (32 - c))


@partial(jax.jit, static_argnames=("n_packets", "rem"))
def _hash_chunks_device(words, rem_packet, init, n_packets: int, rem: int):
    """words: (B, n_packets, 8) u32 (little-endian 64-bit lane pairs);
    rem_packet: (B, 8) u32 pre-packed remainder packet (ignored when
    rem == 0); init: 8 x (4,) u32 from _init_state_np.
    Returns (B, 8) u32 digests."""
    B = words.shape[0]
    # Batch-minor layout: (n, 8, B) packet stream, (4, B) state (see
    # _update_lanes layout note). One device-side transpose up front.
    words = jnp.transpose(words, (1, 2, 0))
    rem_t = rem_packet.T
    names = ("v0lo", "v0hi", "v1lo", "v1hi", "m0lo", "m0hi", "m1lo", "m1hi")
    state = {n: jnp.broadcast_to(init[i][:, None], (4, B)).astype(jnp.uint32)
             for i, n in enumerate(names)}

    def body(i, st):
        pkt = jax.lax.dynamic_slice_in_dim(words, i, 1, axis=0)[0]
        plo = pkt[0::2]
        phi = pkt[1::2]
        return _update_lanes(st, plo, phi)

    if n_packets:
        state = jax.lax.fori_loop(0, n_packets, body, state)

    if rem:
        # v0 += (rem << 32) + rem; v1 = rot32_halves(v1, rem & 31)
        # (hh256._update_remainder with static sizes).
        rlo = jnp.uint32(rem)
        state["v0lo"], state["v0hi"] = _add64(
            state["v0lo"], state["v0hi"],
            jnp.broadcast_to(rlo, (4, B)), jnp.broadcast_to(rlo, (4, B)))
        state["v1lo"] = _rot32_halves(state["v1lo"], rem & 31)
        state["v1hi"] = _rot32_halves(state["v1hi"], rem & 31)
        state = _update_lanes(state, rem_t[0::2], rem_t[1::2])

    for _ in range(10):
        state = _permute_and_update(state)

    # h = mod_reduction over (v1[i]+mul1[i], v0[i]+mul0[i]) lane sums.
    slo, shi = _add64(state["v1lo"], state["v1hi"],
                      state["m1lo"], state["m1hi"])   # v1 + mul1
    tlo, thi = _add64(state["v0lo"], state["v0hi"],
                      state["m0lo"], state["m0hi"])   # v0 + mul0
    h1lo, h1hi, h0lo, h0hi = _modular_reduction(
        slo[1], shi[1], slo[0], shi[0],
        tlo[1], thi[1], tlo[0], thi[0])
    h3lo, h3hi, h2lo, h2hi = _modular_reduction(
        slo[3], shi[3], slo[2], shi[2],
        tlo[3], thi[3], tlo[2], thi[2])
    out = jnp.stack([h0lo, h0hi, h1lo, h1hi, h2lo, h2hi, h3lo, h3hi],
                    axis=1)
    return out


def _pack_remainder(tail: np.ndarray, rem: int) -> np.ndarray:
    """(B, rem) trailing bytes -> (B, 8) u32 remainder packets, exactly
    hh256._update_remainder's byte layout (static given rem)."""
    B = tail.shape[0]
    size_mod4 = rem & 3
    remainder_off = rem & ~3
    packet = np.zeros((B, 32), dtype=np.uint8)
    packet[:, :remainder_off] = tail[:, :remainder_off]
    if rem & 16:
        for i in range(4):
            packet[:, 28 + i] = tail[:, remainder_off + i + size_mod4 - 4]
    elif size_mod4:
        packet[:, 16] = tail[:, remainder_off]
        packet[:, 17] = tail[:, remainder_off + (size_mod4 >> 1)]
        packet[:, 18] = tail[:, remainder_off + size_mod4 - 1]
    return packet.view(np.uint32)


def hash_chunks(chunks: np.ndarray, key: bytes = MAGIC_KEY) -> np.ndarray:
    """Hash B equal-length chunks on the device.

    chunks: (B, L) uint8, L > 0 (any length — the remainder step is
    in-kernel). Returns (B, 32) uint8 HighwayHash-256 digests,
    byte-identical to ops/hh256.HighwayHash256.
    """
    if chunks.ndim != 2:
        raise ValueError("chunks must be (B, L)")
    B, L = chunks.shape
    if L == 0:
        raise ValueError("chunk length must be positive")
    n_full, rem = divmod(L, 32)
    chunks = np.ascontiguousarray(chunks)
    words = chunks[:, :n_full * 32].copy().view(np.uint32).reshape(
        B, n_full, 8)
    if rem:
        rem_packet = _pack_remainder(chunks[:, n_full * 32:], rem)
    else:
        rem_packet = np.zeros((B, 8), dtype=np.uint32)
    init = _init_state_np(key)
    # Spread independent chunks across the serving mesh; the hash chain
    # is per-row, so no cross-device collectives.
    from . import batching
    from ..obs.kernel_stats import HH256, KERNEL, timed
    m = batching.serving_mesh()
    if m is not None and B % m.size == 0:
        from ..parallel.mesh import rows_sharding
        words = jax.device_put(words, rows_sharding(m, B, 3))
        rem_packet = jax.device_put(rem_packet, rows_sharding(m, B, 2))
    with timed() as t:
        out = np.asarray(_hash_chunks_device(words, rem_packet, init,
                                             n_full, rem))
    KERNEL.record(HH256, True, chunks.nbytes, t.s, blocks=B,
                  backend=batching.attempt_backend())
    return out.view(np.uint8).reshape(B, 32)
