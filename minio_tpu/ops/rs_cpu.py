"""CPU reference Reed-Solomon codec (numpy, table-driven).

Semantics mirror the reference's codec seam (Erasure.EncodeData /
DecodeDataBlocks, ref cmd/erasure-coding.go:70,89 and the underlying
klauspost Encoder contract):

- split(data): k shards of ceil(len/k) bytes, zero-padded (ref Split,
  dependency of cmd/erasure-coding.go:74).
- encode: parity rows of the systematic matrix applied to the data shards.
- reconstruct_data: rebuild missing DATA shards from any k survivors.
- reconstruct: rebuild all missing shards (data + parity).

This is the golden model for the TPU kernels and the byte-identity oracle
for tests. It is deliberately simple; the fast CPU path is the C++
nibble-shuffle kernel (native/rs.cc via ops/batching.host_apply) and the
fast device path is rs_tpu/rs_pallas.
"""

from __future__ import annotations

import numpy as np

from ..utils import ceil_frac
from .gf256 import gf_mat_vec_apply
from .rs_matrix import decode_matrix, encode_matrix, parity_matrix


def shard_len(data_len: int, k: int) -> int:
    return ceil_frac(data_len, k)


def split(data: bytes | np.ndarray, k: int, m: int) -> np.ndarray:
    """Split a byte buffer into a (k+m, shard_len) array.

    Data shards hold the (zero-padded) input; parity rows are zero until
    encode() fills them. Empty input is rejected like the reference
    (ErrShortData).
    """
    buf = np.frombuffer(bytes(data), dtype=np.uint8) if not isinstance(
        data, np.ndarray) else data.astype(np.uint8, copy=False).ravel()
    if buf.size == 0:
        raise ValueError("cannot split empty data")
    per = shard_len(buf.size, k)
    shards = np.zeros((k + m, per), dtype=np.uint8)
    padded = np.zeros(k * per, dtype=np.uint8)
    padded[:buf.size] = buf
    shards[:k] = padded.reshape(k, per)
    return shards


def encode(shards: np.ndarray, k: int, m: int) -> np.ndarray:
    """Fill parity rows in-place from data rows; returns shards."""
    pm = parity_matrix(k, m)
    shards[k:] = gf_mat_vec_apply(pm, shards[:k])
    return shards


def encode_data(data: bytes, k: int, m: int) -> np.ndarray:
    """split + encode, the EncodeData equivalent."""
    return encode(split(data, k, m), k, m)


def join(shards: np.ndarray, k: int, data_len: int) -> bytes:
    """Concatenate data shards and trim padding to the original length."""
    return shards[:k].tobytes()[:data_len]


def reconstruct_data(shards: list[np.ndarray | None], k: int, m: int,
                     ) -> list[np.ndarray]:
    """Rebuild missing data shards. `shards` has k+m entries, None = missing.

    Returns the full list with data entries (0..k-1) all filled; parity
    entries are left as-is (possibly None) — matching ReconstructData.
    """
    available = [i for i, s in enumerate(shards) if s is not None]
    missing_data = [i for i in range(k) if shards[i] is None]
    if not missing_data:
        return list(shards)
    dec, used = decode_matrix(k, m, available)
    src = np.stack([shards[i] for i in used])
    rows = dec[missing_data, :]
    rebuilt = gf_mat_vec_apply(rows, src)
    out = list(shards)
    for idx, r in zip(missing_data, rebuilt):
        out[idx] = r
    return out


def reconstruct(shards: list[np.ndarray | None], k: int, m: int,
                ) -> list[np.ndarray]:
    """Rebuild ALL missing shards (data then parity re-encode)."""
    out = reconstruct_data(shards, k, m)
    missing_parity = [i for i in range(k, k + m) if out[i] is None]
    if missing_parity:
        pm = encode_matrix(k, m)[missing_parity, :]
        data = np.stack(out[:k])
        rebuilt = gf_mat_vec_apply(pm, data)
        for idx, r in zip(missing_parity, rebuilt):
            out[idx] = r
    return out


def verify(shards: np.ndarray, k: int, m: int) -> bool:
    """Check parity consistency (Encoder.Verify equivalent)."""
    pm = parity_matrix(k, m)
    expect = gf_mat_vec_apply(pm, shards[:k])
    return bool(np.array_equal(expect, shards[k:]))
