"""Reed-Solomon encoding matrix construction, byte-compatible with the
reference's codec dependency.

The reference creates its encoder as `reedsolomon.New(k, m)` (ref
cmd/erasure-coding.go:56) which uses the default systematic-Vandermonde
construction:

    vm[r, c]  = r^c  over GF(2^8)         (rows k+m, cols k)
    encode    = vm @ inverse(vm[:k, :k])

The top k rows of `encode` are the identity (systematic: data shards pass
through); rows k..k+m-1 generate parity. Reproducing this construction —
including the galExp(0,0)==1 convention — is what makes shards
byte-identical to the Go reference.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from .gf256 import gf_exp, gf_mat_invert, gf_matmul

MAX_SHARDS = 256  # k + m <= 256 (ref cmd/erasure-coding.go:41)


def vandermonde(rows: int, cols: int) -> np.ndarray:
    out = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            out[r, c] = gf_exp(r, c)
    return out


@lru_cache(maxsize=None)
def _encode_matrix_cached(data_shards: int, parity_shards: int) -> np.ndarray:
    total = data_shards + parity_shards
    if data_shards <= 0 or parity_shards <= 0:
        raise ValueError("data and parity shard counts must be positive")
    if total > MAX_SHARDS:
        raise ValueError(f"too many shards: {total} > {MAX_SHARDS}")
    vm = vandermonde(total, data_shards)
    top_inv = gf_mat_invert(vm[:data_shards, :data_shards])
    enc = gf_matmul(vm, top_inv)
    enc.setflags(write=False)
    return enc


def encode_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """Full (k+m, k) systematic encoding matrix. Top k rows are identity."""
    return _encode_matrix_cached(data_shards, parity_shards)


def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (m, k) parity-generating rows."""
    return encode_matrix(data_shards, parity_shards)[data_shards:]


@lru_cache(maxsize=4096)
def any_decode_matrix(data_shards: int, parity_shards: int,
                      available: tuple[int, ...],
                      missing: tuple[int, ...],
                      ) -> tuple[np.ndarray, tuple[int, ...]]:
    """Matrix rebuilding arbitrary missing shards (data AND parity) from
    the first-k survivors, in ONE application.

    Data rows come straight from the decode matrix; a missing parity row
    p is enc[p] @ dec (parity = enc_row @ data and data = dec @ survivors),
    so heal's full-shard regeneration is a single matmul instead of
    decode-then-re-encode (ref DecodeDataAndParityBlocks,
    cmd/erasure-coding.go:106, done there as two passes).

    Returns ((len(missing), k) matrix, used_shard_indices).
    """
    dec, used = decode_matrix(data_shards, parity_shards, list(available))
    enc = encode_matrix(data_shards, parity_shards)
    rows = [dec[i] if i < data_shards else gf_matmul(enc[i:i + 1], dec)[0]
            for i in missing]
    mat = (np.stack(rows).astype(np.uint8) if rows
           else np.zeros((0, data_shards), dtype=np.uint8))
    mat.setflags(write=False)
    return mat, tuple(used)


def decode_matrix(data_shards: int, parity_shards: int,
                  available: list[int]) -> tuple[np.ndarray, list[int]]:
    """Build the data-reconstruction matrix for a given availability set.

    `available` lists the shard indices (0..k+m-1) that are present. Following
    the reference dependency's ReconstructData: take the FIRST k available
    shards in index order, gather their rows of the encode matrix, invert.
    Row r of the returned (k, k) matrix reconstructs data shard r from those
    k survivor shards.

    Returns (data_decode_matrix, used_shard_indices).
    """
    if len(available) < data_shards:
        raise ValueError(
            f"need at least {data_shards} shards, have {len(available)}")
    enc = encode_matrix(data_shards, parity_shards)
    used = sorted(available)[:data_shards]
    sub = enc[used, :]
    return gf_mat_invert(sub), used
