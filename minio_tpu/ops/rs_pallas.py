"""Pallas packed-GF Reed-Solomon kernel: bytes in HBM, bit-planes in VMEM.

The round-1..3 device codec (rs_tpu.gf_apply) lowered GF(2^8) to a
bit-plane matmul in plain XLA: unpack bytes to (8k, S) bf16, matmul,
pack. XLA materializes the unpacked planes in HBM — 16x the input
bytes of traffic (8 planes x 2-byte bf16) — so the codec was HBM-bound
at a fraction of the achievable rate.

This kernel keeps the inflation on-chip (round-1..3 verdict ask):

    HBM:   (B*k, S) uint8  ->  (B*r, S) uint8      (bytes only)
    VMEM:  unpack (k,T)->(8k,T) bf16, MXU matmul, mask+pack

Per grid cell (one batch row x one lane tile T):
  1. load (k, T) bytes, widen to int32 on the VPU
  2. unpack LSB-first bit-planes as a CONCAT along sublanes — plane-major
     layout (plane a of all k bytes contiguous), not byte-major, so no
     sublane interleave is needed
  3. one (8r, 8k) @ (8k, T) MXU matmul, f32 accumulation — exact: the
     popcount per output bit is <= 8k <= 128 < 2^24
  4. mod-2 via int32 &1, pack 8 planes back to bytes with shifts+or

The (8r, 8k) GF(2) matrix is permuted host-side to match the
plane-major layout (_permute_bitplane): row b*r+i is bit b of output
byte i, column a*k+j is bit a of input byte j. The permutation is a
pure relabeling of the same GF(2) linear map, so results are
byte-identical to the XLA path and to the rs_cpu golden codec
(tests/test_rs_pallas.py, interpret mode).

Serves encode, reconstruct and heal exactly like rs_tpu.gf_apply — the
matrix is the only difference between them. Reference parity points:
cmd/erasure-coding.go:70 (EncodeData), :89 (DecodeDataBlocks); the
reference's AVX2 galois kernels are SIMD table lookups, which have no
MXU analogue — the bit-plane matmul is the TPU-native formulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128           # TPU lane width: last-dim tiles must be multiples
_MAX_TILE = 4096     # lanes per grid cell; bounds VMEM (see _tile_for)


@functools.lru_cache(maxsize=None)
def _plane_perms(r: int, k: int) -> tuple[np.ndarray, np.ndarray]:
    """(row_perm, col_perm) mapping plane-major positions to the
    byte-major layout of gf256.gf_matrix_to_bitplane."""
    rows = np.array([i * 8 + b for b in range(8) for i in range(r)],
                    dtype=np.int32)
    cols = np.array([j * 8 + a for a in range(8) for j in range(k)],
                    dtype=np.int32)
    return rows, cols


def _permute_bitplane(big_m: jnp.ndarray, r: int, k: int,
                      dtype=jnp.bfloat16) -> jnp.ndarray:
    """Byte-major (8r, 8k) bit matrix -> plane-major."""
    rows, cols = _plane_perms(r, k)
    return big_m[rows][:, cols].astype(dtype)


def _tile_for(r: int, k: int, S: int) -> int:
    """Lane-tile size: large enough to amortize grid overhead, small
    enough that the unpacked planes + accumulator fit VMEM comfortably
    (bits (8k,T) bf16 + acc (8r,T) f32 + int32 temps, double-buffered)."""
    budget = 6 * 1024 * 1024
    per_lane = 16 * k + 4 * 8 * r + 8 * k  # bf16 planes + f32 acc + temps
    t = min(_MAX_TILE, max(LANE, (budget // per_lane) // LANE * LANE))
    if S < t:
        t = (S + LANE - 1) // LANE * LANE
    return t


def _kernel(r: int, k: int, dtype, m_ref, x_ref, o_ref):
    """One (k, T) byte tile -> (r, T) byte tile."""
    xi = x_ref[...].astype(jnp.int32)                       # (k, T)
    planes = [((xi >> a) & 1) for a in range(8)]
    bits = jnp.concatenate(planes, axis=0).astype(dtype)    # (8k, T)
    acc = jnp.dot(m_ref[...], bits,
                  preferred_element_type=jnp.float32)       # (8r, T)
    ib = acc.astype(jnp.int32) & 1
    out = ib[0:r, :]
    for b in range(1, 8):
        out = out | (ib[b * r:(b + 1) * r, :] << b)
    o_ref[...] = out.astype(jnp.uint8)


@functools.partial(jax.jit,
                   static_argnames=("r", "k", "interpret", "with_data"))
def _apply_jit(big_m: jnp.ndarray, shards: jnp.ndarray, r: int, k: int,
               interpret: bool = False,
               with_data: bool = False) -> jnp.ndarray:
    """One fused dispatch: permute matrix, lane-pad, pallas_call,
    un-pad, and (encode) append parity to data — all under jit so the
    pad/slice/concat around the kernel never round-trip HBM separately."""
    lead = shards.shape[:-2]
    S = shards.shape[-1]
    B = 1
    for d in lead:
        B *= d
    # bf16 operands feed the MXU on TPU; interpret mode (CPU CI) uses
    # f32 — XLA-CPU has no bf16 dot thunk. Both are exact: operands are
    # 0/1 and the f32 accumulator holds popcounts <= 8k <= 128.
    dtype = jnp.float32 if interpret else jnp.bfloat16
    mperm = _permute_bitplane(big_m, r, k, dtype)
    x = shards.reshape(B * k, S)
    T = _tile_for(r, k, S)
    pad = (-S) % T
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
    Sp = S + pad
    grid = (B, Sp // T)
    out = pl.pallas_call(
        functools.partial(_kernel, r, k, dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r, 8 * k), lambda b, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, T), lambda b, t: (b, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, T), lambda b, t: (b, t),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * r, Sp), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * grid[0] * grid[1] * (8 * r) * (8 * k) * T,
            bytes_accessed=B * k * Sp + B * r * Sp,
            transcendentals=0),
        interpret=interpret,
    )(mperm, x)
    if pad:
        out = out[:, :S]
    out = out.reshape(*lead, r, S)
    if with_data:
        return jnp.concatenate([shards, out], axis=-2)
    return out


def _norm(big_m, shards) -> tuple[jnp.ndarray, jnp.ndarray, int, int]:
    big_m = jnp.asarray(big_m)
    shards = jnp.asarray(shards, dtype=jnp.uint8)
    r, k = big_m.shape[0] // 8, big_m.shape[1] // 8
    if shards.shape[-2] != k:
        raise ValueError(
            f"shards sublane dim {shards.shape[-2]} != k={k}")
    return big_m, shards, r, k


def gf_apply(big_m, shards, *, interpret: bool = False) -> jnp.ndarray:
    """Pallas drop-in for rs_tpu.gf_apply.

    big_m:  (8r, 8k) byte-major bit-plane matrix (0/1, any float/int
            dtype) — the SAME matrices rs_tpu builds; permutation to the
            kernel's plane-major layout happens in-jit.
    shards: (..., k, S) uint8.
    Returns (..., r, S) uint8, byte-identical to the XLA path.
    """
    big_m, shards, r, k = _norm(big_m, shards)
    return _apply_jit(big_m, shards, r, k, interpret=interpret)


def encode_blocks(big_m, data, *, interpret: bool = False) -> jnp.ndarray:
    """(..., k, S) data -> (..., k+m, S) all shards (parity appended)."""
    big_m, data, r, k = _norm(big_m, data)
    return _apply_jit(big_m, data, r, k, interpret=interpret,
                      with_data=True)


def _shard_map(fn, mesh, in_specs, out_specs):
    """shard_map with the replication/vma checker off: the kernel body
    is a pallas_call (whose out_shape declares no varying-axes info) and
    contains no collectives, so the check adds nothing but rejects the
    call. Prefers the supported jax.shard_map; falls back to the
    experimental module (and its older check_rep keyword) on old jax."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def _apply_sharded(mesh, big_m, x, *, interpret: bool,
                   with_data: bool) -> jnp.ndarray:
    """Multi-chip apply: shard_map over the serving mesh, each device
    running the packed kernel on its local (B/nb, k, S/nl) block.

    GF(2^8) maps are independent per byte column and per batch row, so
    there are ZERO collectives — the mesh only partitions work. Specs
    come from parallel/mesh.batch_sharding (single source of truth for
    placement), so the shard_map matches how device_put_batch laid the
    data out and no resharding occurs.
    """
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import batch_sharding
    big_m, x, r, k = _norm(big_m, x)
    if x.ndim != 3:
        raise ValueError("sharded apply expects (B, k, S)")
    B, _, S = x.shape
    spec = batch_sharding(mesh, B, S).spec
    fn = _shard_map(
        functools.partial(_apply_jit, r=r, k=k, interpret=interpret,
                          with_data=with_data),
        mesh, (P(None, None), spec), spec)
    return fn(big_m, x)


def gf_apply_sharded(mesh, big_m, shards, *,
                     interpret: bool = False) -> jnp.ndarray:
    return _apply_sharded(mesh, big_m, shards, interpret=interpret,
                          with_data=False)


def encode_blocks_sharded(mesh, big_m, data, *,
                          interpret: bool = False) -> jnp.ndarray:
    """Multi-chip encode: local data+parity concat on each device."""
    return _apply_sharded(mesh, big_m, data, interpret=interpret,
                          with_data=True)


def smoke() -> None:
    """Tiny eager compile+run proving Mosaic works on this platform and
    produces correct bytes; raises otherwise. Run ONCE by
    rs_tpu._pallas_enabled so a Mosaic-less platform falls back eagerly,
    not at some caller's jit-compile time."""
    from .gf256 import gf_mat_vec_apply
    from .rs_matrix import parity_matrix
    from .rs_tpu import parity_bitplane
    k, m, S = 4, 2, LANE
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (1, k, S)).astype(np.uint8)
    got = np.asarray(gf_apply(parity_bitplane(k, m), data))
    want = gf_mat_vec_apply(parity_matrix(k, m), data[0])
    if not np.array_equal(got[0], want):
        raise RuntimeError("pallas smoke: parity bytes differ from host")
