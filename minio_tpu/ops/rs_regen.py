"""Product-matrix regenerating-code kernels (the REGEN storage class).

Construction: repair-by-transfer product-matrix MBR (PM-MBR, Rashmi/
Shah/Kumar product-matrix framework; "Fast Product-Matrix Regenerating
Codes" is the batched-evaluation treatment this module follows).  For a
k+m layout the code uses n = k+m nodes, repair degree d = n-1, per-node
sub-symbol count alpha = d and message size B = k*d - k*(k-1)/2 stripe
symbols per block.

The message matrix is the classic symmetric PM-MBR form

    M = [[S, T], [T^t, 0]]   (d x d)

with S a k x k symmetric matrix holding k(k+1)/2 message symbols and T
a k x (d-k) matrix holding the rest.  With Psi the n x d Vandermonde
encoding matrix, the full product P = Psi @ M @ Psi^t is symmetric and
node i stores the off-diagonal row sigma_i = (P[i, j] : j != i) — an
invertible remap of the conventional PM-MBR share psi_i^t M (any d rows
of Psi are independent, so the remap matrix Psi_{-i}^t is invertible).

That remap is what buys repair-by-transfer: to repair node f, helper i
reads and ships exactly ONE stored stripe symbol, P[i, f] = P[f, i],
and the d helper responses ARE sigma_f verbatim — no helper-side matrix
math, no rebuilder-side inversion, and per repaired block both disk and
network traffic are d/B of the block instead of the ~1 block plain RS
pays (4+2: 5/14 ≈ 0.36x, a ~2.8x reduction).  The price is MBR storage
overhead: n*alpha/B raw bytes per byte stored (4+2: 30/14 ≈ 2.14x vs
RS 1.5x) — the REGEN-vs-RS tradeoff documented in docs/robustness.md.

Everything here is plain GF(2^8) linear algebra so the batched apply
rides the existing lanes: the Pallas/XLA bit-plane matmul
(rs_tpu.gf_apply) on the jit lanes and the native/numpy table-gather
(batching.host_apply_tagged) on the host lanes, recorded under the
``regen_code`` kernel and planned by the ops/autotune probe ladder.

Layout contract (consumed by erasure/regen, heal and repair_project):
a block of L bytes packs into W with shape (B, nst), nst =
ceil(L / B), column-major stripes (pad -> reshape(nst, B) -> T), and
node i's chunk is its (d, nst) symbol rows flattened row-major — so
stored row r of a block lives contiguous at byte offset r*nst inside
the chunk, which is what makes the minimum-bandwidth repair read a
plain ranged read.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

from .gf256 import (MUL_TABLE, gf_inv, gf_mat_invert, gf_mat_vec_apply,
                    gf_matmul, gf_matrix_to_bitplane)
from .rs_matrix import vandermonde

# Kernel name for autotune plans / kernprof / metrics2 series.  One name
# covers encode and decode: both are a single GF matmul of the same
# shape family, so one measured lane model fits both.
REGEN_CODE = "regen_code"


@dataclass(frozen=True)
class RegenGeometry:
    """Derived PM-MBR parameters for a k+m layout."""

    k: int
    m: int
    n: int      # total nodes = k + m
    d: int      # repair degree = n - 1 (every survivor helps)
    alpha: int  # stripe symbols stored per node per block = d
    B: int      # message stripe symbols per block = k*d - k*(k-1)/2


@functools.lru_cache(maxsize=64)
def geometry(k: int, m: int) -> RegenGeometry:
    if k <= 0 or m <= 0:
        raise ValueError("regen geometry needs k > 0 and m > 0")
    n = k + m
    if n > 255:
        raise ValueError("too many shards for GF(2^8) evaluation points")
    d = n - 1
    return RegenGeometry(k=k, m=m, n=n, d=d, alpha=d,
                         B=k * d - k * (k - 1) // 2)


@functools.lru_cache(maxsize=64)
def basis_positions(k: int, m: int) -> tuple[tuple[int, int], ...]:
    """Message-symbol slots inside the d x d matrix M, in stripe order:
    S's upper triangle first (row-major, i <= j < k), then T row-major
    (i < k, k <= j < d).  Symmetric mirror positions are implied."""
    g = geometry(k, m)
    pos = [(i, j) for i in range(g.k) for j in range(i, g.k)]
    pos += [(i, j) for i in range(g.k) for j in range(g.k, g.d)]
    return tuple(pos)


def message_matrix(k: int, m: int, w: np.ndarray) -> np.ndarray:
    """Stripe vector w (B,) -> symmetric message matrix M (d, d)."""
    g = geometry(k, m)
    M = np.zeros((g.d, g.d), dtype=np.uint8)
    for t, (i, j) in enumerate(basis_positions(k, m)):
        M[i, j] = w[t]
        M[j, i] = w[t]
    return M


@functools.lru_cache(maxsize=64)
def node_generators(k: int, m: int) -> np.ndarray:
    """(n, d, B) generator tensor: node i's stored row r is
    G[i, r] @ w for message stripe w.

    Built by pushing each basis stripe e_t through the bilinear form
    P_t = Psi @ M_t @ Psi^t and reading off the off-diagonal row of
    each node (B is small — 14 for 4+2, 184 for 16+4 — so the B
    passes of tiny gf_matmuls are negligible and cached per (k, m))."""
    g = geometry(k, m)
    psi = vandermonde(g.n, g.d)
    G = np.zeros((g.n, g.d, g.B), dtype=np.uint8)
    others = [[j for j in range(g.n) if j != i] for i in range(g.n)]
    w = np.zeros(g.B, dtype=np.uint8)
    for t in range(g.B):
        w[:] = 0
        w[t] = 1
        P = gf_matmul(gf_matmul(psi, message_matrix(k, m, w)), psi.T)
        for i in range(g.n):
            G[i, :, t] = P[i, others[i]]
    return G


@functools.lru_cache(maxsize=64)
def encode_matrix_regen(k: int, m: int) -> np.ndarray:
    """(n*d, B) flattened encode matrix: all nodes' stored rows from one
    GF matmul against the (B, S) stripe columns."""
    g = geometry(k, m)
    return np.ascontiguousarray(
        node_generators(k, m).reshape(g.n * g.d, g.B))


@functools.lru_cache(maxsize=64)
def encode_bitplane(k: int, m: int) -> np.ndarray:
    return gf_matrix_to_bitplane(encode_matrix_regen(k, m))


def _independent_rows(rows: np.ndarray, want: int) -> list[int]:
    """Greedy GF(2^8) row selection: indices of the first `want`
    linearly independent rows (Gaussian elimination over the field)."""
    basis: list[tuple[int, np.ndarray]] = []
    chosen: list[int] = []
    for ri in range(rows.shape[0]):
        r = rows[ri].copy()
        for p, br in basis:
            c = int(r[p])
            if c:
                r ^= MUL_TABLE[c, br]
        nz = np.nonzero(r)[0]
        if nz.size == 0:
            continue
        p = int(nz[0])
        r = MUL_TABLE[gf_inv(int(r[p])), r]
        basis.append((p, r))
        chosen.append(ri)
        if len(chosen) == want:
            break
    return chosen


@functools.lru_cache(maxsize=256)
def decode_plan(k: int, m: int, nodes: tuple[int, ...],
                ) -> tuple[tuple[tuple[int, int], ...], np.ndarray]:
    """Conventional MBR decode plan from >= k surviving nodes.

    Returns (picks, inv): picks is a tuple of B (node, stored_row)
    coordinates whose generator rows are independent, and inv is the
    (B, B) inverse such that W = inv @ stacked_picked_symbol_rows.
    MBR decodability guarantees any k nodes span the full message; the
    greedy selection just finds a concrete invertible subset."""
    g = geometry(k, m)
    if len(set(nodes)) < g.k:
        raise ValueError(
            f"regen decode needs >= {g.k} nodes, got {len(set(nodes))}")
    G = node_generators(k, m)
    rows = np.concatenate([G[i] for i in nodes], axis=0)
    sel = _independent_rows(rows, g.B)
    if len(sel) < g.B:
        raise ValueError(
            f"regen generator rows rank-deficient: {len(sel)}/{g.B}")
    inv = gf_mat_invert(rows[sel])
    picks = tuple((nodes[p // g.d], p % g.d) for p in sel)
    return picks, inv


@functools.lru_cache(maxsize=256)
def decode_bitplane(k: int, m: int, nodes: tuple[int, ...]) -> np.ndarray:
    return gf_matrix_to_bitplane(decode_plan(k, m, nodes)[1])


def repair_rows(k: int, m: int, failed: int,
                ) -> tuple[tuple[int, int, int], ...]:
    """Repair-by-transfer plan for node `failed`.

    Returns ((helper, helper_row, dest_row), ...): helper i's stored
    row for partner j=failed (its helper_row-th stored row) IS the
    failed node's stored row for partner j=i (its dest_row-th row) —
    P is symmetric, so the shipped symbols need no transform at all."""
    g = geometry(k, m)
    if not 0 <= failed < g.n:
        raise ValueError(f"failed node {failed} out of range 0..{g.n - 1}")
    plan = []
    for helper in range(g.n):
        if helper == failed:
            continue
        helper_row = failed - 1 if failed > helper else failed
        dest_row = helper - 1 if helper > failed else helper
        plan.append((helper, helper_row, dest_row))
    return tuple(plan)


# --- stripe packing -----------------------------------------------------------


def stripe_count(k: int, m: int, length: int) -> int:
    """Stripes per block of `length` bytes: nst = ceil(length / B)."""
    g = geometry(k, m)
    return -(-length // g.B)


def pack_block(k: int, m: int, data: bytes | np.ndarray) -> np.ndarray:
    """One block's bytes -> (B, nst) stripe columns (zero-padded)."""
    g = geometry(k, m)
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.asarray(
            data, dtype=np.uint8)
    nst = stripe_count(k, m, buf.size)
    padded = np.zeros(nst * g.B, dtype=np.uint8)
    padded[:buf.size] = buf
    return np.ascontiguousarray(padded.reshape(nst, g.B).T)


def pack_blocks_batch(k: int, m: int, blocks: np.ndarray) -> np.ndarray:
    """(nblk, L) equal-length blocks -> (B, nblk * nst) stripe columns,
    block b occupying column slice [b*nst, (b+1)*nst)."""
    g = geometry(k, m)
    nblk, L = blocks.shape
    nst = stripe_count(k, m, L)
    padded = np.zeros((nblk, nst * g.B), dtype=np.uint8)
    padded[:, :L] = blocks
    cols = padded.reshape(nblk, nst, g.B).transpose(2, 0, 1)
    return np.ascontiguousarray(cols.reshape(g.B, nblk * nst))


def unpack_block(W: np.ndarray, length: int) -> bytes:
    """(B, nst) stripe columns -> the block's first `length` bytes."""
    return np.ascontiguousarray(W.T).tobytes()[:length]


# --- measured-lane dispatch ---------------------------------------------------


def apply_regen(mat: np.ndarray, cols: np.ndarray, *,
                use_device, bitplane: np.ndarray | None = None,
                affinity: int | None = None, blocks: int = 1,
                device_fallback: bool = True) -> np.ndarray:
    """One GF matmul (mat @ cols) on the measured lane.

    use_device: callable(nbytes) -> bool (the codec's _use_tpu seam).
    bitplane: precomputed gf_matrix_to_bitplane(mat) for the jit lanes
    (the per-(k, m) caches above), recomputed on the fly if omitted.
    Recorded under REGEN_CODE in kernel_stats/kernprof so the autotuner
    refines the regen lanes from live traffic like rs_encode/rs_decode.
    """
    from ..obs.kernel_stats import KERNEL, timed
    from ..qos import scheduler as qos_sched
    from . import batching
    cols = np.ascontiguousarray(cols, dtype=np.uint8)
    nbytes = int(cols.nbytes)
    lane = qos_sched.current_lane()
    with qos_sched.GATE.dispatch(lane):
        if use_device(nbytes) and batching._device_allowed(device_fallback):
            try:
                from ..faultinject import FAULTS
                FAULTS.kernel(REGEN_CODE)
                out = _device_apply(mat if bitplane is None else None,
                                    bitplane, cols, affinity, blocks)
                batching.STATS.add(True, nbytes, 1)
                return out
            except Exception as exc:
                if not device_fallback:
                    raise
                batching.device_dispatch_failed(exc)
        from .autotune import AUTOTUNE
        with timed() as t:
            out, backend = batching.host_apply_tagged(
                mat, cols, AUTOTUNE.host_lane(REGEN_CODE, nbytes))
        KERNEL.record(REGEN_CODE, False, nbytes, t.s, blocks=blocks,
                      backend=backend)
        batching.STATS.add(False, nbytes, 1)
        return out


def _device_apply(mat: np.ndarray | None, bitplane: np.ndarray | None,
                  cols: np.ndarray, affinity: int | None,
                  blocks: int) -> np.ndarray:
    from ..obs.kernel_stats import KERNEL, timed
    from . import batching, rs_tpu
    bm = gf_matrix_to_bitplane(mat) if bitplane is None else bitplane
    with timed() as t:
        out = np.asarray(rs_tpu.gf_apply(
            batching.device_put_replicated(bm),
            batching.device_put_batch(cols[None], affinity)))[0]
    KERNEL.record(REGEN_CODE, True, cols.nbytes, t.s, blocks=blocks,
                  backend=batching.attempt_backend())
    return out


# --- probe (ops/autotune ladder) ----------------------------------------------


def probe_lane(lane: str, nstripes: int) -> tuple[float | None, str]:
    """Known-answer throughput probe of one regen dispatch lane.

    Mirrors select_kernels.probe_lane: a deterministic 4+2 encode of
    `nstripes` stripe columns, checked against the table-gather truth,
    timed after one warm-up run.  Returns (bytes/s, "") or (None, why).
    """
    import time

    from ..obs.kernprof import DEVICE, HOST, NATIVE, XLA_CPU
    from . import batching
    k, m = 4, 2
    g = geometry(k, m)
    rng = np.random.default_rng(12073022)
    W = rng.integers(0, 256, size=(g.B, nstripes), dtype=np.uint8)
    mat = encode_matrix_regen(k, m)
    want = gf_mat_vec_apply(mat, W)
    nbytes = W.nbytes
    try:
        from ..faultinject import FAULTS
        FAULTS.kernel(REGEN_CODE)
        if lane in (DEVICE, XLA_CPU):
            from . import rs_tpu
            bm = encode_bitplane(k, m)
            np.asarray(rs_tpu.gf_apply(bm, W[None]))  # warm/compile
            t0 = time.perf_counter()
            got = np.asarray(rs_tpu.gf_apply(bm, W[None]))[0]
            wall = time.perf_counter() - t0
        elif lane in (NATIVE, HOST):
            batching.host_apply_tagged(mat, W, lane)  # warm
            t0 = time.perf_counter()
            got, backend = batching.host_apply_tagged(mat, W, lane)
            wall = time.perf_counter() - t0
            if lane == NATIVE and backend != NATIVE:
                return None, "native kernel not built"
        else:
            return None, f"unknown lane {lane!r}"
        if not np.array_equal(got, want):
            return None, "known-answer mismatch"
        return nbytes / max(wall, 1e-9), ""
    except Exception as exc:  # probe must never take the ladder down
        return None, f"{type(exc).__name__}: {exc}"
