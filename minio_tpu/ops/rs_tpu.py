"""TPU-native Reed-Solomon: GF(2^8) linear maps as MXU bit-plane matmuls.

Design (TPU-first, NOT a port of the reference's SIMD table lookups):

GF(2^8) multiplication by a constant is GF(2)-linear in the bits of the
input byte. An (r, k) GF(2^8) matrix therefore lowers to an (8r, 8k) 0/1
matrix over GF(2) (gf256.gf_matrix_to_bitplane). Applying it to shard bytes
becomes:

    unpack bytes -> bit-planes        (k, S) u8  -> (8k, S) bf16
    parity_bits  = (BigM @ bits) & 1  MXU matmul, f32 accumulation (exact:
                                      popcount <= 8k <= 2048 < 2^24)
    pack bit-planes -> bytes          (8m, S) -> (m, S) u8

The whole encode is one batched matmul — large, static-shaped, bf16: exactly
what the MXU wants. Reconstruction is the same kernel with a different
(host-inverted, see rs_matrix.decode_matrix) matrix, so a single compiled
function serves encode, reconstruct, and heal; the matrix is a runtime
argument and never triggers recompilation.

Batching: callers coalesce many blocks into (B, k, S) before dispatch
(ops/batching.py); the grid then has B*ceil(S/tile) independent tiles.

Reference parity points: cmd/erasure-coding.go:70 (EncodeData),
:89 (DecodeDataBlocks); shard bytes are byte-identical to the Go encoder
because the matrices come from rs_matrix (same construction).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .gf256 import gf_matrix_to_bitplane
from .rs_matrix import any_decode_matrix, decode_matrix, parity_matrix

# --- host-side matrix prep ----------------------------------------------------


@lru_cache(maxsize=None)
def parity_bitplane(k: int, m: int) -> np.ndarray:
    """(8m, 8k) bf16 bit-plane matrix generating parity from data shards."""
    return gf_matrix_to_bitplane(parity_matrix(k, m)).astype(np.float32)


@lru_cache(maxsize=1024)
def decode_bitplane(k: int, m: int, available: tuple[int, ...],
                    missing: tuple[int, ...]) -> tuple[np.ndarray, list[int]]:
    """Bit-plane matrix rebuilding `missing` data shards from survivors.

    Returns (bitplane_matrix (8*len(missing), 8k), used_shard_indices).
    """
    dec, used = decode_matrix(k, m, list(available))
    rows = dec[list(missing), :]
    return gf_matrix_to_bitplane(rows).astype(np.float32), used


@lru_cache(maxsize=1024)
def any_decode_bitplane(k: int, m: int, available: tuple[int, ...],
                        missing: tuple[int, ...],
                        ) -> tuple[np.ndarray, tuple[int, ...]]:
    """Bit-plane matrix rebuilding arbitrary missing shards (data and
    parity) from survivors — one matmul serves GET-with-loss and heal
    (see rs_matrix.any_decode_matrix)."""
    mat, used = any_decode_matrix(k, m, available, missing)
    return gf_matrix_to_bitplane(mat).astype(np.float32), used


@lru_cache(maxsize=1024)
def _placed_parity(k: int, m: int, mesh,
                   device_index: int | None = None) -> "jnp.ndarray":
    """parity_bitplane already cached host-side; this caches the
    DEVICE-PLACED copy so the hot PUT path doesn't re-transfer the
    matrix on every dispatch (mesh is hashable; None on a single
    device).  ``device_index`` pins the matrix to the batch's home
    device when the batch itself is affinity-pinned — a mesh-
    replicated matrix against a single-device operand is a jit
    placement error."""
    from . import batching
    if device_index is not None:
        return _device_pinned(parity_bitplane(k, m), device_index)
    return batching.device_put_replicated(parity_bitplane(k, m))


@lru_cache(maxsize=1024)
def _placed_any_decode(k: int, m: int, available: tuple[int, ...],
                       missing: tuple[int, ...], mesh,
                       device_index: int | None = None,
                       ) -> "jnp.ndarray":
    from . import batching
    bm, _ = any_decode_bitplane(k, m, available, missing)
    if device_index is not None:
        return _device_pinned(bm, device_index)
    return batching.device_put_replicated(bm)


def _device_pinned(x: np.ndarray, device_index: int) -> "jnp.ndarray":
    devs = jax.devices()
    return jax.device_put(x, devs[device_index % len(devs)])


# --- device kernel ------------------------------------------------------------
#
# Two implementations of the same bit-plane linear map:
#  - rs_pallas.gf_apply: Pallas/Mosaic kernel that keeps the 16x bit-plane
#    inflation in VMEM (bytes-only HBM traffic) — the fast path on TPU.
#  - _gf_apply_xla below: plain XLA fallback (materializes the planes) —
#    used on CPU, for non-batched (2-D) inputs on a mesh, and when
#    Mosaic is unavailable on the platform (disabled loudly, once).
#    Mesh-sharded 3-D batches run the Pallas kernel under shard_map
#    (rs_pallas.gf_apply_sharded) — one local kernel per chip.

_pallas_state: dict = {"enabled": None}


def _pallas_enabled() -> bool:
    """Pallas on a non-CPU platform, unless disabled by env or by a
    prior compile failure. On a single device the kernel is called
    directly; on a multi-device serving mesh it runs under shard_map
    (rs_pallas.gf_apply_sharded) — each chip applies the packed kernel
    to its local block, no collectives."""
    import os
    st = _pallas_state["enabled"]
    if st is False:
        return False
    if os.environ.get("MINIO_TPU_NO_PALLAS"):
        return False
    if st is None:
        try:
            import jax as _jax
            ok = any(d.platform != "cpu" for d in _jax.devices())
            if ok:
                # Eager one-time smoke compile: a platform without Mosaic
                # must fall back HERE, not at a caller's jit-compile.
                from . import rs_pallas
                rs_pallas.smoke()
        except Exception as exc:
            _disable_pallas(exc)
            return False
        _pallas_state["enabled"] = ok
        st = ok
    return bool(st)


def _disable_pallas(exc: BaseException) -> None:
    import logging
    _pallas_state["enabled"] = False
    logging.getLogger("minio_tpu.ops").warning(
        "Pallas GF kernel unavailable on this platform; using the XLA "
        "bit-plane path: %r", exc)


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """(..., k, S) uint8 -> (..., 8k, S) bf16 bit-planes (LSB-first)."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    # (..., k, 8, S)
    bits = (x[..., :, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    shape = bits.shape[:-3] + (bits.shape[-3] * 8, bits.shape[-1])
    return bits.reshape(shape).astype(jnp.bfloat16)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., 8m, S) int32 0/1 -> (..., m, S) uint8."""
    shape = bits.shape[:-2] + (bits.shape[-2] // 8, 8, bits.shape[-1])
    b = bits.reshape(shape)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    return jnp.sum(b * weights, axis=-2).astype(jnp.uint8)


@jax.jit
def _gf_apply_xla(big_m: jnp.ndarray, shards: jnp.ndarray) -> jnp.ndarray:
    bits = _unpack_bits(shards)
    acc = jnp.matmul(big_m.astype(jnp.bfloat16), bits,
                     preferred_element_type=jnp.float32)
    out_bits = acc.astype(jnp.int32) & 1
    return _pack_bits(out_bits)


def _dispatch(pallas_fn, pallas_sharded_fn, xla_fn, big_m, x):
    """Pallas on TPU (direct on one device, shard_map'd over a serving
    mesh), XLA otherwise. Input errors (ValueError: caller bug, same on
    either path) propagate; anything else disables the Pallas path for
    the process — loudly, once — and falls back.

    Scope of the fallback: it protects EAGER callers, i.e. the whole
    serving path (batching, encode_batch). When gf_apply/encode_blocks
    are traced inside a caller's own jit (driver entry points:
    __graft_entry__.entry, models.ec_pipeline.full_step), Mosaic
    compiles later at the outer jit's compile and a shape-specific
    failure surfaces THERE, by design — the driver's compile check must
    see it, not have it silently papered over."""
    if _pallas_enabled():
        from . import batching
        mesh = batching.serving_mesh()
        try:
            if mesh is None:
                return pallas_fn(big_m, x)
            if getattr(x, "ndim", 0) == 3:
                sh = getattr(x, "sharding", None)
                if sh is not None and len(sh.device_set) == 1:
                    # Affinity-pinned batch: the whole batch lives on
                    # one chip (parallel/mesh.batch_placement) — run
                    # the packed kernel there directly, no shard_map.
                    return pallas_fn(big_m, x)
                return pallas_sharded_fn(mesh, big_m, x)
        except ValueError:
            raise
        except Exception as exc:  # Mosaic compile/platform failure
            _disable_pallas(exc)
    return xla_fn(big_m, x)


def gf_apply(big_m: jnp.ndarray, shards: jnp.ndarray) -> jnp.ndarray:
    """Apply a bit-plane GF matrix to shard bytes.

    big_m:  (8r, 8k) float/bf16 0/1 matrix (from parity_bitplane /
            decode_bitplane).
    shards: (..., k, S) uint8.
    Returns (..., r, S) uint8.

    Dispatches to the Pallas packed kernel on a single TPU, the XLA
    bit-plane matmul otherwise; both are byte-identical.
    """
    from . import rs_pallas
    return _dispatch(rs_pallas.gf_apply, rs_pallas.gf_apply_sharded,
                     _gf_apply_xla, big_m, shards)


@jax.jit
def _encode_blocks_xla(big_m: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    parity = _gf_apply_xla(big_m, data)
    return jnp.concatenate([data, parity], axis=-2)


def encode_blocks(big_m: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """Batched encode: (..., k, S) data shards -> (..., k+m, S) all shards."""
    from . import rs_pallas
    return _dispatch(rs_pallas.encode_blocks,
                     rs_pallas.encode_blocks_sharded, _encode_blocks_xla,
                     big_m, data)


# --- convenience host API -----------------------------------------------------


def encode_batch(data: np.ndarray, k: int, m: int,
                 affinity: int | None = None) -> np.ndarray:
    """Encode a (B, k, S) or (k, S) uint8 batch on the device(s) —
    batches spread across the serving mesh when >1 device is visible,
    or land whole on the owning set's home device (``affinity``) when
    they don't divide it (ops/batching.device_put_batch). Every
    dispatch lands in the metrics-v2 kernel counters
    (invocations/bytes/wall/occupancy)."""
    from . import batching
    from ..obs.kernel_stats import KERNEL, RS_ENCODE, timed
    home = (batching.batch_home_device(data, affinity)
            if data.ndim == 3 else None)
    bm = _placed_parity(k, m, batching.serving_mesh(), home)
    with timed() as t:
        if data.ndim == 3:
            placed = batching.device_put_batch(data, affinity)
        else:
            placed = jnp.asarray(data)
        out = np.asarray(encode_blocks(bm, placed))
    KERNEL.record(RS_ENCODE, True, data.nbytes, t.s,
                  blocks=data.shape[0] if data.ndim == 3 else 1,
                  backend=batching.attempt_backend())
    return out


def reconstruct_batch(shards: np.ndarray, k: int, m: int,
                      available: tuple[int, ...],
                      missing: tuple[int, ...]) -> np.ndarray:
    """Rebuild `missing` data shards for a batch sharing one erasure mask.

    shards: (B, n_avail, S) uint8 — ONLY the survivor shards actually used,
    i.e. the first k available in index order (see decode_bitplane's `used`).
    Returns (B, len(missing), S) rebuilt shards.

    Batches are grouped by mask on the host (ops/batching.py) so each device
    call has a single dense matrix — SURVEY §7 hard part (f).
    """
    bm, used = decode_bitplane(k, m, available, missing)
    if shards.shape[-2] != len(used):
        raise ValueError(
            f"expected {len(used)} survivor shards, got {shards.shape[-2]}")
    return np.asarray(gf_apply(jnp.asarray(bm), jnp.asarray(shards)))
