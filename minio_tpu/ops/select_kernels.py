"""Batched predicate kernels for columnar S3 Select.

The scan engine (s3select/engine.py) hands a compiled predicate plan
plus one ColumnBatch here per dispatch; this module owns WHERE the
math runs and the accounting that keeps that decision honest:

- **Lane choice** rides the measured autotuner model
  (ops/autotune.py, kernel ``select_scan``) like RS math does: the
  fastest healthy lane per batch-size bucket wins, a kernprof-DOWN
  lane is never chosen, and every dispatch feeds the model back
  through ``KernelStats.record``.  There is no C++ select kernel, so
  a NATIVE plan resolves to the numpy host lane.

- **The jit lanes** (device when an accelerator answers, xla-cpu
  otherwise) evaluate the SAME compile.py node tree under jax.numpy,
  traced once per plan and cached.  Only float32-exact plans are
  eligible (compile.Plan.jit_ok + the dtype check at bind) — the jit
  image must be bit-exact against the row oracle, not approximately
  right.  int32 cells past 2^24 join the fallback mask at bind for
  the same reason.

- **QoS**: every dispatch enters the priority gate on the BACKGROUND
  lane — an analytics sweep's kernels defer to in-flight PUT/GET
  dispatches and promote only by aging, so heavy scans cannot starve
  the serving path (the `select` admission class caps concurrency
  one layer up).

- A jit-lane failure feeds the kernprof backend state machine
  (``batching.device_dispatch_failed``) and the batch re-runs on the
  host lane — scans degrade exactly like RS dispatch does.
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs.kernel_stats import KERNEL, SELECT_SCAN, timed
from ..obs.kernprof import DEVICE, HOST, NATIVE, XLA_CPU

# int32 cells past float32's exact-integer range (2^24) cannot ride
# the f32 jit image exactly; they take the row fallback instead.
_F32_INT_EXACT = 1 << 24

_jit_build_mu = threading.Lock()


def plan_nbytes(plan, batch) -> int:
    """Referenced-column payload bytes: the autotuner's size-bucket
    input for this dispatch."""
    total = 0
    for name in plan.cols:
        col = batch.col(name)
        if col is not None:
            total += col.data_nbytes()
    return total


def choose_lane(plan, nbytes: int) -> str:
    """The measured plan's lane for this dispatch; NATIVE resolves to
    HOST (no C++ select kernel), jit lanes require a jit-eligible
    plan."""
    from .autotune import AUTOTUNE
    lane = AUTOTUNE.decide(SELECT_SCAN, nbytes)
    if lane == NATIVE:
        lane = HOST
    if lane in (DEVICE, XLA_CPU) and not plan.jit_ok:
        lane = HOST
    return lane


def eval_predicate(plan, batch) -> tuple[np.ndarray, np.ndarray]:
    """Evaluate a compiled predicate over one batch ->
    (pass mask, fallback mask); accounts the dispatch under kernel
    ``select_scan`` with the lane that actually ran."""
    from ..qos import scheduler as qos_sched
    from ..s3select.compile import passing_mask
    n = batch.nrows
    nbytes = plan_nbytes(plan, batch)
    lane = choose_lane(plan, nbytes)
    blocks = max(1, len(plan.cols))
    with qos_sched.GATE.dispatch(qos_sched.BACKGROUND):
        if lane in (DEVICE, XLA_CPU):
            bound = _bind_jit(plan, batch)
            if bound is None:
                lane = HOST
            else:
                arrs, base_fb = bound
                try:
                    with timed() as t:
                        val, valid = _run_jit(plan, arrs, n)
                    ok = (np.asarray(val) & np.asarray(valid)
                          & ~base_fb)
                    KERNEL.record(SELECT_SCAN, True, nbytes, t.s,
                                  blocks=blocks, backend=lane)
                    return ok, base_fb
                except Exception as exc:  # noqa: BLE001 - lane failover
                    from .batching import device_dispatch_failed
                    device_dispatch_failed(exc)
                    lane = HOST
        with timed() as t:
            vv = plan.eval_host(batch)
            ok, fb = passing_mask(vv, n)
        KERNEL.record(SELECT_SCAN, False, nbytes, t.s, blocks=blocks,
                      backend=HOST)
        return ok, fb


# -- jit lane ----------------------------------------------------------------


def _bind_jit(plan, batch):
    """(ordered arrays, base fallback mask) for the f32 jit image, or
    None when a referenced column's dtype has no exact f32 embedding
    (int64/float64/strings) — the host lane then runs the batch."""
    n = batch.nrows
    arrs: list[np.ndarray] = []
    fb = np.zeros(n, dtype=bool)
    for name in plan.cols:
        col = batch.col(name)
        if col is None:
            arrs.extend((np.zeros(n, dtype=np.float32),
                         np.zeros(n, dtype=bool),
                         np.ones(n, dtype=bool)))
            continue
        valid = ~col.null_mask()
        miss = col.miss_mask()
        if col.kind == "bool":
            arrs.extend((np.asarray(col.raw, dtype=bool), valid,
                         miss))
            continue
        if col.kind != "num":
            return None
        raw = np.asarray(col.raw)
        if raw.dtype.kind == "f":
            if raw.dtype.itemsize > 4:
                return None
            vals = raw.astype(np.float32)
        elif raw.dtype.kind in "iu":
            if raw.dtype.itemsize > 4:
                return None
            big = np.abs(raw.astype(np.int64)) > _F32_INT_EXACT
            if big.any():
                fb |= big & valid
            vals = raw.astype(np.float32)
        else:
            return None
        arrs.extend((vals, valid, miss))
    return arrs, fb


def _run_jit(plan, arrs: list[np.ndarray], n: int):
    fn = plan._jit_fn
    if fn is None:
        with _jit_build_mu:
            fn = plan._jit_fn
            if fn is None:
                fn = plan._jit_fn = _build_jit(plan)
    return fn(*arrs)


def _build_jit(plan):
    import jax

    from ..s3select.compile import Ctx

    order = list(plan.cols)

    def fn(*arrs):
        import jax.numpy as jnp
        n = arrs[0].shape[0]
        arrays = {name: (arrs[3 * i], arrs[3 * i + 1],
                         arrs[3 * i + 2])
                  for i, name in enumerate(order)}
        vv = plan.root.run(Ctx(jnp, n, arrays=arrays))
        val = jnp.broadcast_to(jnp.asarray(vv.val), (n,))
        valid = jnp.broadcast_to(jnp.asarray(vv.valid), (n,))
        return val, valid

    return jax.jit(fn)


# -- autotune probe ----------------------------------------------------------


def probe_lane(lane: str, nrows: int) -> tuple[float | None, str]:
    """One sized known-answer probe of a select lane: (bytes/s, "")
    or (None, cause).  A REAL dispatch — it routes through the
    fault-injection `kernel` hook like the RS probes, so an active
    fault plan keeps the lane unmeasured."""
    import time as _time

    from ..faultinject import FAULTS
    from ..s3select import sql
    from ..s3select.columnar import Column, ColumnBatch
    from ..s3select.compile import Plan, lower, passing_mask

    rng = np.random.default_rng(nrows)
    a = rng.integers(0, 97, nrows).astype(np.float32)
    b = rng.integers(0, 97, nrows).astype(np.float32)
    cols = {"a": Column("a", "num", raw=a),
            "b": Column("b", "num", raw=b)}
    batch = ColumnBatch(["a", "b"], cols, nrows, int(a.nbytes * 2))
    where = sql.BoolOp("and", sql.Cmp("<", sql.Col(("a",)),
                                      sql.Lit(48)),
                       sql.Cmp(">=", sql.Col(("b",)), sql.Lit(16)))
    plan = Plan(lower(where, batch))
    want = (a < 48) & (b >= 16)
    try:
        FAULTS.kernel(SELECT_SCAN)
        if lane in (DEVICE, XLA_CPU):
            bound = _bind_jit(plan, batch)
            if bound is None:
                return None, "jit bind declined"
            arrs, _ = bound

            def run():
                val, valid = _run_jit(plan, arrs, nrows)
                return np.asarray(val) & np.asarray(valid)
        else:
            def run():
                return passing_mask(plan.eval_host(batch),
                                    nrows)[0]
        got = run()   # warm: trace/compile
        t0 = _time.perf_counter()
        got = run()
        wall = _time.perf_counter() - t0
        if not (np.asarray(got) == want).all():
            return None, "known-answer mismatch"
        return batch.nbytes / max(wall, 1e-9), ""
    except Exception as exc:  # noqa: BLE001 - a probe must not raise
        return None, f"{type(exc).__name__}: {exc}"
