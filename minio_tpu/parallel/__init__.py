"""Mesh/sharding machinery (device parallelism) and host-side quorum
parallelism (thread-pool fan-out with write/read quorum semantics)."""
