"""Device-mesh parallelism for the erasure data plane.

The object store's parallel axes (SURVEY §2.6 parallelism inventory) map to
a 2-D device mesh:

- 'blocks' (≈DP): independent 10MiB-stripe blocks from concurrent PUTs/heals
  batch along the leading axis — embarrassingly parallel.
- 'lanes'  (≈TP): shard bytes (the S axis). Every GF(2^8) op is elementwise
  along S, so S shards cleanly with zero communication in encode/decode;
  collectives only appear in integrity reductions (verify sums) and in
  cross-host shard movement.

Multi-chip hardware is not present in dev; shapes/shardings are validated on
a virtual CPU mesh (tests) and via __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import math
import threading

import jax
from jax.sharding import (Mesh, NamedSharding, PartitionSpec as P,
                          SingleDeviceSharding)


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, str] = ("blocks", "lanes"),
              ) -> Mesh:
    """Build a near-square 2-D mesh over the first n devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    # Factor n into (a, b) with a as large as possible <= sqrt-ish.
    a = 1
    for cand in range(int(math.isqrt(n)), 0, -1):
        if n % cand == 0:
            a = cand
            break
    import numpy as np
    arr = np.array(devs).reshape(a, n // a)
    return Mesh(arr, axis_names)


def block_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (B, k, S) shard-block batches: B over 'blocks', S over
    'lanes', shard index replicated (each chip sees whole GF columns)."""
    return NamedSharding(mesh, P("blocks", None, "lanes"))


def batch_sharding(mesh: Mesh, B: int, S: int) -> NamedSharding:
    """block_sharding with divisibility fallback: an axis that doesn't
    divide its mesh dimension stays replicated (serving batches have
    arbitrary B and tail-block S). Single source of truth for the
    serving path AND the dryrun demo."""
    return NamedSharding(mesh, P(
        "blocks" if B % mesh.shape["blocks"] == 0 else None, None,
        "lanes" if S % mesh.shape["lanes"] == 0 else None))


def rows_sharding(mesh: Mesh, B: int, ndim: int) -> NamedSharding:
    """Row-parallel sharding for per-row-independent kernels (the
    HighwayHash batch): B spreads over EVERY mesh axis when divisible,
    remaining dims replicated."""
    if B % mesh.size == 0:
        return NamedSharding(
            mesh, P(tuple(mesh.axis_names), *([None] * (ndim - 1))))
    return NamedSharding(mesh, P())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_placement(mesh: Mesh, B: int, S: int,
                    affinity: int | None = None,
                    ) -> tuple[object, tuple[int, ...]]:
    """(sharding, device indices) for a (B, k, S) serving batch.

    Divisible axes shard across the mesh exactly like
    ``batch_sharding``.  A batch NEITHER axis of which divides used to
    replicate to every chip (each one redundantly computing the whole
    thing); with a per-set ``affinity`` it now lands WHOLE on the
    owning erasure set's home device, so concurrent sets' small
    dispatches spread across chips instead of all queueing on device
    0.  The device-index tuple is what the dispatch actually occupies
    — fed to ``MESH_AFFINITY.record_dispatch`` so the spread is
    provable, not aspirational."""
    sh = batch_sharding(mesh, B, S)  # the one divisibility rule
    if affinity is not None and sh.spec == P(None, None, None):
        devs = jax.devices()
        idx = affinity % len(devs)
        return SingleDeviceSharding(devs[idx]), (idx,)
    return sh, tuple(range(mesh.size))


class DeviceAffinity:
    """Per-erasure-set home-device assignment + per-device dispatch
    census (``MESH_AFFINITY``).

    Each ``ErasureObjects`` set registers at construction and gets the
    next device round-robin; every placed dispatch records which
    device indices it occupied.  The census is the proof behind the
    admin ``/codec-plan`` affinity map and the 8-virtual-device spread
    tests — per-set affinity is only real if the counters say so."""

    def __init__(self):
        self._mu = threading.Lock()
        self._assign: dict[str, int] = {}
        self._next = 0
        self._dispatches: dict[int, int] = {}
        self._bytes: dict[int, int] = {}

    @staticmethod
    def n_devices() -> int:
        try:
            return len(jax.devices())
        except Exception:
            return 1

    def assign(self, owner: str) -> int | None:
        """Home device index for `owner` (idempotent); None on a
        single-device box — affinity only means something on a mesh."""
        n = self.n_devices()
        if n <= 1:
            return None
        with self._mu:
            idx = self._assign.get(owner)
            if idx is None:
                idx = self._next % n
                self._next += 1
                self._assign[owner] = idx
            return idx

    def release(self, owner: str) -> None:
        with self._mu:
            self._assign.pop(owner, None)

    def record_dispatch(self, device_indices: tuple[int, ...],
                        nbytes: int) -> None:
        with self._mu:
            for i in device_indices:
                self._dispatches[i] = self._dispatches.get(i, 0) + 1
                self._bytes[i] = self._bytes.get(i, 0) + nbytes

    def counters(self) -> dict[int, dict]:
        with self._mu:
            return {i: {"dispatches": self._dispatches.get(i, 0),
                        "bytes": self._bytes.get(i, 0)}
                    for i in sorted(set(self._dispatches)
                                    | set(self._bytes))}

    def snapshot(self) -> dict:
        """The affinity map the admin /codec-plan serves."""
        with self._mu:
            return {
                "nDevices": self.n_devices(),
                "assignments": dict(sorted(self._assign.items())),
                "dispatches": {
                    str(i): {"dispatches": self._dispatches.get(i, 0),
                             "bytes": self._bytes.get(i, 0)}
                    for i in sorted(set(self._dispatches)
                                    | set(self._bytes))},
            }

    def reset(self) -> None:
        with self._mu:
            self._assign.clear()
            self._next = 0
            self._dispatches.clear()
            self._bytes.clear()


MESH_AFFINITY = DeviceAffinity()
