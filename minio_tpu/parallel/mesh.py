"""Device-mesh parallelism for the erasure data plane.

The object store's parallel axes (SURVEY §2.6 parallelism inventory) map to
a 2-D device mesh:

- 'blocks' (≈DP): independent 10MiB-stripe blocks from concurrent PUTs/heals
  batch along the leading axis — embarrassingly parallel.
- 'lanes'  (≈TP): shard bytes (the S axis). Every GF(2^8) op is elementwise
  along S, so S shards cleanly with zero communication in encode/decode;
  collectives only appear in integrity reductions (verify sums) and in
  cross-host shard movement.

Multi-chip hardware is not present in dev; shapes/shardings are validated on
a virtual CPU mesh (tests) and via __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, str] = ("blocks", "lanes"),
              ) -> Mesh:
    """Build a near-square 2-D mesh over the first n devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    # Factor n into (a, b) with a as large as possible <= sqrt-ish.
    a = 1
    for cand in range(int(math.isqrt(n)), 0, -1):
        if n % cand == 0:
            a = cand
            break
    import numpy as np
    arr = np.array(devs).reshape(a, n // a)
    return Mesh(arr, axis_names)


def block_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (B, k, S) shard-block batches: B over 'blocks', S over
    'lanes', shard index replicated (each chip sees whole GF columns)."""
    return NamedSharding(mesh, P("blocks", None, "lanes"))


def batch_sharding(mesh: Mesh, B: int, S: int) -> NamedSharding:
    """block_sharding with divisibility fallback: an axis that doesn't
    divide its mesh dimension stays replicated (serving batches have
    arbitrary B and tail-block S). Single source of truth for the
    serving path AND the dryrun demo."""
    return NamedSharding(mesh, P(
        "blocks" if B % mesh.shape["blocks"] == 0 else None, None,
        "lanes" if S % mesh.shape["lanes"] == 0 else None))


def rows_sharding(mesh: Mesh, B: int, ndim: int) -> NamedSharding:
    """Row-parallel sharding for per-row-independent kernels (the
    HighwayHash batch): B spreads over EVERY mesh axis when divisible,
    remaining dims replicated."""
    if B % mesh.size == 0:
        return NamedSharding(
            mesh, P(tuple(mesh.axis_names), *([None] * (ndim - 1))))
    return NamedSharding(mesh, P())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
