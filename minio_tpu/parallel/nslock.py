"""Namespace locks: per-(bucket, object) RW locks.

Single-node: in-process reader/writer locks (ref pkg/lsync +
cmd/namespace-lock.go:276). Distributed: the same interface backed by
dsync quorum locks over the lock RPC (rpc/locks.py).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class _RWLock:
    """Writer-preferring reader/writer lock."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self, timeout: float | None = None) -> bool:
        with self._cond:
            def ready():
                return not self._writer and self._writers_waiting == 0
            if not self._cond.wait_for(ready, timeout):
                return False
            self._readers += 1
            return True

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self, timeout: float | None = None) -> bool:
        with self._cond:
            self._writers_waiting += 1
            try:
                def ready():
                    return not self._writer and self._readers == 0
                if not self._cond.wait_for(ready, timeout):
                    return False
                self._writer = True
                return True
            finally:
                self._writers_waiting -= 1

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    def idle(self) -> bool:
        return (not self._writer and self._readers == 0
                and self._writers_waiting == 0)


class LocalNSLock:
    """In-process namespace lock registry (ref nsLockMap,
    cmd/namespace-lock.go). Entries are reference-counted so a lock
    object handed to a waiter is never GC'd out from under it (the
    ref/waiter count is the reference's nsLock ref counter)."""

    def __init__(self):
        self._mu = threading.Lock()
        # key -> [lock, refcount]
        self._locks: dict[tuple[str, str], list] = {}

    def _get(self, bucket: str, obj: str) -> _RWLock:
        with self._mu:
            key = (bucket, obj)
            ent = self._locks.get(key)
            if ent is None:
                ent = [_RWLock(), 0]
                self._locks[key] = ent
            ent[1] += 1
            return ent[0]

    def _put(self, bucket: str, obj: str) -> None:
        with self._mu:
            key = (bucket, obj)
            ent = self._locks.get(key)
            if ent is None:
                return
            ent[1] -= 1
            if ent[1] <= 0 and ent[0].idle():
                del self._locks[key]

    @contextmanager
    def write_locked(self, bucket: str, obj: str,
                     timeout: float | None = 30.0):
        lk = self._get(bucket, obj)
        try:
            if not lk.acquire_write(timeout):
                raise TimeoutError(f"write lock timeout: {bucket}/{obj}")
            try:
                yield
            finally:
                lk.release_write()
        finally:
            self._put(bucket, obj)

    @contextmanager
    def read_locked(self, bucket: str, obj: str,
                    timeout: float | None = 30.0):
        lk = self._get(bucket, obj)
        try:
            if not lk.acquire_read(timeout):
                raise TimeoutError(f"read lock timeout: {bucket}/{obj}")
            try:
                yield
            finally:
                lk.release_read()
        finally:
            self._put(bucket, obj)
