"""Host-side quorum parallelism: thread-pool fan-out over disks with the
reference's quorum-reduction semantics (ref cmd/erasure-metadata-utils.go
reduceErrs, cmd/erasure-encode.go parallelWriter, pkg/dsync quorum math).
"""

from __future__ import annotations

import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Sequence

# One process-wide pool shared by every quorum fan-out. Round-4 verdict
# weak #3 (PutObject p50): the old per-call `with ThreadPoolExecutor()`
# spawned AND joined ~4 fresh threads per disk fan-out — three fan-outs
# per PUT made thread churn ~40% of the request. Idle pool threads cost
# nothing; the pool grows lazily up to the cap.
_POOL: ThreadPoolExecutor | None = None
_POOL_LOCK = threading.Lock()
_POOL_WORKERS = 256
# Borrowed-worker accounting: submits beyond the pool's capacity run
# INLINE instead of queueing, so nested blocking fan-outs can never
# deadlock on a saturated pool (a queued thunk whose parent holds a
# worker would otherwise wait forever). The count is exact for
# parallel_map (decremented in-band) and callback-driven for submit().
_ACTIVE = 0


def _pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                _POOL = ThreadPoolExecutor(
                    max_workers=_POOL_WORKERS,
                    thread_name_prefix="quorum")
    return _POOL


def _borrow(want: int) -> int:
    """Reserve up to `want` pool workers; returns how many granted."""
    global _ACTIVE
    with _POOL_LOCK:
        grant = max(0, min(want, _POOL_WORKERS - _ACTIVE))
        _ACTIVE += grant
    return grant


def _release(n: int) -> None:
    global _ACTIVE
    if n:
        with _POOL_LOCK:
            _ACTIVE -= n


def stats() -> dict:
    """Fan-out pool census for the bench/observability surfaces:
    workers the pool may park vs how many a fan-out holds RIGHT NOW —
    the threaded half of the thread-vs-inflight comparison the async
    RPC fabric is measured against (rpc/aio.py census)."""
    with _POOL_LOCK:
        active = _ACTIVE
        started = len(getattr(_POOL, "_threads", ())) if _POOL else 0
    return {"active": active, "started": started,
            "workers": _POOL_WORKERS,
            "processThreads": threading.active_count()}


class QuorumError(Exception):
    """Not enough disks agreed/succeeded."""

    def __init__(self, message: str, errs: list[BaseException | None]):
        super().__init__(message)
        self.errs = errs


def hash_order(key: str, cardinality: int) -> list[int]:
    """Deterministic shard distribution for an object key: a rotation of
    1..n starting at crc32(key) % n (ref hashOrder,
    cmd/erasure-metadata-utils.go)."""
    if cardinality <= 0:
        return []
    start = zlib.crc32(key.encode("utf-8")) % cardinality
    # 1-based, starting at start+1 (ref loop i=1..n: 1 + (start+i) % n).
    return [1 + (start + i) % cardinality for i in range(1, cardinality + 1)]


import os as _os

# CPU-bound overlap only pays when there is a second core to run it on
# (GIL-released C work still needs a CPU); on 1-core hosts the pool
# dispatch is pure overhead.
MULTICORE = (_os.cpu_count() or 1) > 1

# Flipped to True the moment a RemoteStorage is constructed: network
# round-trips must overlap even on one core, while an all-local
# single-core node (the bench box) measurably prefers inline fan-outs
# (~4.5ms off a 1MiB PUT p50 — thread dispatch on one CPU is pure
# queueing).
FORCE_THREADS = False


def _qos_ctx_wrap(fn: Callable) -> Callable:
    """Carry the caller's QoS context — request deadline and dispatch
    lane — onto pool workers. Contextvars do not cross threads, so
    without this a shard fan-out would run deadline-UNCAPPED remote
    I/O (and heal's fan-outs would lose their background tag) — the
    same cross-thread gap obs spans close by explicit parent passing.
    Delegates to the canonical helper (qos/ctx.py, promoted from here
    once lint rule R1 started requiring it at every thread hop);
    imported lazily because parallel/ loads before qos/."""
    from ..qos.ctx import ctx_wrap
    return ctx_wrap(fn)


def submit(fn: Callable[..., Any], *args) -> Any:
    """Run one callable on the shared pool; returns its Future (or a
    pre-completed one, executed inline, when the pool is saturated).
    For overlapping an independent CPU task (e.g. the etag md5, which
    releases the GIL on >2KiB buffers) with work on the caller
    thread. Callers should check MULTICORE first for CPU-bound work."""
    from concurrent.futures import Future
    if _borrow(1) == 0:
        fut: Future = Future()
        try:
            fut.set_result(fn(*args))
        except BaseException as e:  # noqa: BLE001 — surfaced by result()
            fut.set_exception(e)
        return fut
    f = _pool().submit(_qos_ctx_wrap(fn), *args)
    f.add_done_callback(lambda _f: _release(1))
    return f


def first_success(fns: Sequence[Callable[[], Any]],
                  swallow: type | tuple = Exception) -> Any:
    """Race thunks on the shared pool; return the FIRST successful
    result. Unlike parallel_map this never waits for the slowest thunk
    — stragglers finish on the pool and are discarded. Thunks that
    could not get a pool worker run inline with serial EARLY-EXIT (the
    pre-parallel walk): under pool saturation a dead disk behind a
    healthy one still costs nothing. Exceptions not matching `swallow`
    propagate immediately; when every thunk fails, QuorumError carries
    the swallowed errors."""
    from concurrent.futures import FIRST_COMPLETED, wait
    errs: list[BaseException] = []
    futs = set()
    inline = []
    for fn in fns:
        if _borrow(1):
            f = _pool().submit(_qos_ctx_wrap(fn))
            f.add_done_callback(lambda _f: _release(1))
            futs.add(f)
        else:
            inline.append(fn)
    while futs:
        done, futs = wait(futs, return_when=FIRST_COMPLETED)
        for fut in done:
            try:
                return fut.result()
            except swallow as e:  # noqa: PERF203 — reduced below
                errs.append(e)
    for fn in inline:
        try:
            return fn()
        except swallow as e:
            errs.append(e)
    raise QuorumError(
        f"first_success: all {len(fns)} candidates failed", errs)


def parallel_map(fns: Sequence[Callable[[], Any]],
                 ) -> tuple[list[Any], list[BaseException | None]]:
    """Run thunks concurrently; returns (results, errs) aligned by index.
    A thunk that raises contributes (None, exception).

    The LAST thunk always runs inline on the calling thread (the
    single-thunk case is pool-free), and when the pool is saturated the
    OVERFLOW thunks run inline too (_borrow) — together these make
    nested blocking fan-outs (pools → sets → disks, heal inside
    sweeps) deadlock-free on the bounded shared pool: no thunk ever
    waits in the queue behind a caller that is itself blocked.

    Fan-outs are inline-sequential on a single-core all-local process
    (see FORCE_THREADS above): with no second CPU and no network wait
    to overlap, threads only add dispatch latency."""
    results: list[Any] = [None] * len(fns)
    errs: list[BaseException | None] = [None] * len(fns)
    if not fns:
        return results, errs

    def run_inline(i: int, fn) -> None:
        try:
            results[i] = fn()
        except BaseException as e:  # noqa: BLE001 — collected, reduced
            errs[i] = e

    futures = {}
    granted = 0
    if len(fns) > 1 and (MULTICORE or FORCE_THREADS):
        granted = _borrow(len(fns) - 1)
        pool = _pool()
        futures = {pool.submit(_qos_ctx_wrap(fn)): i for i, fn in
                   enumerate(fns[:granted])}
        for i, fn in enumerate(fns[granted:-1]):
            run_inline(granted + i, fn)
    elif len(fns) > 1:
        for i, fn in enumerate(fns[:-1]):
            run_inline(i, fn)
    run_inline(len(fns) - 1, fns[-1])
    for fut, i in futures.items():
        try:
            results[i] = fut.result()
        except BaseException as e:  # noqa: BLE001 — collected, reduced
            errs[i] = e
    _release(granted)
    return results, errs


def count_errs(errs: Sequence[BaseException | None]) -> int:
    return sum(1 for e in errs if e is not None)


def reduce_quorum_errs(errs: Sequence[BaseException | None],
                       quorum: int, op: str) -> None:
    """Raise QuorumError unless at least `quorum` entries succeeded
    (ref reduceWriteQuorumErrs / reduceReadQuorumErrs)."""
    ok = len(errs) - count_errs(errs)
    if ok < quorum:
        detail = "; ".join(
            f"disk{i}: {type(e).__name__}: {e}"
            for i, e in enumerate(errs) if e is not None)
        raise QuorumError(
            f"{op}: quorum not met ({ok}/{len(errs)} ok, need {quorum}): "
            f"{detail}", list(errs))


def write_quorum(data_blocks: int, parity_blocks: int) -> int:
    """Write quorum: k, +1 when k == m (ref cmd/erasure-object.go:604-608)."""
    q = data_blocks
    if data_blocks == parity_blocks:
        q += 1
    return q


def read_quorum(data_blocks: int) -> int:
    """Read quorum: k (ref cmd/erasure-object.go getReadQuorum)."""
    return data_blocks
