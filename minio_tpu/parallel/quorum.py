"""Host-side quorum parallelism: thread-pool fan-out over disks with the
reference's quorum-reduction semantics (ref cmd/erasure-metadata-utils.go
reduceErrs, cmd/erasure-encode.go parallelWriter, pkg/dsync quorum math).
"""

from __future__ import annotations

import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Sequence


class QuorumError(Exception):
    """Not enough disks agreed/succeeded."""

    def __init__(self, message: str, errs: list[BaseException | None]):
        super().__init__(message)
        self.errs = errs


def hash_order(key: str, cardinality: int) -> list[int]:
    """Deterministic shard distribution for an object key: a rotation of
    1..n starting at crc32(key) % n (ref hashOrder,
    cmd/erasure-metadata-utils.go)."""
    if cardinality <= 0:
        return []
    start = zlib.crc32(key.encode("utf-8")) % cardinality
    # 1-based, starting at start+1 (ref loop i=1..n: 1 + (start+i) % n).
    return [1 + (start + i) % cardinality for i in range(1, cardinality + 1)]


def parallel_map(fns: Sequence[Callable[[], Any]],
                 ) -> tuple[list[Any], list[BaseException | None]]:
    """Run thunks concurrently; returns (results, errs) aligned by index.
    A thunk that raises contributes (None, exception)."""
    results: list[Any] = [None] * len(fns)
    errs: list[BaseException | None] = [None] * len(fns)
    if not fns:
        return results, errs
    with ThreadPoolExecutor(max_workers=max(1, len(fns))) as pool:
        futures = {pool.submit(fn): i for i, fn in enumerate(fns)}
        for fut, i in futures.items():
            try:
                results[i] = fut.result()
            except BaseException as e:  # noqa: BLE001 — collected, reduced
                errs[i] = e
    return results, errs


def count_errs(errs: Sequence[BaseException | None]) -> int:
    return sum(1 for e in errs if e is not None)


def reduce_quorum_errs(errs: Sequence[BaseException | None],
                       quorum: int, op: str) -> None:
    """Raise QuorumError unless at least `quorum` entries succeeded
    (ref reduceWriteQuorumErrs / reduceReadQuorumErrs)."""
    ok = len(errs) - count_errs(errs)
    if ok < quorum:
        detail = "; ".join(
            f"disk{i}: {type(e).__name__}: {e}"
            for i, e in enumerate(errs) if e is not None)
        raise QuorumError(
            f"{op}: quorum not met ({ok}/{len(errs)} ok, need {quorum}): "
            f"{detail}", list(errs))


def write_quorum(data_blocks: int, parity_blocks: int) -> int:
    """Write quorum: k, +1 when k == m (ref cmd/erasure-object.go:604-608)."""
    q = data_blocks
    if data_blocks == parity_blocks:
        q += 1
    return q


def read_quorum(data_blocks: int) -> int:
    """Read quorum: k (ref cmd/erasure-object.go getReadQuorum)."""
    return data_blocks
