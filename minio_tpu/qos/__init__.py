"""QoS subsystem: admission control, deadline propagation, and
priority-aware TPU dispatch.

Three cooperating pieces, wired through the whole stack:

- ``admission``: per-API-class (read/write/list/admin) concurrency caps
  with a bounded FIFO wait queue — the analog of the reference's
  maxClients middleware (`MINIO_API_REQUESTS_MAX` /
  `MINIO_API_REQUESTS_DEADLINE`, cmd/generic-handlers.go) extended with
  per-class overrides so a write flood cannot starve reads.
- ``deadline``: a per-request time budget opened at the S3 handler and
  propagated as an ``x-mtpu-deadline-ms`` header across storage/peer
  RPC, so a nearly-expired request cancels remote shard I/O instead of
  burning peer capacity.
- ``scheduler``: two-priority dispatch lanes for the batching layer —
  background heal/crawler/scanner kernel work yields the coalescing
  window to foreground encode/verify, with aging so background is
  deferred, never starved (the foreground/background interference that
  online-EC studies identify as the dominant tail-latency source,
  arXiv:1709.05365; RapidRAID pipelines repair off the critical path,
  arXiv:1207.6744).
"""

from . import admission, ctx, deadline, scheduler  # noqa: F401
