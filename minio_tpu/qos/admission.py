"""Admission control: per-API-class concurrency caps with bounded FIFO
wait queues (ref the maxClients middleware + `MINIO_API_REQUESTS_MAX` /
`MINIO_API_REQUESTS_DEADLINE`, cmd/generic-handlers.go — extended with
per-class read/write/list/admin caps so one flooded class cannot starve
the others).

Semantics:
- a GLOBAL cap (`api.requests_max`) bounds total in-flight S3 work;
- per-class caps (`api.requests_max_<class>`) bound each class;
- 0 anywhere = unlimited (in-flight is still tracked for metrics and
  for the scheduler's foreground-busy probe);
- over-cap requests wait FIFO up to the request's remaining deadline
  budget, then shed with 503 SlowDown + Retry-After;
- the wait queue itself is bounded (QUEUE_FACTOR x cap): when it is
  full the request sheds immediately — queueing unboundedly under
  overload is the exact failure admission control exists to prevent.

All caps reconfigure live through the config-KV apply hook
(S3Server._apply_config); waiters re-evaluate on every change.
"""

from __future__ import annotations

import collections
import threading
import time

from .deadline import Deadline

API_CLASSES = ("read", "write", "list", "admin", "select")

# Bounded wait queue: at most this many waiters per enforced cap slot.
QUEUE_FACTOR = 4

# Retry-After ceiling (seconds) — clients should back off for about the
# wait budget they'd otherwise have burned, never for minutes.
MAX_RETRY_AFTER = 120


class AdmissionShed(Exception):
    """Request refused by admission control (maps to 503 SlowDown)."""

    def __init__(self, api_class: str, reason: str, retry_after: int):
        super().__init__(f"admission shed ({api_class}): {reason}")
        self.api_class = api_class
        self.reason = reason
        self.retry_after = retry_after


def classify(method: str, bucket: str, key: str,
             params=()) -> str:
    """Map a request shape to its admission class (the coarse read /
    write / list / admin / select split the caps are keyed by).
    SelectObjectContent gets its OWN class: an analytics sweep is
    CPU/kernel-bound scan work, and a dedicated cap
    (`api.requests_max_select`) lets an operator brown it out without
    touching PUT/GET capacity."""
    if key and method == "POST" and "select" in params:
        return "select"
    if key:
        return "read" if method in ("GET", "HEAD") else "write"
    if bucket:
        return "list" if method in ("GET", "HEAD") else "write"
    return "list" if method in ("GET", "HEAD") else "admin"


class _Gate:
    """One FIFO-fair concurrency gate. limit <= 0 admits everything but
    still tracks in-flight."""

    def __init__(self, name: str):
        self.name = name
        self._cv = threading.Condition(threading.Lock())
        self.limit = 0
        self.inflight = 0
        self._queue: collections.deque = collections.deque()

    def set_limit(self, limit: int) -> None:
        with self._cv:
            self.limit = max(0, int(limit))
            self._cv.notify_all()  # a raised cap admits waiters now

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def acquire(self, deadline: Deadline | None) -> None:
        """Admit, or wait FIFO until admitted / the deadline expires /
        the queue is full. Raises AdmissionShed (reason tagged)."""
        me = object()
        with self._cv:
            if self.limit <= 0 or (self.inflight < self.limit
                                   and not self._queue):
                self.inflight += 1
                return
            if len(self._queue) >= self.limit * QUEUE_FACTOR:
                raise AdmissionShed(self.name, "queue-full",
                                    _retry_after(deadline))
            self._queue.append(me)
            try:
                while True:
                    if self.limit <= 0 or (self.inflight < self.limit
                                           and self._queue[0] is me):
                        self._queue.remove(me)
                        self.inflight += 1
                        # Wake the next waiter: one event can admit
                        # MANY (a live cap raise) — without this only
                        # the head would notice until the next release.
                        self._cv.notify_all()
                        return
                    wait = deadline.remaining() if deadline else None
                    if wait is not None and wait <= 0:
                        self._queue.remove(me)
                        self._cv.notify_all()
                        raise AdmissionShed(self.name, "wait-deadline",
                                            _retry_after(deadline))
                    self._cv.wait(wait)
            except AdmissionShed:
                raise
            except BaseException:
                try:
                    self._queue.remove(me)
                except ValueError:
                    pass
                self._cv.notify_all()
                raise

    def release(self) -> None:
        with self._cv:
            self.inflight = max(0, self.inflight - 1)
            self._cv.notify_all()


def _retry_after(deadline: Deadline | None) -> int:
    budget = deadline.budget_s if deadline is not None else 1.0
    return max(1, min(MAX_RETRY_AFTER, int(round(budget))))


class AdmissionController:
    """The server-wide gate set: one global + one per API class."""

    def __init__(self):
        self._global = _Gate("global")
        self._classes = {c: _Gate(c) for c in API_CLASSES}
        self.deadline_s = 10.0  # api.requests_deadline (wait + request)
        # monotonic() of the last foreground release: closed-loop
        # clients leave instantaneous in-flight gaps between requests;
        # the scheduler's throttle probe treats "active within a small
        # window" as busy so sweeps don't slip into those gaps.
        self._last_fg_release = 0.0

    # -- live (re)configuration ---------------------------------------

    def configure(self, requests_max: int, per_class: dict[str, int],
                  deadline_s: float) -> None:
        """Apply config-KV values; waiters react immediately."""
        self._global.set_limit(requests_max)
        for c, gate in self._classes.items():
            gate.set_limit(per_class.get(c, 0))
        self.deadline_s = max(0.0, deadline_s)

    def limit_for(self, api_class: str) -> int:
        return self._classes[api_class].limit

    @property
    def engaged(self) -> bool:
        """True when any cap is configured. The request-EXECUTION
        deadline budget only bites on an engaged (operator-configured)
        system: with no caps, requests_deadline keeps its reference
        semantics (a wait budget that never applies) and long requests
        run uncapped exactly as before — a default-config server must
        not start quorum-committing partial writes under load just
        because a 10s default exists."""
        return (self._global.limit > 0
                or any(g.limit > 0 for g in self._classes.values()))

    def foreground_inflight(self) -> int:
        """Client-facing in-flight work (read/write/list) — the
        scheduler's foreground-busy probe; admin traffic is not
        latency-sensitive foreground load, and neither are `select`
        scans — their kernel dispatches run BACKGROUND-lane and must
        not count themselves as the foreground they defer to."""
        return sum(self._classes[c].inflight
                   for c in ("read", "write", "list"))

    def foreground_active(self, window_s: float = 0.0) -> bool:
        """In-flight now, or released within the last `window_s` (the
        sticky probe the sweep throttle uses)."""
        if self.foreground_inflight() > 0:
            return True
        return (window_s > 0
                and time.monotonic() - self._last_fg_release < window_s)

    # -- admission -----------------------------------------------------

    def acquire(self, api_class: str,
                deadline: Deadline | None = None) -> "_Admitted":
        """Context manager guarding one request; raises AdmissionShed
        with Retry-After when over cap past the wait budget."""
        gate = self._classes[api_class]
        t0 = time.perf_counter()
        try:
            # CLASS gate first: a request queued behind its class cap
            # must not sit on a global slot meanwhile — that would let
            # one flooded class eat global capacity with requests that
            # are not even running, starving the other classes.
            gate.acquire(deadline)
            try:
                self._global.acquire(deadline)
            except BaseException:
                gate.release()
                raise
        except AdmissionShed as shed:
            self._record_shed(api_class, shed.reason)
            raise
        finally:
            self._observe(api_class, gate,
                          (time.perf_counter() - t0) * 1e3)
        return _Admitted(self, api_class)

    def _release(self, api_class: str) -> None:
        self._classes[api_class].release()
        self._global.release()
        if api_class in ("read", "write", "list"):
            self._last_fg_release = time.monotonic()
        self._observe(api_class, self._classes[api_class], None)

    # -- accounting ----------------------------------------------------

    def _observe(self, api_class: str, gate: _Gate,
                 wait_ms: float | None) -> None:
        from ..obs.metrics2 import METRICS2
        labels = {"class": api_class}
        METRICS2.set_gauge("minio_tpu_v2_qos_admission_inflight",
                           labels, gate.inflight)
        METRICS2.set_gauge("minio_tpu_v2_qos_admission_queue_depth",
                           labels, gate.queue_depth())
        if wait_ms is not None:
            METRICS2.observe("minio_tpu_v2_qos_admission_wait_ms",
                             labels, wait_ms)

    def _record_shed(self, api_class: str, reason: str) -> None:
        from ..obs.metrics2 import METRICS2
        from ..obs.span import current_span
        METRICS2.inc("minio_tpu_v2_qos_shed_total",
                     {"class": api_class, "reason": reason})
        span = current_span()
        if span is not None:
            span.add_event("qos.shed", api_class=api_class,
                           reason=reason)


class _Admitted:
    """Held admission slot; releases on context exit (idempotent —
    streaming responses release from the request-finish path, which
    also runs as a safety net)."""

    __slots__ = ("_ctrl", "_api_class", "_released")

    def __init__(self, ctrl: AdmissionController, api_class: str):
        self._ctrl = ctrl
        self._api_class = api_class
        self._released = False

    def __enter__(self) -> "_Admitted":
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ctrl._release(self._api_class)
