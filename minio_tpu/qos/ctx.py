"""The canonical thread-boundary QoS context carrier.

Deadlines (``qos.deadline``) and dispatch lanes (``qos.scheduler``)
live in contextvars, which do NOT cross threads: any
``Thread(target=...)`` or executor ``submit`` on a request path would
silently run deadline-uncapped and lane-untagged on the far side of
the hop. ``ctx_wrap`` captures both on the calling thread and re-enters
them around the callable on the worker.

This used to live as ``parallel/quorum._qos_ctx_wrap`` (grown for the
quorum pool in PR 2's post-review hardening) with an ad-hoc copy in
``utils/pipeline.Prefetch``; it is promoted here — and both call sites
now delegate — because lint rule R1 (tools/mtpu_lint) REQUIRES every
thread hop inside ``minio_tpu/`` to route through it: one helper, one
name the AST rule can see.
"""

from __future__ import annotations

from typing import Callable

from . import deadline as _dl
from . import scheduler as _sched


def ctx_wrap(fn: Callable) -> Callable:
    """Carry the caller's QoS context — request deadline and dispatch
    lane — onto whatever thread eventually runs ``fn``. Returns ``fn``
    unchanged on the default context (no wrap overhead)."""
    ddl = _dl.current_deadline()
    lane = _sched.current_lane()
    if ddl is None and lane == _sched.FOREGROUND:
        return fn

    def wrapped(*a, **kw):
        with _dl.deadline_scope(ddl), _sched.lane_scope(lane):
            return fn(*a, **kw)
    return wrapped
