"""Per-request deadline budgets (ref the reference's
`MINIO_API_REQUESTS_DEADLINE` + context deadlines threaded through its
storage REST client, cmd/storage-rest-client.go).

A ``Deadline`` is an absolute expiry opened at the S3 handler from
`api.requests_deadline`; every phase below shares it through a
contextvar, so the budget decrements naturally as phases consume wall
time. RPC clients forward the REMAINING budget as an
``x-mtpu-deadline-ms`` header and cap their socket timeout to it; the
RPC server refuses already-expired work outright — a request that can
no longer answer its client must not keep burning peer capacity.
"""

from __future__ import annotations

import contextlib
import contextvars
import time

# Remaining-budget header on internal RPC (milliseconds, float ok).
H_DEADLINE = "x-mtpu-deadline-ms"

_current: contextvars.ContextVar["Deadline | None"] = \
    contextvars.ContextVar("minio_tpu_deadline", default=None)


class DeadlineExceeded(TimeoutError):
    """The request's time budget ran out (maps to 503 RequestTimeout
    at the S3 boundary; a named wire error across RPC)."""


class Deadline:
    """An absolute expiry on the monotonic clock."""

    __slots__ = ("expires_at", "budget_s")

    def __init__(self, budget_s: float):
        self.budget_s = budget_s
        self.expires_at = time.monotonic() + budget_s

    @classmethod
    def from_remaining_ms(cls, ms: float) -> "Deadline":
        return cls(ms / 1e3)

    def remaining(self) -> float:
        """Seconds left; <= 0 when expired."""
        return self.expires_at - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining() * 1e3

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, where: str = "") -> None:
        """Raise DeadlineExceeded (recording the event) when expired."""
        if self.expired():
            record_expiry(where)
            raise DeadlineExceeded(
                f"request deadline exceeded ({where or 'unspecified'}, "
                f"budget {self.budget_s:.3f}s)")


def current_deadline() -> Deadline | None:
    return _current.get()


@contextlib.contextmanager
def deadline_scope(dl: Deadline | None):
    """Make `dl` the context's deadline (None clears — background work
    spawned from a request must not inherit the request's budget)."""
    token = _current.set(dl)
    try:
        yield dl
    finally:
        _current.reset(token)


def open_deadline(budget_s: float):
    """Scope a fresh budget; budget <= 0 means no deadline."""
    return deadline_scope(Deadline(budget_s) if budget_s > 0 else None)


def record_expiry(where: str) -> None:
    """Account a deadline expiry: metrics counter + a span event on the
    request's trace tree (PR-1 observability contract)."""
    from ..obs.metrics2 import METRICS2
    from ..obs.span import current_span
    METRICS2.inc("minio_tpu_v2_qos_deadline_expired_total",
                 {"where": where or "unspecified"})
    span = current_span()
    if span is not None:
        span.add_event("qos.deadline_expired", where=where)


def parse_duration(raw: str) -> float:
    """'250ms' / '10s' / '1m' / bare seconds -> seconds (the config-KV
    duration syntax the reference accepts for requests_deadline)."""
    s = raw.strip().lower()
    if not s:
        return 0.0
    for suffix, mult in (("ms", 1e-3), ("s", 1.0), ("m", 60.0),
                         ("h", 3600.0)):
        if s.endswith(suffix) and s[: -len(suffix)]:
            try:
                return float(s[: -len(suffix)]) * mult
            except ValueError:
                break
    return float(s)
