"""Two-priority dispatch lanes for the batching layer.

Background kernel work (heal re-encode, crawler/scanner verify sweeps)
competes with foreground PUT/GET encode for the same coalescing window
and device queue — the foreground/background interference online-EC
studies flag as the dominant tail-latency source (arXiv:1709.05365;
RapidRAID pipelines repair off the critical path, arXiv:1207.6744).

The lane rides a contextvar: heal/crawler call sites wrap their work in
``background_lane()`` and every dispatch in ops/batching.py consults
``GATE.dispatch(current_lane())``. Background dispatches defer while
foreground work is busy — busy meaning a foreground dispatch is in
flight OR the admission controller reports client requests in flight —
re-checking each ``DEFER_SLICE_S``; after ``MAX_DEFERRALS`` slices the
dispatch PROMOTES and proceeds anyway (aging: deferred, never starved).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import weakref

FOREGROUND = "fg"
BACKGROUND = "bg"

_lane: contextvars.ContextVar[str] = contextvars.ContextVar(
    "minio_tpu_qos_lane", default=FOREGROUND)


def current_lane() -> str:
    return _lane.get()


@contextlib.contextmanager
def background_lane():
    """Tag everything in this scope (heal sweep, crawler cycle) as
    background for dispatch priority."""
    token = _lane.set(BACKGROUND)
    try:
        yield
    finally:
        _lane.reset(token)


@contextlib.contextmanager
def lane_scope(lane: str):
    """Re-enter a captured lane on another thread (the quorum pool's
    cross-thread QoS-context hand-off, parallel/quorum.py)."""
    token = _lane.set(lane)
    try:
        yield
    finally:
        _lane.reset(token)


class PriorityGate:
    """Foreground-first dispatch gate with background aging."""

    # One deferral slice ~= a few coalescing windows; MAX_DEFERRALS
    # slices bound background added latency to ~tens of ms per dispatch.
    DEFER_SLICE_S = 0.01
    MAX_DEFERRALS = 4

    # Loop pacing (throttle_background): a background sweep yields
    # between WORK ITEMS while foreground is busy — the dominant
    # interference is the sweep's I/O+hash work, not its kernel
    # dispatches (ref waitForLowHTTPReq + dynamicSleeper,
    # cmd/data-crawler.go: the reference sleeps background ops
    # proportionally to their own cost while client requests are in
    # flight). The wait is THROTTLE_FACTOR x the caller's last item
    # cost (duty cycle ~1/(1+factor) under constant load), capped at
    # THROTTLE_MAX_WAIT_S — the aging bound that keeps one item
    # flowing even under permanent foreground pressure.
    THROTTLE_SLICE_S = 0.02
    THROTTLE_MAX_WAIT_S = 1.0
    THROTTLE_FACTOR = 10.0
    THROTTLE_DEFAULT_COST_S = 0.05
    # Sticky window for the THROTTLE probe only: closed-loop clients
    # leave sub-ms in-flight gaps between requests; "released within
    # this window" still counts as busy so sweeps don't slip through.
    FG_RECENT_S = 0.25

    def __init__(self):
        self._cv = threading.Condition(threading.Lock())
        self._fg_inflight = 0
        # Admission controllers (weakly held — test suites create many
        # short-lived servers): their foreground in-flight counts also
        # mean "busy", so host-only deployments (no shared device
        # queue) still keep heal out of the serving path's way.
        self._controllers: list = []

    def register(self, controller) -> None:
        """Weakly register an AdmissionController as a busy source."""
        with self._cv:
            self._controllers.append(weakref.ref(controller))

    def _fg_busy(self, recent_window_s: float = 0.0) -> bool:
        """Foreground dispatch in flight, or client requests in flight
        on any registered admission controller (optionally sticky:
        active within `recent_window_s`)."""
        if self._fg_inflight > 0:
            return True
        dead = False
        for ref in self._controllers:
            ctrl = ref()
            if ctrl is None:
                dead = True
                continue
            try:
                if ctrl.foreground_active(recent_window_s):
                    return True
            except Exception:
                continue
        if dead:
            self._controllers = [r for r in self._controllers
                                 if r() is not None]
        return False

    @contextlib.contextmanager
    def dispatch(self, lane: str):
        """Scope one batching dispatch. Foreground registers busy;
        background defers while foreground is busy, promoting after
        MAX_DEFERRALS slices."""
        from ..obs.metrics2 import METRICS2
        if lane != BACKGROUND:
            with self._cv:
                self._fg_inflight += 1
            METRICS2.inc("minio_tpu_v2_qos_dispatch_total",
                         {"lane": FOREGROUND})
            try:
                yield
            finally:
                with self._cv:
                    self._fg_inflight -= 1
                    self._cv.notify_all()
            return
        deferrals = 0
        with self._cv:
            while self._fg_busy() and deferrals < self.MAX_DEFERRALS:
                deferrals += 1
                METRICS2.inc("minio_tpu_v2_qos_bg_deferrals_total")
                self._cv.wait(self.DEFER_SLICE_S)
            promoted = self._fg_busy()
        if promoted:
            METRICS2.inc("minio_tpu_v2_qos_bg_promotions_total")
        METRICS2.inc("minio_tpu_v2_qos_dispatch_total",
                     {"lane": BACKGROUND})
        yield

    def throttle_background(self, cost_s: float | None = None) -> float:
        """Pace a background LOOP: called between per-object heal /
        crawl steps, sleeps in slices while foreground is busy, for up
        to THROTTLE_FACTOR x `cost_s` (the last item's own duration),
        aging-capped at THROTTLE_MAX_WAIT_S. Returns seconds waited.
        No-op outside the background lane or with foreground idle
        (cheap enough to call unconditionally)."""
        if _lane.get() != BACKGROUND:
            return 0.0
        if cost_s is None:
            cost_s = self.THROTTLE_DEFAULT_COST_S
        bound = min(self.THROTTLE_MAX_WAIT_S,
                    self.THROTTLE_FACTOR * max(cost_s, 0.0))
        from ..obs.metrics2 import METRICS2
        waited = 0.0
        with self._cv:
            if not self._fg_busy(self.FG_RECENT_S):
                return 0.0
            while self._fg_busy(self.FG_RECENT_S) and waited < bound:
                METRICS2.inc("minio_tpu_v2_qos_bg_deferrals_total")
                t0 = time.monotonic()
                self._cv.wait(self.THROTTLE_SLICE_S)
                waited += time.monotonic() - t0
            promoted = self._fg_busy(self.FG_RECENT_S)
        if promoted:
            METRICS2.inc("minio_tpu_v2_qos_bg_promotions_total")
        return waited


# Process-wide gate shared by every batching dispatch.
GATE = PriorityGate()
