"""Cluster RPC fabric: storage REST (remote StorageAPI), dsync lock
service, peer control plane — HTTP/1.1 with HMAC node auth, one port per
node alongside the S3 API (ref cmd/routers.go:26-37 internal routers,
cmd/storage-rest-server.go, pkg/dsync)."""
