"""Async peer-RPC fabric: every internal hop on ONE event loop.

The PR-11 front door put client serving on an event loop, but each
in-flight peer call still parked a thread inside the pooled
``http.client`` transport — a k+m shard fan-out on a 16-node cluster
cost a fleet of blocked threads exactly where the distributed layer
must scale. This module moves the CLIENT side of the RPC plane onto
asyncio:

- one process-wide daemon event-loop thread (``RPC_LOOP``) owns every
  outbound peer connection; sync call sites bridge onto it with
  ``run_coroutine_threadsafe`` and block on a future — the calling
  thread waits, but no NEW thread exists per in-flight call;
- ``call_async`` replicates ``RPCClient.call`` semantics exactly
  (offline gate + jittered reconnect probe, fault injection, deadline
  fast-fail/capping, self-tuning timeout bookkeeping, the single-shot
  stale-pool retry, control-plane overrides, trace-span grafting) so
  behaviour cannot drift between the fabrics;
- ``fanout``/``fanout_nowait`` run N-peer pushes as N coroutines on
  the one loop (``rpc/peer.py`` previously spawned a thread per peer);
- ``Pipeline`` issues HTTP/1.1 pipelined requests on one dedicated
  connection — ``RemoteStorage.create_file`` streams chunk frames
  without a per-chunk round-trip stall.

The legacy threaded transport stays fully functional behind
``MINIO_RPC_FABRIC=threaded`` (the paired-bench / escape-hatch knob,
mirroring ``MINIO_FRONT_DOOR``).

Thread-model invariant: the per-client async connection pool is only
ever touched FROM the RPC loop thread, so it needs no lock. Cross-
thread entry points (``bridge_call``, ``fanout``, ``Pipeline``,
``close_client``) submit coroutines; they never touch pool state
directly.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time

from ..qos.deadline import (H_DEADLINE, DeadlineExceeded, current_deadline,
                            record_expiry)
from ..storage import errors as serr
from .transport import RPC_PREFIX, RPCClient, frame, sign, unframe, \
    wire_to_error

# Pooled keep-alive connections kept per peer (matches the sync pool).
POOL_SIZE = 8
# In-flight pipelined requests per Pipeline before send() blocks on
# the oldest response (bounds peer-side queueing and sender memory).
PIPELINE_WINDOW = 4


def fabric_async() -> bool:
    """Env knob: MINIO_RPC_FABRIC=threaded keeps the legacy pooled
    http.client transport (paired benches; emergency escape hatch)."""
    import os
    return os.environ.get("MINIO_RPC_FABRIC",
                          "async").strip().lower() != "threaded"


# ---------------------------------------------------------------------------
# The loop thread


class _LoopThread:
    """Lazily-started process-wide event loop on one daemon thread."""

    def __init__(self, name: str = "mtpu-rpc-loop"):
        self._name = name
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._mu = threading.Lock()

    def loop(self) -> asyncio.AbstractEventLoop:
        with self._mu:
            if (self._loop is None or self._loop.is_closed()
                    or self._thread is None or not self._thread.is_alive()):
                loop = asyncio.new_event_loop()
                # mtpu-lint: disable=R1 -- the loop thread itself, not request work; every coroutine scheduled onto it carries its deadline/span EXPLICITLY (contextvars don't cross run_coroutine_threadsafe)
                t = threading.Thread(target=loop.run_forever,
                                     name=self._name, daemon=True)
                t.start()
                self._loop, self._thread = loop, t
                # Health plane: the shared RPC loop carries EVERY peer
                # call — a blocked callback here stalls the whole
                # fabric, so it heartbeats under loopmon like the
                # front-door loops (best-effort: obs must never gate
                # the fabric).
                try:
                    from ..obs.loopmon import LOOPMON
                    LOOPMON.register("rpc", loop)
                except Exception:  # noqa: BLE001 - obs is optional here
                    pass
            return self._loop

    def submit(self, coro):
        """Schedule a coroutine; returns a concurrent.futures.Future.
        QoS context does NOT cross this hop — callers bake the deadline
        and span into the coroutine's arguments (see call_async)."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop())

    def run(self, coro):
        """Run a coroutine to completion from a sync thread."""
        if threading.current_thread() is self._thread:
            # A sync bridge FROM the loop thread would deadlock the
            # loop on its own future; nothing in-tree does this.
            coro.close()
            raise RuntimeError("sync RPC bridge called from the RPC "
                               "loop thread")
        # mtpu-lint: disable=R1 -- deadline/span ride inside the coroutine's own arguments; a contextvar copy would be ignored across the loop hop anyway
        return self.submit(coro).result()


RPC_LOOP = _LoopThread()


# ---------------------------------------------------------------------------
# In-flight census (satellite: the zero-thread claim must be measurable)


class _Census:
    """Counts in-flight peer RPCs across BOTH fabrics; publishes the
    ``minio_tpu_v2_rpc_inflight`` gauge on every transition (an RPC is
    a multi-ms wire round-trip — one gauge write is noise next to it)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._n = 0

    def enter(self) -> None:
        with self._mu:
            self._n += 1
            n = self._n
        self._publish(n)

    def exit(self) -> None:
        with self._mu:
            self._n -= 1
            n = self._n
        self._publish(n)

    def current(self) -> int:
        with self._mu:
            return self._n

    @staticmethod
    def _publish(n: int) -> None:
        from ..obs.metrics2 import METRICS2
        METRICS2.set_gauge("minio_tpu_v2_rpc_inflight", {}, n)


CENSUS = _Census()


def census() -> dict:
    """Timeline/top sample: in-flight internal RPCs vs process thread
    count — the pair that makes "zero threads per in-flight call" a
    measured number instead of a code-reading exercise."""
    return {"rpcInflight": CENSUS.current(),
            "threads": threading.active_count()}


# ---------------------------------------------------------------------------
# Per-client async connection pool (RPC-loop thread only — no lock)


class _AConn:
    __slots__ = ("reader", "writer", "gen")

    def __init__(self, reader, writer, gen):
        self.reader = reader
        self.writer = writer
        self.gen = gen


class _AioState:
    __slots__ = ("pool", "gen")

    def __init__(self):
        self.pool: list[_AConn] = []
        self.gen = 0


def _aio_state(client) -> _AioState:
    st = getattr(client, "_aio_state", None)
    if st is None:
        st = client._aio_state = _AioState()
    return st


def _kill(conn: _AConn) -> None:
    try:
        conn.writer.close()
    except OSError:
        pass


async def _open_aconn(client, timeout: float) -> _AConn:
    kw = {}
    if client.tls is not None:
        kw = {"ssl": client.tls, "server_hostname": client.host}
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(client.host, client.port, **kw), timeout)
    return _AConn(reader, writer, _aio_state(client).gen)


async def _get_aconn(client, timeout: float) -> tuple[_AConn, bool]:
    """(connection, reused) — same contract as the sync pool: callers
    retry once on a FRESH socket when a reused one fails before any
    response byte (a peer restart leaves pooled keep-alives stale)."""
    st = _aio_state(client)
    while st.pool:
        c = st.pool.pop()
        if c.gen == st.gen and not c.reader.at_eof():
            return c, True
        _kill(c)
    return await _open_aconn(client, timeout), False


async def _connect_mapped(client, eff_timeout: float, ddl, override,
                          service: str, method: str):
    """``_get_aconn`` with the threaded transport's failure mapping.

    The sync pool hands back an UNCONNECTED ``http.client`` object —
    the TCP connect happens lazily inside the request try-block, so
    its error mapping covers it for free.  ``asyncio.open_connection``
    connects eagerly, so a refused/timed-out connect here must get the
    identical treatment (offline mark, dyn-timeout tuning on genuine
    ceiling hits only, deadline attribution) or it leaks a raw
    ``OSError`` past the offline gate.
    """
    try:
        return await _get_aconn(client, eff_timeout)
    except (OSError, asyncio.TimeoutError) as e:
        if ddl is not None and ddl.expired():
            # The request DEADLINE elapsed, not the peer: say nothing
            # about peer health.
            record_expiry("rpc-client")
            raise DeadlineExceeded(
                f"{service}/{method} to {client.endpoint()}: deadline "
                f"expired mid-call: {e}")
        # Only genuine ceiling hits tune the timeout up — an instant
        # connection-refused says nothing about slowness.
        if not override and isinstance(e, (TimeoutError,
                                           asyncio.TimeoutError)):
            client.dyn_timeout.log_failure()
        if not override:
            client._mark_offline()
        raise serr.DiskNotFound(
            f"{client.endpoint()} unreachable: {e}")


def _put_aconn(client, conn: _AConn) -> None:
    st = _aio_state(client)
    if conn.gen == st.gen and len(st.pool) < POOL_SIZE:
        st.pool.append(conn)
        return
    _kill(conn)


def _drop_aio_pool(client) -> None:
    """Invalidate every pooled connection (stale after peer restart)."""
    st = _aio_state(client)
    st.gen += 1
    pool, st.pool = st.pool, []
    for c in pool:
        _kill(c)


def close_client(client) -> None:
    """Cross-thread pool teardown (RPCClient.close)."""
    if getattr(client, "_aio_state", None) is None:
        return
    loop = RPC_LOOP.loop()
    loop.call_soon_threadsafe(_drop_aio_pool, client)


# ---------------------------------------------------------------------------
# Wire helpers


def _request_bytes(client, service: str, method: str, args: dict,
                   payload: bytes, ddl, span) -> bytes:
    args_json = json.dumps(args, sort_keys=True)
    ts = str(int(time.time()))
    body = frame(args_json.encode(), payload)
    lines = [
        f"POST {RPC_PREFIX}/{service}/{method} HTTP/1.1",
        f"Host: {client.host}:{client.port}",
        f"x-mtpu-ts: {ts}",
        "x-mtpu-auth: " + sign(client.cluster_key,
                               f"{service}/{method}", ts, args_json,
                               payload),
        f"Content-Length: {len(body)}",
    ]
    if ddl is not None:
        lines.append(f"{H_DEADLINE}: {round(ddl.remaining_ms(), 3)}")
    if span is not None:
        lines.append(f"x-mtpu-trace: {span.trace_id}:{span.span_id}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode() + body


async def _read_response(reader, got_resp: list | None = None,
                         ) -> tuple[int, bytes, bool]:
    """Minimal HTTP/1.1 response read: (status, body, keep_alive).
    The peer's RPC responses always carry Content-Length."""
    line = await reader.readline()
    if not line:
        raise ConnectionResetError("peer closed connection before "
                                   "response")
    if got_resp is not None:
        got_resp[0] = True
    parts = line.split(None, 2)
    if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
        raise ValueError(f"bad rpc status line: {line[:80]!r}")
    status = int(parts[1])
    clen = 0
    keep = True
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n"):
            break
        if not h:
            raise ConnectionResetError("peer closed connection "
                                       "mid-headers")
        k, _, v = h.partition(b":")
        k = k.strip().lower()
        v = v.strip()
        if k == b"content-length":
            clen = int(v)
        elif k == b"connection" and v.lower() == b"close":
            keep = False
    body = await reader.readexactly(clen) if clen else b""
    return status, body, keep


async def _roundtrip(conn: _AConn, req: bytes, got_resp: list,
                     ) -> tuple[int, bytes, bool]:
    conn.writer.write(req)
    await conn.writer.drain()
    return await _read_response(conn.reader, got_resp)


def _graft_spans(result, span) -> None:
    """Pop the peer's server-side span subtree out of the result and
    graft it under the caller's span (same prune bounds as the sync
    transport — peer-supplied subtrees are untrusted input)."""
    if not isinstance(result, dict):
        return
    remote_spans = result.pop("_trace_spans", None)
    if remote_spans and span is not None and isinstance(remote_spans,
                                                        list):
        from ..obs.span import sanitize_remote
        for s in remote_spans[:8]:
            sc = sanitize_remote(s)
            if sc is not None:
                span.add_child(sc)


# ---------------------------------------------------------------------------
# The async call — a faithful port of RPCClient.call


async def call_async(client, service: str, method: str, args: dict,
                     payload: bytes = b"",
                     timeout: float | None = None,
                     ddl=None, span=None) -> tuple[dict, bytes]:
    """Async twin of ``RPCClient.call`` with identical semantics.

    ``ddl``/``span`` are passed EXPLICITLY (captured at the sync
    boundary by ``bridge_call``): contextvars do not reliably cross
    ``run_coroutine_threadsafe``, and making the budget an argument
    keeps the coroutine honest about whose deadline it spends.
    """
    if not client.is_online():
        raise serr.DiskNotFound(f"{client.endpoint()} offline")
    from ..faultinject import FAULTS
    if FAULTS.enabled:
        _lat, _part = FAULTS.peer(client.endpoint())
        if _lat:
            await asyncio.sleep(_lat)
        if _part:
            client._mark_offline()
            raise serr.DiskNotFound(
                f"{client.endpoint()} unreachable: injected partition")
    eff_timeout = timeout if timeout is not None else client.timeout
    if ddl is not None:
        rem_s = ddl.remaining()
        if rem_s <= 0:
            record_expiry("rpc-client")
            raise DeadlineExceeded(
                f"{service}/{method} to {client.endpoint()}: request "
                "deadline exhausted before dispatch")
        base = timeout if timeout is not None else client.timeout
        eff_timeout = max(0.05, min(base, rem_s))
    override = timeout is not None
    req = _request_bytes(client, service, method, args, payload, ddl,
                         span)
    CENSUS.enter()
    try:
        conn, reused = await _connect_mapped(client, eff_timeout, ddl,
                                             override, service, method)
        # mtpu-lint: disable=R6 -- single-shot retry, not a loop: the continue requires reused=True and a fresh socket comes back reused=False, so it fires at most once; no backoff by design (a stale pool is instant-fail, the peer is healthy)
        while True:
            t0 = time.monotonic()
            logged = override
            got_resp = [False]
            try:
                status, rbody, keep = await asyncio.wait_for(
                    _roundtrip(conn, req, got_resp), eff_timeout)
                if not override:
                    client.dyn_timeout.log_success(
                        time.monotonic() - t0)
                logged = True
                if status != 200:
                    if keep:
                        _put_aconn(client, conn)
                    else:
                        _kill(conn)
                    raise wire_to_error(status, rbody)
                result_json, data = unframe(rbody)
                if keep:
                    _put_aconn(client, conn)
                else:
                    _kill(conn)
                result = json.loads(result_json or b"{}")
                _graft_spans(result, span)
                return result, data
            except (OSError, EOFError, ValueError,
                    asyncio.TimeoutError) as e:
                _kill(conn)
                if (reused and not got_resp[0] and isinstance(
                        e, (ConnectionResetError, BrokenPipeError,
                            asyncio.IncompleteReadError))):
                    # Stale pooled socket (peer restarted): the error
                    # arrived BEFORE any response byte, on a reused
                    # keep-alive — the signature of a dead pool, not a
                    # dead peer. Retry ONCE on a fresh socket; errors
                    # after a response began (or on a fresh socket)
                    # never retry, so an RPC the peer may have
                    # executed is never re-sent.
                    _drop_aio_pool(client)
                    conn, reused = await _connect_mapped(
                        client, eff_timeout, ddl, override, service,
                        method)
                    continue
                if ddl is not None and ddl.expired():
                    # The request DEADLINE elapsed, not the peer: say
                    # nothing about peer health.
                    record_expiry("rpc-client")
                    raise DeadlineExceeded(
                        f"{service}/{method} to {client.endpoint()}: "
                        f"deadline expired mid-call: {e}")
                if not logged and isinstance(e, (TimeoutError,
                                                 asyncio.TimeoutError)):
                    client.dyn_timeout.log_failure()
                if not override:
                    client._mark_offline()
                raise serr.DiskNotFound(
                    f"{client.endpoint()} unreachable: {e}")
    finally:
        CENSUS.exit()


def bridge_call(client, service: str, method: str, args: dict,
                payload: bytes = b"",
                timeout: float | None = None) -> tuple[dict, bytes]:
    """Sync bridge: capture the caller's deadline + trace span on the
    calling thread, run the coroutine on the RPC loop, block on its
    future. Every await inside ``call_async`` is bounded, so the
    future always resolves."""
    ddl = current_deadline()
    from ..obs.span import current_span
    span = current_span()
    return RPC_LOOP.run(call_async(client, service, method, args,
                                   payload, timeout=timeout, ddl=ddl,
                                   span=span))


# ---------------------------------------------------------------------------
# Peer fan-out (rpc/peer.py): N peers, N coroutines, zero threads


def _fabric_serves(peers: dict) -> bool:
    """The async fabric only speaks to real RPCClients — test doubles
    and in-process loopback clients keep the thread fan-out path."""
    return (fabric_async() and bool(peers)
            and all(isinstance(c, RPCClient) for c in peers.values()))


def fanout(peers: dict, method: str, args: dict,
           timeout: float | None = None) -> dict | None:
    """Parallel peer fan-out on the RPC loop; returns {key: result
    dict | Exception} like NotificationSys._fanout, or None when these
    peers aren't fabric-servable (caller falls back to threads)."""
    if not _fabric_serves(peers):
        return None
    ddl = current_deadline()
    from ..obs.span import current_span
    span = current_span()

    async def one(key: str, client) -> tuple:
        try:
            res, _ = await call_async(client, "peer", method, args,
                                      timeout=timeout, ddl=ddl,
                                      span=span)
            return key, res
        except Exception as exc:  # noqa: BLE001 - per-peer failure
            return key, exc

    async def gather() -> dict:
        pairs = await asyncio.gather(
            *(one(k, c) for k, c in peers.items()))
        return dict(pairs)

    return RPC_LOOP.run(gather())


async def _swallow(coro) -> None:
    try:
        await coro
    except Exception:  # noqa: BLE001 - fire-and-forget push
        pass


def fanout_nowait(peers: dict, method: str, args: dict) -> bool:
    """Fire-and-forget fan-out: schedule one coroutine per peer and
    return immediately. Deliberately deadline-free and span-free — the
    push must OUTLIVE the mutating request that triggered it (same
    contract as the old daemon-thread _fanout_async). Returns False
    when these peers need the thread fallback."""
    if not _fabric_serves(peers):
        return False
    for key, client in peers.items():
        # mtpu-lint: disable=R1 -- fire-and-forget: deadline-FREE and span-free BY CONTRACT (the push must outlive the mutating request), so there is no context to carry
        RPC_LOOP.submit(_swallow(call_async(client, "peer", method,
                                            args, ddl=None,
                                            span=None)))
    return True


# ---------------------------------------------------------------------------
# HTTP/1.1 pipelining (RemoteStorage.create_file streamed writes)


class _PipeState:
    """Loop-side state of one pipelined connection. Writes stay
    ordered because each exchange coroutine writes in its FIRST slice
    (tasks start in submission order) and responses are read in the
    same order under a FIFO asyncio.Lock."""
    __slots__ = ("conn", "rlock", "broken")

    def __init__(self, conn: _AConn):
        self.conn = conn
        self.rlock = asyncio.Lock()
        self.broken: BaseException | None = None


async def _pipe_open(client, timeout: float) -> _PipeState:
    # Always a FRESH connection: a pipeline's burst of writes on a
    # stale pooled socket could not be safely retried (requests past
    # the first may have executed), so don't start on one.
    return _PipeState(await _open_aconn(client, timeout))


async def _pipe_exchange(client, st: _PipeState, req: bytes,
                         eff_timeout: float) -> tuple[dict, bytes]:
    if st.broken is not None:
        raise serr.DiskNotFound(
            f"{client.endpoint()} unreachable: pipeline broken: "
            f"{st.broken}")
    CENSUS.enter()
    try:
        try:
            st.conn.writer.write(req)
            async with st.rlock:
                await st.conn.writer.drain()
                status, rbody, _keep = await asyncio.wait_for(
                    _read_response(st.conn.reader), eff_timeout)
        except (OSError, EOFError, ValueError,
                asyncio.TimeoutError) as e:
            st.broken = e
            _kill(st.conn)
            client._mark_offline()
            raise serr.DiskNotFound(
                f"{client.endpoint()} unreachable: {e}")
        if status != 200:
            raise wire_to_error(status, rbody)
        result_json, data = unframe(rbody)
        return json.loads(result_json or b"{}"), data
    finally:
        CENSUS.exit()


async def _pipe_close(client, st: _PipeState, healthy: bool) -> None:
    if healthy and st.broken is None:
        _put_aconn(client, st.conn)
    else:
        _kill(st.conn)


class Pipeline:
    """Sync handle for pipelined RPCs to ONE peer over one dedicated
    connection: up to PIPELINE_WINDOW requests ride the wire before
    the sender blocks on the oldest response, so a streamed
    create_file overlaps chunk N's upload with chunk N-1..N-3's disk
    writes instead of stalling a full RTT per chunk.

    Pipelined calls never tune the dynamic timeout (a multi-chunk
    stream's per-response time measures queueing, not peer RTT) but DO
    mark the peer offline on connection-level failures — they are the
    data plane."""

    def __init__(self, client, timeout: float | None = None):
        self.client = client
        self._ddl = current_deadline()
        self._base = timeout if timeout is not None else client.timeout
        self._pending: list = []
        if not client.is_online():
            raise serr.DiskNotFound(f"{client.endpoint()} offline")
        from ..faultinject import FAULTS
        if FAULTS.enabled:
            _lat, _part = FAULTS.peer(client.endpoint())
            if _lat:
                time.sleep(_lat)
            if _part:
                client._mark_offline()
                raise serr.DiskNotFound(
                    f"{client.endpoint()} unreachable: injected "
                    "partition")
        try:
            self._st = RPC_LOOP.run(_pipe_open(client,
                                               self._eff_timeout()))
        except (OSError, asyncio.TimeoutError) as e:
            client._mark_offline()
            raise serr.DiskNotFound(
                f"{client.endpoint()} unreachable: {e}")

    def _eff_timeout(self) -> float:
        if self._ddl is not None:
            return max(0.05, min(self._base, self._ddl.remaining()))
        return self._base

    def send(self, service: str, method: str, args: dict,
             payload: bytes = b"") -> None:
        """Queue one call; blocks only when the window is full (on the
        OLDEST in-flight response, raising its mapped error)."""
        if self._ddl is not None:
            self._ddl.check(f"rpc.pipeline.{service}/{method}")
        req = _request_bytes(self.client, service, method, args,
                             payload, self._ddl, None)
        while len(self._pending) >= PIPELINE_WINDOW:
            self._pending.pop(0).result()
        # mtpu-lint: disable=R1 -- the deadline is baked into the request frame and _eff_timeout; the exchange coroutine carries no ambient context
        self._pending.append(RPC_LOOP.submit(_pipe_exchange(
            self.client, self._st, req, self._eff_timeout())))

    def finish(self) -> None:
        """Wait for every outstanding response (raising the first
        error), then return the connection to the peer's pool."""
        try:
            while self._pending:
                self._pending.pop(0).result()
        except BaseException:
            self.abort()
            raise
        # mtpu-lint: disable=R1 -- connection return/teardown, no request context exists to carry
        RPC_LOOP.submit(_pipe_close(self.client, self._st, True))

    def abort(self) -> None:
        """Drain outstanding responses (errors swallowed — the caller
        already has its exception) and close the connection: requests
        past a failure must not be re-interleaved onto a pooled
        socket."""
        while self._pending:
            f = self._pending.pop(0)
            try:
                f.result()
            except Exception:  # noqa: BLE001 - already failing
                pass
        # mtpu-lint: disable=R1 -- connection teardown, no request context exists to carry
        RPC_LOOP.submit(_pipe_close(self.client, self._st, False))
