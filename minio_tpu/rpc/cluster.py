"""Distributed node assembly (ref cmd/endpoint.go Endpoint /
EndpointServerPools, cmd/server-main.go:388 serverMain boot order,
cmd/prepare-storage.go waitForFormatErasure).

Every node runs the same command with the same endpoint list, e.g.:
    minio-tpu server http://127.0.0.1:{9001...9003}/data/n{1...2}
Endpoints whose host:port match --address become local XLStorage disks;
the rest become RemoteStorage RPC clients. The node owning the FIRST
endpoint coordinates format minting; others poll until formats appear.
"""

from __future__ import annotations

import hashlib
import re
import time
import urllib.parse
from dataclasses import dataclass

from ..erasure.pools import ErasureServerPools
from ..erasure.sets import ErasureSets
from ..storage.format import (FormatErasure, init_or_load_formats,
                              load_format)
from ..storage.xl import XLStorage
from ..utils.ellipses import expand
from .locks import (DistNSLock, LocalLocker, LockRPCService,
                    _LocalLockerClient, _RemoteLockerClient)
from .storage import RemoteStorage, StorageRPCService
from .transport import RPCClient, RPCRegistry


def local_host_names(my_host: str) -> set[str]:
    """All names/addresses that mean 'this node' (handles --address
    0.0.0.0 by collecting the machine's own hostnames/IPs; ref
    cmd/endpoint.go isLocalHost resolution)."""
    import socket
    names = {"127.0.0.1", "localhost", "::1"}
    if my_host not in ("", "0.0.0.0", "::"):
        names.add(my_host)
    try:
        hn = socket.gethostname()
        names.add(hn)
        for info in socket.getaddrinfo(hn, None):
            names.add(info[4][0])
    except OSError:
        pass
    return names


@dataclass(frozen=True)
class Endpoint:
    host: str | None   # None => plain local path
    port: int | None
    path: str
    secure: bool = False   # https:// endpoint (TLS internode)

    @property
    def is_url(self) -> bool:
        return self.host is not None

    def is_local(self, my_hosts: set[str], my_port: int) -> bool:
        if not self.is_url:
            return True
        return self.host in my_hosts and self.port == my_port

    def node_key(self) -> str | None:
        return f"{self.host}:{self.port}" if self.is_url else None


def parse_endpoint(arg: str) -> Endpoint:
    if re.match(r"^https?://", arg):
        u = urllib.parse.urlparse(arg)
        if not u.port:
            raise ValueError(f"endpoint needs an explicit port: {arg}")
        if not u.path or u.path == "/":
            raise ValueError(f"endpoint needs a disk path: {arg}")
        return Endpoint(u.hostname, u.port, u.path,
                        secure=u.scheme == "https")
    return Endpoint(None, None, arg)


def derive_cluster_key(access_key: str, secret_key: str) -> bytes:
    """Node-auth key from the root credentials (the reference signs
    internal RPC with JWT minted from the same credentials)."""
    return hashlib.sha256(
        f"minio-tpu-cluster:{access_key}:{secret_key}".encode()).digest()


class ClusterNode:
    """Everything one node contributes: its object layer, its RPC
    services (local disks + locker + peer control plane), and peer
    clients."""

    def __init__(self, layer: ErasureServerPools, registry: RPCRegistry,
                 local_disks: dict[str, XLStorage],
                 peers: dict[str, RPCClient],
                 peer_service=None, notification=None):
        self.layer = layer
        self.registry = registry
        self.local_disks = local_disks
        self.peers = peers
        self.peer_service = peer_service    # rpc.peer.PeerRPCService
        self.notification = notification    # rpc.peer.NotificationSys


def build_cluster_node(disk_args: list[str], my_host: str, my_port: int,
                       access_key: str, secret_key: str,
                       block_size: int | None = None,
                       format_timeout: float = 30.0,
                       registry: RPCRegistry | None = None) -> ClusterNode:
    """Pass `registry` (already wired into a RUNNING HTTP server) so
    peers can reach this node's storage RPC while everyone waits for
    formats — local disks and services register before the format loop."""
    cluster_key = derive_cluster_key(access_key, secret_key)

    # One pool per ellipses arg; plain args combine into a single pool
    # (ref createServerEndpoints legacy vs pools syntax).
    from ..utils.ellipses import has_ellipses
    pool_endpoints: list[list[Endpoint]] = []
    plain: list[Endpoint] = []
    for arg in disk_args:
        if has_ellipses(arg):
            pool_endpoints.append(
                [parse_endpoint(e) for e in expand(arg)])
        else:
            plain.append(parse_endpoint(arg))
    if plain:
        pool_endpoints.append(plain)

    # Peer clients, one per distinct remote node.
    peers: dict[str, RPCClient] = {}
    local_disks: dict[str, XLStorage] = {}
    my_hosts = local_host_names(my_host)

    any_secure = any(ep.secure for eps in pool_endpoints for ep in eps)
    rpc_tls = None
    if any_secure:
        from ..utils.certs import client_context_from_env
        rpc_tls = client_context_from_env()

    def realize(ep: Endpoint):
        if ep.is_local(my_hosts, my_port):
            import os
            os.makedirs(ep.path, exist_ok=True)
            disk = XLStorage(ep.path)
            local_disks[ep.path] = disk
            return disk
        key = ep.node_key()
        if key not in peers:
            peers[key] = RPCClient(ep.host, ep.port, cluster_key,
                                   tls=rpc_tls if ep.secure else None)
        return RemoteStorage(peers[key], ep.path)

    pool_disks = [[realize(ep) for ep in eps] for eps in pool_endpoints]

    # Register services FIRST — the format wait below depends on peers
    # being able to call us, and us them. The peer service must answer
    # handshakes before this node finishes booting (ref
    # bootstrap-peer-server registering ahead of waitForFormatErasure).
    from .peer import NotificationSys, PeerRPCService, topology_hash
    topo = topology_hash(sorted(
        f"{ep.host}:{ep.port}{ep.path}" if ep.is_url else ep.path
        for eps in pool_endpoints for ep in eps))
    peer_service = PeerRPCService(topo)
    locker = LocalLocker()
    if registry is None:
        registry = RPCRegistry(cluster_key)
    registry.register("lock", LockRPCService(locker))
    registry.register("storage", StorageRPCService(local_disks))
    registry.register("peer", peer_service)

    all_nodes: set[str] = set()
    my_keys = {f"{h}:{my_port}" for h in my_hosts}
    for eps in pool_endpoints:
        for ep in eps:
            if ep.is_url:
                all_nodes.add(ep.node_key())
    distributed = bool(all_nodes - my_keys)
    lock_clients = [_LocalLockerClient(locker)]
    for key in sorted(all_nodes):
        if key not in my_keys:
            lock_clients.append(_RemoteLockerClient(peers.setdefault(
                key, RPCClient(key.rsplit(":", 1)[0],
                               int(key.rsplit(":", 1)[1]), cluster_key,
                               tls=rpc_tls))))

    # Peer control plane shares the lock/storage RPC clients (the
    # setdefault loop above guarantees one per remote node).
    notification = NotificationSys(
        {k: c for k, c in peers.items() if k not in my_keys})

    # Bootstrap verify BEFORE joining the format dance: refuse peers
    # that disagree on version/protocol/topology (ref
    # cmd/bootstrap-peer-server.go:162, cmd/server-main.go:469-483).
    # Peers not yet answering (still booting) verify us when they do.
    if distributed:
        notification.verify_bootstrap(topo)

    kwargs = {}
    if block_size:
        kwargs["block_size"] = block_size

    pools = []
    for eps, disks in zip(pool_endpoints, pool_disks):
        if len(disks) < 2:
            raise ValueError("each pool needs at least 2 disks")
        # Boot coordination: the owner of endpoint[0] mints formats;
        # everyone else waits for them (ref waitForFormatErasure retry,
        # cmd/prepare-storage.go).
        i_coordinate = eps[0].is_local(my_hosts, my_port)
        deadline = time.monotonic() + format_timeout
        while True:
            try:
                have_any = any(
                    _try_load(d) is not None for d in disks)
                if have_any or i_coordinate:
                    fmt, ordered, fresh = init_or_load_formats(disks)
                    break
            except Exception:
                if time.monotonic() >= deadline:
                    raise
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    "timed out waiting for cluster formats")
            time.sleep(0.25)
        layout = [len(s) for s in fmt.sets]
        sets = ErasureSets(ordered, layout, fmt.deployment_id, **kwargs)
        if distributed:
            dist_lock = DistNSLock(lock_clients)
            for s in sets.sets:
                s.ns_lock = dist_lock
        pools.append(sets)

    layer = ErasureServerPools(pools)

    # Cluster-shared metacache: every (bucket, root) listing has one
    # owning node; the others stream its cache over the peer plane
    # instead of re-walking the set (ref owner-routed metacache,
    # cmd/metacache-server-pool.go:38, cmd/metacache-set.go:247).
    if distributed:
        from .peer import MetacacheShare
        share = MetacacheShare(notification, all_nodes & my_keys,
                               sorted(all_nodes))
        for pi, pool_sets in enumerate(layer.pools):
            for si, s in enumerate(pool_sets.sets):
                s.metacache.peer_share = share
                s.metacache.share_id = (pi, si)

    return ClusterNode(layer, registry, local_disks, peers,
                       peer_service=peer_service,
                       notification=notification)


def _try_load(disk) -> FormatErasure | None:
    try:
        return load_format(disk)
    except Exception:
        return None
