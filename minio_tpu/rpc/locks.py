"""dsync: distributed RW locks with quorum (ref pkg/dsync/drwmutex.go:49,
cmd/local-locker.go, cmd/lock-rest-server.go).

Algorithm (ref lock:207): try to acquire on ALL lockers in parallel;
success iff >= quorum grants (n/2+1 for write, n/2 for read, matching
the reference); on failure release all grants and retry with jitter
until timeout. Stale locks expire server-side after LOCK_TTL (lock
maintenance sweep, ref lock-rest-server.go lockMaintenance); held locks
are refreshed by a background keep-alive (ref drwmutex continuous
refresh) so long operations never silently lose exclusion.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from contextlib import contextmanager

from ..storage import errors as serr

LOCK_TTL = 60.0  # orphaned-lock expiry (maintenance sweep)


class LocalLocker:
    """Node-local lock table (ref localLocker, cmd/local-locker.go)."""

    def __init__(self):
        self._mu = threading.Lock()
        # resource -> {"writer": uid | None, "readers": {uid: expiry},
        #              "expiry": float}
        self._locks: dict[str, dict] = {}

    def _sweep(self, now: float) -> None:
        for res in list(self._locks):
            st = self._locks[res]
            if st["writer"] and st["expiry"] < now:
                st["writer"] = None
            st["readers"] = {u: e for u, e in st["readers"].items()
                             if e >= now}
            if not st["writer"] and not st["readers"]:
                del self._locks[res]

    def lock(self, resource: str, uid: str, writer: bool) -> bool:
        """Acquire or refresh: a repeat call from the holding uid renews
        the TTL (the keep-alive path). A failed writer attempt leaves a
        short writer-preference window during which new readers are
        refused, so steady reads can't starve writes."""
        now = time.monotonic()
        with self._mu:
            self._sweep(now)
            st = self._locks.setdefault(
                resource, {"writer": None, "readers": {}, "expiry": 0.0,
                           "writer_wait": 0.0})
            if writer:
                if st["writer"] is None and not st["readers"]:
                    st["writer"] = uid
                    st["expiry"] = now + LOCK_TTL
                    st["writer_wait"] = 0.0
                    return True
                if st["writer"] == uid:
                    st["expiry"] = now + LOCK_TTL
                    return True
                st["writer_wait"] = now + 1.0
                return False
            if st["writer"] is None and st.get("writer_wait", 0.0) <= now:
                st["readers"][uid] = now + LOCK_TTL
                return True
            if st["writer"] is None and uid in st["readers"]:
                st["readers"][uid] = now + LOCK_TTL  # refresh held read
                return True
            return False

    def unlock(self, resource: str, uid: str, writer: bool) -> bool:
        with self._mu:
            st = self._locks.get(resource)
            if st is None:
                return False
            if writer:
                if st["writer"] == uid:
                    st["writer"] = None
            else:
                st["readers"].pop(uid, None)
            if not st["writer"] and not st["readers"]:
                self._locks.pop(resource, None)
            return True

    def force_unlock(self, resource: str) -> None:
        with self._mu:
            self._locks.pop(resource, None)

    def top_locks(self) -> list[dict]:
        with self._mu:
            return [{"resource": r, "writer": bool(st["writer"]),
                     "readers": len(st["readers"])}
                    for r, st in self._locks.items()]


class LockRPCService:
    """Exposes a LocalLocker over the RPC transport."""

    def __init__(self, locker: LocalLocker):
        self.locker = locker

    def rpc_lock(self, a, p):
        ok = self.locker.lock(a["resource"], a["uid"], a["writer"])
        return {"granted": ok}, b""

    def rpc_unlock(self, a, p):
        self.locker.unlock(a["resource"], a["uid"], a["writer"])
        return {}, b""

    def rpc_force_unlock(self, a, p):
        self.locker.force_unlock(a["resource"])
        return {}, b""

    def rpc_top_locks(self, a, p):
        return {"locks": self.locker.top_locks()}, b""


class _LocalLockerClient:
    """In-process locker endpoint (this node's own table)."""

    def __init__(self, locker: LocalLocker):
        self.locker = locker

    def lock(self, resource, uid, writer):
        return self.locker.lock(resource, uid, writer)

    def unlock(self, resource, uid, writer):
        self.locker.unlock(resource, uid, writer)


class _RemoteLockerClient:
    """Peer locker endpoint over RPC."""

    def __init__(self, client):
        self.client = client

    def lock(self, resource, uid, writer):
        try:
            res, _ = self.client.call("lock", "lock",
                                      {"resource": resource, "uid": uid,
                                       "writer": writer})
            return bool(res.get("granted"))
        except serr.StorageError:
            return False

    def unlock(self, resource, uid, writer):
        try:
            self.client.call("lock", "unlock",
                             {"resource": resource, "uid": uid,
                              "writer": writer})
        except serr.StorageError:
            pass


class DRWMutex:
    """Distributed RW mutex over a set of locker endpoints
    (ref DRWMutex, pkg/dsync/drwmutex.go)."""

    def __init__(self, lockers: list, resource: str):
        self.lockers = lockers
        self.resource = resource

    def _quorum(self, writer: bool) -> int:
        """Write quorum n/2+1, read quorum n/2 (min 1) — ref
        pkg/dsync/drwmutex.go:207 quorum math."""
        n = len(self.lockers)
        return n // 2 + 1 if writer else max(n // 2, 1)

    def _fan(self, fn_name: str, uid: str, writer: bool) -> list[bool]:
        from ..parallel.quorum import parallel_map
        results, _ = parallel_map(
            [lambda lk=lk: getattr(lk, fn_name)(self.resource, uid,
                                                writer)
             for lk in self.lockers])
        return [bool(r) for r in results]

    def _try(self, uid: str, writer: bool) -> bool:
        grants = self._fan("lock", uid, writer)
        if sum(grants) >= self._quorum(writer):
            return True
        # Release partial grants (ref releaseAll:364).
        for lk, g in zip(self.lockers, grants):
            if g:
                lk.unlock(self.resource, uid, writer)
        return False

    def acquire(self, writer: bool, timeout: float = 30.0) -> str:
        uid = uuid.uuid4().hex
        deadline = time.monotonic() + timeout
        while True:
            if self._try(uid, writer):
                return uid
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"dsync: could not acquire {self.resource}")
            time.sleep(random.uniform(0.01, 0.05))

    def refresh(self, uid: str, writer: bool) -> bool:
        """Keep-alive: re-lock on every locker renews the server TTL.
        Returns False when the quorum was LOST (swept/usurped during a
        partition) — the holder no longer has exclusion."""
        grants = self._fan("lock", uid, writer)
        return sum(grants) >= self._quorum(writer)

    def release(self, uid: str, writer: bool) -> None:
        self._fan("unlock", uid, writer)


class DistNSLock:
    """Namespace-lock provider backed by dsync — drop-in for
    parallel.nslock.LocalNSLock in distributed mode
    (ref cmd/namespace-lock.go NewNSLock)."""

    def __init__(self, lockers: list, default_timeout: float = 30.0):
        self.lockers = lockers
        self.default_timeout = default_timeout
        # One shared keep-alive sweeper refreshes every held lock
        # (ref drwmutex continuous refresh; avoids a thread per lock).
        self._mu = threading.Lock()
        self._held: dict[int, dict] = {}
        self._next_id = 0
        self._sweeper: threading.Thread | None = None

    def _ensure_sweeper(self) -> None:
        if self._sweeper is None or not self._sweeper.is_alive():
            # mtpu-lint: disable=R1 -- lease-expiry sweeper daemon; runs for the server lifetime
            self._sweeper = threading.Thread(target=self._sweep_loop,
                                             daemon=True)
            self._sweeper.start()

    def _sweep_loop(self) -> None:
        while True:
            time.sleep(LOCK_TTL / 3)
            with self._mu:
                entries = list(self._held.values())
            for e in entries:
                if not e["mutex"].refresh(e["uid"], e["writer"]):
                    e["lost"] = True

    @contextmanager
    def _locked(self, bucket: str, obj: str, writer: bool,
                timeout: float | None):
        m = DRWMutex(self.lockers, f"{bucket}/{obj}")
        uid = m.acquire(writer=writer,
                        timeout=timeout or self.default_timeout)
        entry = {"mutex": m, "uid": uid, "writer": writer, "lost": False}
        with self._mu:
            hid = self._next_id
            self._next_id += 1
            self._held[hid] = entry
        self._ensure_sweeper()
        try:
            yield
            if entry["lost"]:
                # Exclusion was lost mid-operation (partition longer
                # than LOCK_TTL): surface it loudly instead of
                # pretending the op was safe.
                raise TimeoutError(
                    f"dsync: lock on {bucket}/{obj} lost during "
                    f"operation (possible concurrent writer)")
        finally:
            with self._mu:
                self._held.pop(hid, None)
            m.release(uid, writer=writer)

    def write_locked(self, bucket: str, obj: str,
                     timeout: float | None = None):
        return self._locked(bucket, obj, True, timeout)

    def read_locked(self, bucket: str, obj: str,
                    timeout: float | None = None):
        return self._locked(bucket, obj, False, timeout)
