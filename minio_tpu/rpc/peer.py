"""Peer control plane: node-to-node notifications, cluster-wide admin
fan-in, and the boot handshake.

The reference fans ~35 methods across peers (ref cmd/notification.go:48
NotificationSys, cmd/peer-rest-common.go:27-61 method list) and refuses
mismatched nodes at boot (ref cmd/bootstrap-peer-server.go:162
verifyServerSystemConfig, cmd/server-main.go:469-483). This rebuild
keeps the same responsibilities on the existing HMAC RPC transport
(rpc/transport.py), with the set of methods the rest of the codebase
actually consumes:

  handshake               boot-time version/topology verification
  load_iam                push IAM invalidation (replaces cross-node
                          freshness polling as the primary mechanism)
  load_bucket_metadata /  push bucket-config invalidation
  delete_bucket_metadata
  trace                   bounded trace collection for cluster-wide
                          `admin trace` (ref peerRESTMethodTrace)
  profiling_start/stop    cluster-wide CPU profiling fan-out
  metrics                 per-node codec dispatch + request counters
  server_info             per-node admin info for cluster aggregation

Fan-out is parallel and failure-tolerant: an unreachable peer degrades
that node's freshness to its fallback poll, never the caller's request.
"""

from __future__ import annotations

import hashlib
import threading

from .. import __version__
from .transport import RPCClient

PROTOCOL_VERSION = 1


def topology_hash(disk_args_expanded: list[str]) -> str:
    """Deterministic digest of the cluster shape every node must agree
    on (the reference compares endpoint ordering, CmdLine and version
    in verifyServerSystemConfig)."""
    doc = "\n".join(disk_args_expanded)
    return hashlib.sha256(doc.encode()).hexdigest()


class PeerRPCService:
    """Server side of the peer control plane. Constructed (and
    registered on the RPC registry) before the S3 server has a layer —
    handshake works immediately; server-backed methods bind later via
    bind()."""

    def __init__(self, topo_hash: str):
        self.topo_hash = topo_hash
        self.server = None          # S3Server, set by bind()
        self._profiler = None

    def bind(self, server) -> None:
        self.server = server

    # -- bootstrap -----------------------------------------------------

    def rpc_handshake(self, args: dict, payload: bytes):
        return ({"version": __version__, "protocol": PROTOCOL_VERSION,
                 "topology": self.topo_hash}, b"")

    # -- invalidation pushes -------------------------------------------

    def _server(self):
        if self.server is None:
            raise RuntimeError("peer service not bound yet")
        return self.server

    def rpc_load_iam(self, args: dict, payload: bytes):
        iam = self._server().iam
        if iam is not None:
            iam.load()
        return ({"ok": True}, b"")

    def rpc_load_bucket_metadata(self, args: dict, payload: bytes):
        self._server().bucket_meta.invalidate(args["bucket"])
        return ({"ok": True}, b"")

    def rpc_delete_bucket_metadata(self, args: dict, payload: bytes):
        self._server().bucket_meta.invalidate(args["bucket"])
        # A deleted bucket's hot-object entries must die with it.
        from ..cache.hotcache import HOTCACHE
        HOTCACHE.invalidate_bucket(args["bucket"])
        return ({"ok": True}, b"")

    def rpc_cache_invalidate(self, args: dict, payload: bytes):
        """Hot-object cache invalidation push (cache/hotcache.py): a
        peer overwrote/deleted bucket/key — drop our cached decoded
        copies and poison in-flight fills. The epoch is the writer's
        per-key version stamp (max-merged on our side); applied
        WITHOUT re-propagation, so invalidations can't storm. Needs no
        server binding — the cache is process-wide."""
        from ..cache.hotcache import HOTCACHE
        HOTCACHE.apply_peer_invalidation(args["bucket"], args["key"],
                                         int(args.get("epoch", 0)))
        return ({"ok": True}, b"")

    # -- cluster-wide admin fan-in -------------------------------------

    def rpc_trace(self, args: dict, payload: bytes):
        """Bounded trace collect, same contract as admin h_trace."""
        timeout = min(float(args.get("timeout", 3)), 30.0)
        entries = self._server().trace_hub.collect(timeout)
        return ({"entries": entries}, b"")

    def rpc_profiling_start(self, args: dict, payload: bytes):
        from ..utils.profiler import SamplingProfiler
        if self._profiler is not None:
            raise ValueError("profiling already running")
        self._profiler = SamplingProfiler(
            interval=float(args.get("intervalMs", 5)) / 1000.0)
        self._profiler.start()
        return ({"ok": True}, b"")

    def rpc_profiling_stop(self, args: dict, payload: bytes):
        prof = self._profiler
        if prof is None:
            raise ValueError("profiling not running")
        self._profiler = None
        return ({"profile": prof.stop()}, b"")

    def rpc_metrics(self, args: dict, payload: bytes):
        from ..ops import batching
        srv = self._server()
        return ({"rs": batching.STATS.snapshot(),
                 "bitrot": batching.HH_STATS.snapshot(),
                 "requests": dict(srv.metrics.requests),
                 "rx_bytes": srv.metrics.rx_bytes,
                 "tx_bytes": srv.metrics.tx_bytes}, b"")

    def rpc_metrics2(self, args: dict, payload: bytes):
        """This node's metrics-v2 snapshot for cluster aggregation
        (ref the cluster collectors of cmd/metrics-v2.go scraping
        peers over peerRESTClient)."""
        from ..obs.metrics2 import METRICS2
        return ({"metrics2": METRICS2.snapshot()}, b"")

    def rpc_drivemon(self, args: dict, payload: bytes):
        """This node's drive-health snapshot for the cluster drive
        endpoint's fan-in merge (same peer-scrape shape as metrics2)."""
        from ..obs.drivemon import DRIVEMON
        return ({"drivemon": DRIVEMON.snapshot()}, b"")

    def rpc_timeline(self, args: dict, payload: bytes):
        """This node's timeline sample ring for the cluster timeline
        endpoint's bucket-aligned merge (obs/timeline.py
        merge_timelines).  `n` bounds the tail so a peer scrape never
        ships more history than the caller will merge."""
        from ..obs.timeline import TIMELINE
        n = None
        if args.get("n") is not None:
            n = max(1, min(int(args["n"]), 36000))
        return ({"timeline": TIMELINE.snapshot(n=n)}, b"")

    def rpc_alerts(self, args: dict, payload: bytes):
        """This node's watchdog alert census for the cluster alerts
        endpoint's fan-in merge (obs/watchdog.py merge_alerts — worst
        state per rule with honest node counts).  Needs no server
        binding: the watchdog is process-wide."""
        from ..obs.watchdog import WATCHDOG
        return ({"alerts": WATCHDOG.snapshot()}, b"")

    def rpc_usage(self, args: dict, payload: bytes):
        """This node's workload-attribution snapshot (obs/usage.py)
        for the cluster usage endpoint's fan-in merge — accounts sum,
        sketches merge via their count-min backing.  Needs no server
        binding: the accountant is process-wide."""
        from ..obs.usage import USAGE
        return ({"usage": USAGE.snapshot()}, b"")

    def rpc_server_info(self, args: dict, payload: bytes):
        srv = self._server()
        return ({"version": __version__,
                 "uptime": __import__("time").time()
                 - srv.metrics.start_time,
                 "endpoint": f"{srv.host}:{srv.port}"
                 if hasattr(srv, "host") else ""}, b"")

    # -- cluster-shared metacache --------------------------------------

    def rpc_list_entries(self, args: dict, payload: bytes):
        """Serve this node's metacache entries for one (pool, set,
        bucket, root) — paged like the storage walk RPC, so listings
        cross the wire in bounded frames. Non-owner nodes call this
        instead of walking their own disks (ref the owner-routed
        metacache: cmd/metacache-server-pool.go:38 listPath picking up
        an existing listing, cmd/metacache-set.go:247)."""
        import bisect
        from ..s3.admin import _pools
        layer = self._server().layer
        pools = _pools(layer)
        mgr = pools[int(args["pool"])].sets[int(args["set"])].metacache
        if args.get("force"):
            # The caller wrote through its own node since its last
            # fetch: our tracker never saw that, so drop the cache and
            # rescan (preserves read-after-write through any node).
            with mgr._mu:
                mgr._caches.pop((args["bucket"],
                                 args.get("root", "")), None)
        entries = mgr._entries_local(args["bucket"],
                                     args.get("root", ""))
        after = args.get("after", "")
        limit = max(1, min(int(args.get("limit") or LIST_PAGE_ENTRIES),
                           10 * LIST_PAGE_ENTRIES))
        lo = bisect.bisect_right(entries, after,
                                 key=lambda e: e["name"]) if after else 0
        page = entries[lo:lo + limit]
        return ({"entries": page,
                 "truncated": lo + limit < len(entries)}, b"")


# Entries per shared-listing RPC page (bounds frame size both ways).
LIST_PAGE_ENTRIES = 2000


class MetacacheShare:
    """Owner routing for cluster-shared listings: every (bucket, root)
    hashes to ONE node in the (topology-identical) node list; everyone
    else streams that owner's cache over the peer plane instead of
    re-walking the set (round-4 verdict missing #2). Installed on each
    set's MetacacheManager by the cluster wiring."""

    def __init__(self, notification: "NotificationSys",
                 my_keys: set[str], node_keys: list[str]):
        self.notification = notification
        # ALL aliases this node appears under in the endpoint list: a
        # root hashing to any alias is ours (a single-key check would
        # misroute aliased roots to a peers[] lookup that KeyErrors).
        self.my_keys = set(my_keys)
        self.node_keys = sorted(node_keys)

    def owner_key(self, bucket: str, root: str) -> str | None:
        """The owning node's key, or None when this node owns it."""
        if not self.node_keys:
            return None
        digest = hashlib.sha256(f"{bucket}\x00{root}".encode()).digest()
        owner = self.node_keys[int.from_bytes(digest[:8], "big")
                               % len(self.node_keys)]
        return None if owner in self.my_keys else owner

    def fetch_entries(self, owner: str, share_id: tuple[int, int],
                      bucket: str, root: str, after: str = "",
                      force: bool = False):
        """Generator streaming the owner's entries page by page,
        starting past `after`; pages stop being fetched as soon as the
        consumer stops (a list_path hitting max_keys never pulls the
        rest of a huge listing). `force` makes the FIRST page drop the
        owner's cache (writes went through the caller's node)."""
        client = self.notification.peers[owner]
        first = True
        while True:
            res, _ = client.call("peer", "list_entries", {
                "pool": share_id[0], "set": share_id[1],
                "bucket": bucket, "root": root, "after": after,
                "force": bool(force and first),
                "limit": LIST_PAGE_ENTRIES})
            first = False
            entries = res["entries"]
            yield from entries
            if not res.get("truncated") or not entries:
                return
            after = entries[-1]["name"]


class BootstrapMismatch(RuntimeError):
    """A peer disagrees about version/protocol/topology — refusing to
    join (ref bootstrap verify error, cmd/server-main.go:469-483)."""


class NotificationSys:
    """Client side: parallel fan-out to every peer (ref NotificationSys,
    cmd/notification.go:48). All pushes are fire-and-forget from the
    caller's perspective — failures degrade the peer to its fallback
    poll and are reported in the return value for tests/observability."""

    def __init__(self, peers: dict[str, RPCClient]):
        self.peers = dict(peers)

    def _fanout(self, method: str, args: dict,
                timeout: float | None = None,
                ) -> dict[str, dict | Exception]:
        results: dict[str, dict | Exception] = {}
        if not self.peers:
            return results

        # Async fabric (rpc/aio.py): N peers become N coroutines on
        # the process-wide RPC loop — the caller blocks on ONE future,
        # zero fan-out threads. Falls through to the thread path when
        # the fabric is off or a peer isn't a real RPCClient (test
        # doubles, in-process loopbacks).
        from . import aio
        fabric = aio.fanout(self.peers, method, args, timeout=timeout)
        if fabric is not None:
            return fabric

        def one(key: str, client: RPCClient) -> None:
            try:
                results[key], _ = client.call("peer", method, args,
                                              timeout=timeout)
            except Exception as exc:  # noqa: BLE001 - per-peer failure
                results[key] = exc

        # ctx_wrap: the RPC client reads the request deadline from a
        # contextvar (transport.py) — bare threads here ran cluster
        # fan-outs deadline-UNCAPPED and header-less (found by lint
        # rule R1, the same gap PR 2 fixed on the quorum pool).
        from ..qos.ctx import ctx_wrap
        threads = [threading.Thread(target=ctx_wrap(one), args=kv,
                                    daemon=True)
                   for kv in self.peers.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def _fanout_async(self, method: str, args: dict) -> None:
        """Push without blocking the mutating request on peer RPCs."""
        from . import aio
        if aio.fanout_nowait(self.peers, method, args):
            # Scheduled on the RPC loop deadline-free and span-free:
            # the push must OUTLIVE the mutating request (same
            # contract the daemon-thread fallback encodes below).
            return
        # mtpu-lint: disable=R1 -- fire-and-forget push must OUTLIVE the request; inheriting its deadline would cancel the notify
        threading.Thread(target=self._fanout, args=(method, args),
                         daemon=True).start()

    # -- bootstrap -----------------------------------------------------

    def verify_bootstrap(self, topo_hash: str) -> dict[str, str]:
        """Handshake every reachable peer; BootstrapMismatch on any
        disagreement. Unreachable peers are skipped (they verify us
        when they boot; the reference retries until the cluster
        converges). Returns {peer: status} for logging."""
        statuses: dict[str, str] = {}
        for key, res in self._fanout("handshake", {}).items():
            if isinstance(res, Exception):
                statuses[key] = f"unreachable: {res}"
                continue
            if res.get("protocol") != PROTOCOL_VERSION:
                raise BootstrapMismatch(
                    f"peer {key} speaks protocol {res.get('protocol')}, "
                    f"this node {PROTOCOL_VERSION}")
            if res.get("version") != __version__:
                raise BootstrapMismatch(
                    f"peer {key} runs version {res.get('version')}, "
                    f"this node {__version__}")
            if res.get("topology") != topo_hash:
                raise BootstrapMismatch(
                    f"peer {key} has a different endpoint topology "
                    f"({res.get('topology', '')[:12]}... vs "
                    f"{topo_hash[:12]}...) — same endpoint list "
                    "required on every node")
            statuses[key] = "ok"
        return statuses

    # -- pushes --------------------------------------------------------

    def load_iam(self) -> None:
        self._fanout_async("load_iam", {})

    def load_bucket_metadata(self, bucket: str) -> None:
        self._fanout_async("load_bucket_metadata", {"bucket": bucket})

    def delete_bucket_metadata(self, bucket: str) -> None:
        self._fanout_async("delete_bucket_metadata", {"bucket": bucket})

    def cache_invalidate(self, bucket: str, key: str,
                         epoch: int) -> None:
        """Fire-and-forget hot-object cache invalidation: a lost push
        degrades the peer to its ETag-revalidation backstop
        (cache/hotcache.py), never the writer's request."""
        self._fanout_async("cache_invalidate",
                           {"bucket": bucket, "key": key,
                            "epoch": int(epoch)})

    # -- synchronous fan-ins (admin aggregation) -----------------------

    def trace_all(self, timeout: float) -> list:
        entries = []
        # The peer blocks up to `timeout` by design: give the RPC its
        # own window instead of the data plane's self-tuning one.
        for res in self._fanout("trace", {"timeout": timeout},
                                timeout=timeout + 10).values():
            if isinstance(res, dict):
                entries.extend(res.get("entries", []))
        return entries

    def profiling_start_all(self, interval_ms: float) -> dict:
        return {k: (str(v) if isinstance(v, Exception) else "ok")
                for k, v in self._fanout(
                    "profiling_start",
                    {"intervalMs": interval_ms}).items()}

    def profiling_stop_all(self) -> dict:
        out = {}
        for k, v in self._fanout("profiling_stop", {}).items():
            out[k] = v.get("profile") if isinstance(v, dict) else str(v)
        return out

    def metrics_all(self) -> dict:
        return {k: (v if isinstance(v, dict) else {"error": str(v)})
                for k, v in self._fanout("metrics", {}).items()}

    def metrics2_all(self) -> dict:
        """Per-peer metrics-v2 snapshots; unreachable peers degrade to
        an error entry (the cluster endpoint reports how many nodes
        actually contributed)."""
        return {k: (v if isinstance(v, dict) else {"error": str(v)})
                for k, v in self._fanout("metrics2", {}).items()}

    def drivemon_all(self) -> dict:
        """Per-peer drive-health snapshots for the cluster drives
        endpoint (unreachable peers degrade, never the scrape)."""
        return {k: (v if isinstance(v, dict) else {"error": str(v)})
                for k, v in self._fanout("drivemon", {}).items()}

    def timeline_all(self, n: int | None = None) -> dict:
        """Per-peer timeline snapshots for the cluster timeline merge
        (unreachable peers degrade to an error entry; their buckets
        simply carry fewer nodes)."""
        args: dict = {} if n is None else {"n": n}
        return {k: (v if isinstance(v, dict) else {"error": str(v)})
                for k, v in self._fanout("timeline", args).items()}

    def alerts_all(self) -> dict:
        """Per-peer watchdog snapshots for the cluster alerts merge
        (unreachable peers degrade to an error entry — the endpoint
        counts them as unreachable, never as alert-free)."""
        return {k: (v if isinstance(v, dict) else {"error": str(v)})
                for k, v in self._fanout("alerts", {}).items()}

    def usage_all(self) -> dict:
        """Per-peer usage snapshots for the cluster attribution merge
        (unreachable peers degrade to an error entry — the endpoint
        counts them as unreachable, never as idle)."""
        return {k: (v if isinstance(v, dict) else {"error": str(v)})
                for k, v in self._fanout("usage", {}).items()}

    def server_info_all(self) -> dict:
        return {k: (v if isinstance(v, dict) else {"error": str(v)})
                for k, v in self._fanout("server_info", {}).items()}
