"""Storage RPC: every StorageAPI method over the wire, so a peer node's
disks join an erasure set exactly like local ones (ref
cmd/storage-rest-server.go route table :1025-1075, storage-rest-client).

StorageRPCService exposes a node's LOCAL disks (indexed by their path);
RemoteStorage implements StorageAPI against a peer's service.
"""

from __future__ import annotations

import base64
import contextlib

from ..storage import errors as serr
from ..storage.interface import StorageAPI
from ..storage.metadata import FileInfo
from .transport import RPCClient


# Entries per walk_dir RPC page: bounds both the frame size (~300B per
# single-version entry -> ~300KiB pages) and server/client memory.
WALK_PAGE_ENTRIES = 1000

_NULL_CTX = contextlib.nullcontext()


def _fi_to_wire(fi: FileInfo) -> dict:
    d = fi.to_version_dict()
    d["_volume"] = fi.volume
    d["_name"] = fi.name
    return d


def _fi_from_wire(d: dict) -> FileInfo:
    fi = FileInfo.from_version_dict(d.get("_volume", ""),
                                    d.get("_name", ""), d)
    return fi


class StorageRPCService:
    """Server side: dispatches to this node's local disks by disk path."""

    def __init__(self, local_disks: dict[str, StorageAPI]):
        self.disks = local_disks

    def _disk(self, args: dict) -> StorageAPI:
        d = self.disks.get(args["disk"])
        if d is None:
            raise serr.DiskNotFound(args.get("disk", "?"))
        return d

    # Each rpc_* takes (args, payload) -> (result, body).

    def rpc_disk_info(self, a, p):
        return self._disk(a).disk_info(), b""

    def rpc_make_volume(self, a, p):
        self._disk(a).make_volume(a["volume"])
        return {}, b""

    def rpc_list_volumes(self, a, p):
        return {"volumes": self._disk(a).list_volumes()}, b""

    def rpc_stat_volume(self, a, p):
        return self._disk(a).stat_volume(a["volume"]), b""

    def rpc_delete_volume(self, a, p):
        self._disk(a).delete_volume(a["volume"], a.get("force", False))
        return {}, b""

    def rpc_write_all(self, a, p):
        self._disk(a).write_all(a["volume"], a["path"], p)
        return {}, b""

    def rpc_read_all(self, a, p):
        return {}, self._disk(a).read_all(a["volume"], a["path"])

    def rpc_read_file(self, a, p):
        return {}, self._disk(a).read_file(a["volume"], a["path"],
                                           a["offset"], a["length"])

    def rpc_repair_project(self, a, p):
        return {}, self._disk(a).repair_project(
            a["volume"], a["path"],
            [(int(o), int(ln)) for o, ln in a["ranges"]])

    def rpc_create_file(self, a, p):
        self._disk(a).create_file(a["volume"], a["path"], p)
        return {}, b""

    def rpc_append_file(self, a, p):
        self._disk(a).append_file(a["volume"], a["path"], p)
        return {}, b""

    def rpc_delete(self, a, p):
        self._disk(a).delete(a["volume"], a["path"],
                             a.get("recursive", False))
        return {}, b""

    def rpc_rename_file(self, a, p):
        self._disk(a).rename_file(a["src_volume"], a["src_path"],
                                  a["dst_volume"], a["dst_path"])
        return {}, b""

    def rpc_list_dir(self, a, p):
        return {"entries": self._disk(a).list_dir(a["volume"],
                                                  a["path"])}, b""

    def rpc_walk_dir(self, a, p):
        # STREAMED walk: bounded pages with a resume token instead of
        # the whole listing in one frame — a million-object bucket is
        # many small frames, O(page) memory on both ends (ref WalkDir
        # streamed over storage REST with trailing-error framing,
        # cmd/metacache-walk.go, cmd/storage-rest-server.go:1025; the
        # strict request/response transport here makes the resume
        # token carry the stream position instead).
        import itertools
        limit = max(1, min(int(a.get("limit") or WALK_PAGE_ENTRIES),
                           10 * WALK_PAGE_ENTRIES))
        it = self._disk(a).walk_dir_iter(a["volume"],
                                         a.get("prefix", ""),
                                         a.get("after", ""))
        entries = list(itertools.islice(it, limit + 1))
        truncated = len(entries) > limit
        return {"entries": entries[:limit], "truncated": truncated}, b""

    def rpc_rename_data(self, a, p):
        self._disk(a).rename_data(a["src_volume"], a["src_path"],
                                  _fi_from_wire(a["fi"]),
                                  a["dst_volume"], a["dst_path"])
        return {}, b""

    def rpc_write_metadata(self, a, p):
        self._disk(a).write_metadata(a["volume"], a["path"],
                                     _fi_from_wire(a["fi"]))
        return {}, b""

    def rpc_read_version(self, a, p):
        fi = self._disk(a).read_version(a["volume"], a["path"],
                                        a.get("version_id", ""))
        return {"fi": _fi_to_wire(fi)}, b""

    def rpc_read_versions(self, a, p):
        fis = self._disk(a).read_versions(a["volume"], a["path"])
        return {"fis": [_fi_to_wire(fi) for fi in fis]}, b""

    def rpc_delete_version(self, a, p):
        self._disk(a).delete_version(a["volume"], a["path"],
                                     _fi_from_wire(a["fi"]))
        return {}, b""

    def rpc_read_parts(self, a, p):
        return {"parts": self._disk(a).read_parts(
            a["volume"], a["path"], a["data_dir"])}, b""

    def rpc_verify_file(self, a, p):
        self._disk(a).verify_file(a["volume"], a["path"],
                                  _fi_from_wire(a["fi"]))
        return {}, b""


class RemoteStorage(StorageAPI):
    """StorageAPI over the wire: one peer disk (ref storageRESTClient,
    cmd/storage-rest-client.go)."""

    def __init__(self, client: RPCClient, disk_path: str):
        self.client = client
        self.disk_path = disk_path
        # Remote disks mean quorum fan-outs wait on the network: those
        # waits must overlap even on a single-core host. This is a
        # deliberate ONE-WAY latch for the process lifetime (see
        # parallel/quorum.py FORCE_THREADS): a node that ever had a
        # remote disk may still hold RPC-backed lockers/peers, and
        # threaded fan-outs are always correct — only ~ms slower on
        # the single-core all-local case.
        from ..parallel import quorum
        quorum.FORCE_THREADS = True

    def __repr__(self) -> str:
        return f"RemoteStorage({self.client.endpoint()}{self.disk_path})"

    def _drive_key(self) -> str:
        """Drive-health identity of this remote disk (duck-typed:
        in-process loopback clients in tests have no endpoint())."""
        host = getattr(self.client, "endpoint", lambda: "?")()
        return f"{host}{self.disk_path}"

    def _call(self, method: str, args: dict | None = None,
              payload: bytes = b"") -> tuple[dict, bytes]:
        a = {"disk": self.disk_path}
        a.update(args or {})
        # Deadline fast-fail: a shard fan-out whose request budget is
        # spent skips the remote I/O entirely (the transport would
        # refuse too, but this avoids even building the span).
        from ..qos.deadline import current_deadline
        ddl = current_deadline()
        if ddl is not None:
            ddl.check(f"rpc.storage.{method}")
        # Drive-health accounting at the CLIENT boundary: wire time
        # included, because that is what this node's quorum fan-outs
        # actually wait on for a remote disk (obs/drivemon.py).
        import time as _time
        from ..obs.drivemon import DRIVEMON, is_drive_fault
        from ..obs.span import TRACER, current_span
        t0 = _time.perf_counter()
        err = None
        try:
            if current_span() is None:  # untraced fast path: no tags
                return self.client.call("storage", method, a, payload)
            # Traced callers get a client-side RPC span here; the
            # peer's server-side subtree grafts under the SAME span
            # when the transport pops _trace_spans (rpc/transport.py),
            # so wire time vs remote disk time separate cleanly in the
            # stitched trace.
            with TRACER.span(f"rpc.storage.{method}",
                             endpoint=getattr(self.client, "endpoint",
                                              lambda: "?")(),
                             disk=self.disk_path):
                return self.client.call("storage", method, a, payload)
        except BaseException as e:
            err = e
            raise
        finally:
            DRIVEMON.record(self._drive_key(), method,
                            (_time.perf_counter() - t0) * 1e3,
                            error=is_drive_fault(err))

    def endpoint(self) -> str:
        return f"{self.client.endpoint()}{self.disk_path}"

    def is_online(self) -> bool:
        return self.client.is_online()

    def disk_info(self) -> dict:
        return self._call("disk_info")[0]

    def make_volume(self, volume):
        self._call("make_volume", {"volume": volume})

    def list_volumes(self):
        return self._call("list_volumes")[0]["volumes"]

    def stat_volume(self, volume):
        return self._call("stat_volume", {"volume": volume})[0]

    def delete_volume(self, volume, force=False):
        self._call("delete_volume", {"volume": volume, "force": force})

    def write_all(self, volume, path, data):
        self._call("write_all", {"volume": volume, "path": path},
                   bytes(data))

    def read_all(self, volume, path):
        data = self._call("read_all", {"volume": volume,
                                       "path": path})[1]
        # Corrupt-over-the-wire injection (minio_tpu/faultinject):
        # keyed by the remote drive identity so a plan can rot ONE
        # peer disk's reads — the caller's bitrot verification must
        # catch it exactly like on-platter rot.
        from ..faultinject import FAULTS
        return FAULTS.filter_read(self._drive_key(), "read_all", data)

    def read_file(self, volume, path, offset, length):
        data = self._call("read_file", {"volume": volume, "path": path,
                                        "offset": offset,
                                        "length": length})[1]
        from ..faultinject import FAULTS
        return FAULTS.filter_read(self._drive_key(), "read_file", data)

    def repair_project(self, volume, path, ranges):
        # The whole point of REGEN repair: ONE round trip carrying only
        # the projection bytes (d stored rows per group), not a ranged
        # read per row and never the helper's full chunk.
        data = self._call("repair_project",
                          {"volume": volume, "path": path,
                           "ranges": [[o, ln] for o, ln in ranges]})[1]
        from ..faultinject import FAULTS
        return FAULTS.filter_read(self._drive_key(), "repair_project",
                                  data)

    def create_file(self, volume, path, data):
        if isinstance(data, (bytes, bytearray, memoryview)):
            self._call("create_file", {"volume": volume, "path": path},
                       bytes(data))
            return
        # Streamed write: first chunk creates/truncates, the rest append
        # — one bounded RPC frame per chunk, never the whole object
        # (ref storageRESTClient.CreateFile streaming body,
        # cmd/storage-rest-client.go). On the async fabric the chunk
        # frames ride ONE pipelined connection (up to aio.
        # PIPELINE_WINDOW in flight) so chunk N's upload overlaps the
        # peer's disk write for chunks N-1..N-3 instead of paying a
        # full round-trip stall per chunk.
        from . import aio
        if aio.fabric_async() and isinstance(self.client, RPCClient):
            self._create_file_pipelined(volume, path, data)
            return
        first = True
        for chunk in data:
            if first:
                self._call("create_file",
                           {"volume": volume, "path": path}, bytes(chunk))
                first = False
            else:
                self._call("append_file",
                           {"volume": volume, "path": path}, bytes(chunk))
        if first:  # empty stream still creates the file
            self._call("create_file", {"volume": volume, "path": path},
                       b"")

    def _create_file_pipelined(self, volume: str, path: str,
                               chunks) -> None:
        """Streamed create over one pipelined connection. Chunk frames
        carry no per-call trace header (a big object would mint one
        server span per append); traced callers get a single
        client-side span for the whole stream, and drive-health
        accounting records one create_file covering the wire time the
        quorum fan-out actually waited."""
        from . import aio
        from ..qos.deadline import current_deadline
        ddl = current_deadline()
        if ddl is not None:
            ddl.check("rpc.storage.create_file")
        import time as _time
        from ..obs.drivemon import DRIVEMON, is_drive_fault
        from ..obs.span import TRACER, current_span
        a = {"disk": self.disk_path, "volume": volume, "path": path}
        t0 = _time.perf_counter()
        err = None
        try:
            span = (TRACER.span("rpc.storage.create_file",
                                endpoint=self.client.endpoint(),
                                disk=self.disk_path, pipelined=True)
                    if current_span() is not None else None)
            with span if span is not None else _NULL_CTX:
                pipe = aio.Pipeline(self.client)
                try:
                    first = True
                    for chunk in chunks:
                        pipe.send("storage",
                                  "create_file" if first
                                  else "append_file", a, bytes(chunk))
                        first = False
                    if first:  # empty stream still creates the file
                        pipe.send("storage", "create_file", a, b"")
                    pipe.finish()
                except BaseException:
                    pipe.abort()
                    raise
        except BaseException as e:
            err = e
            raise
        finally:
            DRIVEMON.record(self._drive_key(), "create_file",
                            (_time.perf_counter() - t0) * 1e3,
                            error=is_drive_fault(err))

    def append_file(self, volume, path, data):
        self._call("append_file", {"volume": volume, "path": path},
                   bytes(data))

    def delete(self, volume, path, recursive=False):
        self._call("delete", {"volume": volume, "path": path,
                              "recursive": recursive})

    def rename_file(self, src_volume, src_path, dst_volume, dst_path):
        self._call("rename_file", {"src_volume": src_volume,
                                   "src_path": src_path,
                                   "dst_volume": dst_volume,
                                   "dst_path": dst_path})

    def list_dir(self, volume, path):
        return self._call("list_dir", {"volume": volume,
                                       "path": path})[0]["entries"]

    def walk_dir_iter(self, volume, prefix="", after=""):
        # Streaming walk over the paged RPC: yield each page as it
        # arrives; the resume token (last yielded name) makes every
        # frame independent, so peak RPC frame size and client memory
        # are O(page) regardless of bucket size.
        while True:
            res, _ = self._call("walk_dir", {
                "volume": volume, "prefix": prefix, "after": after,
                "limit": WALK_PAGE_ENTRIES})
            entries = res["entries"]
            yield from entries
            if not res.get("truncated") or not entries:
                return
            after = entries[-1]["name"]

    def walk_dir(self, volume, prefix=""):
        return list(self.walk_dir_iter(volume, prefix))

    def rename_data(self, src_volume, src_path, fi, dst_volume, dst_path):
        self._call("rename_data", {"src_volume": src_volume,
                                   "src_path": src_path,
                                   "fi": _fi_to_wire(fi),
                                   "dst_volume": dst_volume,
                                   "dst_path": dst_path})

    def write_metadata(self, volume, path, fi):
        self._call("write_metadata", {"volume": volume, "path": path,
                                      "fi": _fi_to_wire(fi)})

    def read_version(self, volume, path, version_id=""):
        res, _ = self._call("read_version", {"volume": volume,
                                             "path": path,
                                             "version_id": version_id})
        return _fi_from_wire(res["fi"])

    def read_versions(self, volume, path):
        res, _ = self._call("read_versions", {"volume": volume,
                                              "path": path})
        return [_fi_from_wire(d) for d in res["fis"]]

    def delete_version(self, volume, path, fi):
        self._call("delete_version", {"volume": volume, "path": path,
                                      "fi": _fi_to_wire(fi)})

    def read_parts(self, volume, path, data_dir):
        return self._call("read_parts", {"volume": volume, "path": path,
                                         "data_dir": data_dir,
                                         })[0]["parts"]

    def verify_file(self, volume, path, fi):
        self._call("verify_file", {"volume": volume, "path": path,
                                   "fi": _fi_to_wire(fi)})
