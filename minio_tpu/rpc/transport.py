"""Internal RPC transport: authenticated POST with length-prefixed JSON +
binary framing, pooled keep-alive connections, and health-gated clients
with reconnect (ref cmd/rest/client.go:62,193 MarkOffline +
HealthCheckFn).

Wire format per call (everything in the BODY — headers stay tiny):
    POST /minio-tpu/rpc/v1/<service>/<method>
    x-mtpu-auth: hex hmac-sha256(cluster_key,
                   service/method + "\\n" + ts + "\\n" + args_json
                   + "\\n" + sha256(payload))
    x-mtpu-ts:   unix seconds (rejected outside +/- 5 min skew window;
                 bounds replay — cluster ports are expected to run on a
                 trusted network like the reference's)
    body: [4B big-endian args_len][args_json][payload]
Response 200: [4B result_len][result_json][body]; errors are 4xx/5xx with
a JSON {error_type, message} mapped back to storage errors.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import json
import random
import struct
import socket
import threading
import time

from ..qos.deadline import (H_DEADLINE, Deadline, DeadlineExceeded,
                            current_deadline, deadline_scope,
                            record_expiry)
from ..storage import errors as serr

RPC_PREFIX = "/minio-tpu/rpc/v1"
MAX_SKEW = 300  # seconds

_ERR_TYPES = {
    "DiskNotFound": serr.DiskNotFound,
    "FaultyDisk": serr.FaultyDisk,
    "VolumeNotFound": serr.VolumeNotFound,
    "VolumeExists": serr.VolumeExists,
    "FileNotFound": serr.FileNotFound,
    "VersionNotFound": serr.VersionNotFound,
    "FileCorrupt": serr.FileCorrupt,
    "DiskFull": serr.DiskFull,
    "DeadlineExceeded": DeadlineExceeded,
}


def sign(cluster_key: bytes, method: str, ts: str, args_json: str,
         payload: bytes) -> str:
    msg = "\n".join([method, ts, args_json,
                     hashlib.sha256(payload).hexdigest()])
    return hmac.new(cluster_key, msg.encode(), hashlib.sha256).hexdigest()


def frame(args_json: bytes, payload: bytes) -> bytes:
    return struct.pack(">I", len(args_json)) + args_json + payload


def unframe(body: bytes) -> tuple[bytes, bytes]:
    if len(body) < 4:
        raise ValueError("short rpc frame")
    n = struct.unpack(">I", body[:4])[0]
    if len(body) < 4 + n:
        raise ValueError("truncated rpc frame")
    return body[4:4 + n], body[4 + n:]


def error_to_wire(e: BaseException) -> tuple[int, bytes]:
    name = type(e).__name__
    if isinstance(e, (serr.FileNotFound, serr.VolumeNotFound,
                      serr.VersionNotFound)):
        status = 404
    elif isinstance(e, DeadlineExceeded):
        status = 503  # retryable: the CALLER's budget ran out
    else:
        status = 500
    return status, json.dumps({"error_type": name,
                               "message": str(e)}).encode()


def wire_to_error(status: int, body: bytes) -> Exception:
    try:
        doc = json.loads(body)
        cls = _ERR_TYPES.get(doc.get("error_type"), serr.FaultyDisk)
        return cls(doc.get("message", f"rpc status {status}"))
    except (ValueError, KeyError):
        return serr.FaultyDisk(f"rpc status {status}: {body[:200]!r}")


class RPCClient:
    """Health-gated RPC caller to one peer, with a pooled keep-alive
    connection."""

    # Seconds a peer stays marked offline before a reconnect probe.
    # Live-reloadable via config-KV `rpc offline_retry=` (the server's
    # apply hook rewrites the CLASS attribute, so every client in the
    # process follows without reconstruction).
    OFFLINE_RETRY = 2.0
    # Reconnect-probe jitter: each offline window is stretched by a
    # random factor in [1, 1 + OFFLINE_JITTER] so a restarted peer
    # sees the cluster's reconnect probes SPREAD over the window
    # instead of a thundering herd at the exact same instant (every
    # node marked it offline within the same failed fan-out).
    OFFLINE_JITTER = 0.5

    def __init__(self, host: str, port: int, cluster_key: bytes,
                 timeout: float = 30.0, tls=None):
        """tls: ssl.SSLContext for https:// cluster endpoints (see
        utils.certs.client_context_from_env); the HMAC signing below
        authenticates every call either way — TLS adds transport
        privacy (ref the reference's TLS-everywhere internode with
        JWT auth on top)."""
        from ..utils.dyntimeout import DynamicTimeout
        self.host = host
        self.port = port
        self.cluster_key = cluster_key
        self.tls = tls
        # Self-tuning timeout: slow peers stretch it, fast ones shrink
        # it back (ref cmd/dynamic-timeouts.go:35). The floor is 2.5s,
        # not the reference's 1s: a peer served by the event-loop
        # front door answers through loop→worker→loop hops whose tail
        # under CPU contention is scheduling-bound, and a spurious
        # sub-second timeout here MARKS THE PEER OFFLINE — one blip
        # then degrades every write to that node for OFFLINE_RETRY,
        # which is how a momentarily-busy box turns into MRF backlog.
        self.dyn_timeout = DynamicTimeout(timeout, minimum=2.5)
        self._offline_until = 0.0
        self._mu = threading.Lock()
        self._pool: list[http.client.HTTPConnection] = []

    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def is_online(self) -> bool:
        return time.monotonic() >= self._offline_until

    def _mark_offline(self) -> None:
        window = self.OFFLINE_RETRY * (
            1.0 + self.OFFLINE_JITTER * random.random())
        with self._mu:
            self._offline_until = time.monotonic() + window

    @property
    def timeout(self) -> float:
        return self.dyn_timeout.timeout

    def _get_conn(self, t: float | None = None,
                  ) -> tuple[http.client.HTTPConnection, bool]:
        """(connection, reused): callers retry once on a FRESH socket
        when a pooled one fails — a peer restart leaves every pooled
        keep-alive connection stale, and treating that as peer death
        knocks a healthy node out for OFFLINE_RETRY."""
        if t is None:
            t = self.timeout
        with self._mu:
            if self._pool:
                conn = self._pool.pop()
                conn.timeout = t  # used on (re)connect
                if conn.sock is not None:
                    conn.sock.settimeout(t)
                return conn, True
        return self._new_conn(t), False

    def _new_conn(self, t: float) -> http.client.HTTPConnection:
        if self.tls is not None:
            return http.client.HTTPSConnection(
                self.host, self.port, timeout=t, context=self.tls)
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=t)

    def _drop_pool(self) -> None:
        """Close every pooled connection (stale after a peer restart)."""
        with self._mu:
            pool, self._pool = self._pool, []
        for c in pool:
            c.close()

    def _put_conn(self, conn: http.client.HTTPConnection) -> None:
        with self._mu:
            if len(self._pool) < 8:
                self._pool.append(conn)
                return
        conn.close()

    def call(self, service: str, method: str, args: dict,
             payload: bytes = b"",
             timeout: float | None = None) -> tuple[dict, bytes]:
        """Returns (result_json, body_bytes); raises storage errors.

        `timeout` overrides the self-tuning data-plane timeout for
        calls that legitimately block server-side (e.g. a 3-30s trace
        long-poll) — such calls neither tune the dynamic timeout nor
        mark the peer offline on expiry, so a slow control-plane poll
        can never knock a healthy peer out of the data plane.

        By default the call runs on the async fabric (rpc/aio.py): the
        coroutine twin of the body below executes on the process-wide
        RPC event loop and this thread blocks on its future — same
        semantics, zero extra threads per in-flight call.
        MINIO_RPC_FABRIC=threaded keeps the pooled http.client path."""
        from . import aio
        if aio.fabric_async():
            return aio.bridge_call(self, service, method, args, payload,
                                   timeout)
        return self._call_threaded(service, method, args, payload,
                                   timeout)

    def _call_threaded(self, service: str, method: str, args: dict,
                       payload: bytes = b"",
                       timeout: float | None = None) -> tuple[dict, bytes]:
        """Legacy thread-blocking transport (MINIO_RPC_FABRIC=threaded
        and the paired fabric bench): one pooled http.client
        connection, this thread parked on the socket."""
        if not self.is_online():
            raise serr.DiskNotFound(f"{self.endpoint()} offline")
        # Per-peer wire faults (minio_tpu/faultinject): an injected
        # partition behaves exactly like an unreachable peer — the
        # health gate closes and reconnect probes (with jitter) take
        # over; slow-wire adds latency ahead of the socket I/O.
        from ..faultinject import FAULTS
        if FAULTS.enabled:
            _lat, _part = FAULTS.peer(self.endpoint())
            if _lat:
                time.sleep(_lat)
            if _part:
                self._mark_offline()
                raise serr.DiskNotFound(
                    f"{self.endpoint()} unreachable: injected "
                    "partition")
        # Deadline propagation (qos/deadline.py): a request whose
        # budget is already spent must not burn peer capacity — fail
        # here. Otherwise forward the REMAINING budget so the peer can
        # refuse expired work, and cap the socket timeout to it so a
        # slow peer call cancels when the deadline expires instead of
        # holding the handler for the full transport timeout.
        ddl = current_deadline()
        eff_timeout = timeout
        if ddl is not None:
            rem_s = ddl.remaining()
            if rem_s <= 0:
                record_expiry("rpc-client")
                raise DeadlineExceeded(
                    f"{service}/{method} to {self.endpoint()}: request "
                    "deadline exhausted before dispatch")
            base = timeout if timeout is not None else self.timeout
            eff_timeout = max(0.05, min(base, rem_s))
        args_json = json.dumps(args, sort_keys=True)
        ts = str(int(time.time()))
        body = frame(args_json.encode(), payload)
        headers = {
            "x-mtpu-ts": ts,
            "x-mtpu-auth": sign(self.cluster_key, f"{service}/{method}",
                                ts, args_json, payload),
            "Content-Length": str(len(body)),
        }
        if ddl is not None:
            headers[H_DEADLINE] = str(round(ddl.remaining_ms(), 3))
        # Distributed tracing: the caller's trace context rides a tiny
        # header; the peer opens a server-side span under it and ships
        # its subtree back in the reserved _trace_spans result key, so
        # a cross-node request stitches into ONE tree (the reference
        # has no cross-node stitching — its admin trace merges flat
        # per-node entries).
        from ..obs.span import current_span
        _cur = current_span()
        if _cur is not None:
            headers["x-mtpu-trace"] = f"{_cur.trace_id}:{_cur.span_id}"
        override = timeout is not None
        from .aio import CENSUS
        CENSUS.enter()
        try:
            conn, reused = self._get_conn(eff_timeout)
            # mtpu-lint: disable=R6 -- single-shot retry, not a loop: the continue requires reused=True and a fresh socket comes back reused=False, so it fires at most once; no backoff by design (a stale pool is instant-fail, the peer is healthy)
            while True:
                t0 = time.monotonic()
                logged = override
                resp = None
                try:
                    conn.request("POST",
                                 f"{RPC_PREFIX}/{service}/{method}",
                                 body=body, headers=headers)
                    resp = conn.getresponse()
                    rbody = resp.read()
                    if not override:
                        self.dyn_timeout.log_success(
                            time.monotonic() - t0)
                    logged = True
                    if resp.status != 200:
                        self._put_conn(conn)
                        raise wire_to_error(resp.status, rbody)
                    result_json, data = unframe(rbody)
                    self._put_conn(conn)
                    result = json.loads(result_json or b"{}")
                    if isinstance(result, dict):
                        remote_spans = result.pop("_trace_spans", None)
                        if remote_spans and _cur is not None and \
                                isinstance(remote_spans, list):
                            # Peer-supplied subtrees are untrusted
                            # input: prune to the local depth/fan-out/
                            # size bounds before they enter the trace
                            # ring.
                            from ..obs.span import sanitize_remote
                            for s in remote_spans[:8]:
                                sc = sanitize_remote(s)
                                if sc is not None:
                                    _cur.add_child(sc)
                    return result, data
                except (OSError, http.client.HTTPException,
                        ValueError) as e:
                    conn.close()
                    if (reused and resp is None and isinstance(
                            e, (http.client.RemoteDisconnected,
                                ConnectionResetError,
                                BrokenPipeError))):
                        # A stale pooled socket (peer restarted): the
                        # error arrived BEFORE any response started, on
                        # a reused keep-alive connection — the
                        # signature of a dead pool, not a dead peer.
                        # Retry ONCE on a fresh socket; errors after a
                        # response began (or any error on a fresh
                        # socket) never retry, so an RPC the peer may
                        # have executed is never re-sent.
                        self._drop_pool()
                        conn, reused = self._get_conn(eff_timeout)
                        continue
                    if ddl is not None and ddl.expired():
                        # The request DEADLINE elapsed, not the peer:
                        # the socket timeout above was deadline-capped,
                        # so say nothing about peer health — no offline
                        # mark, no dynamic-timeout tuning.
                        record_expiry("rpc-client")
                        raise DeadlineExceeded(
                            f"{service}/{method} to {self.endpoint()}: "
                            f"deadline expired mid-call: {e}")
                    # Only genuine ceiling hits tune the timeout up —
                    # an instant connection-refused says nothing about
                    # slowness.
                    if not logged and isinstance(e, (TimeoutError,
                                                     socket.timeout)):
                        self.dyn_timeout.log_failure()
                    if not override:
                        self._mark_offline()
                    raise serr.DiskNotFound(
                        f"{self.endpoint()} unreachable: {e}")
        finally:
            CENSUS.exit()

    def close(self) -> None:
        with self._mu:
            for c in self._pool:
                c.close()
            self._pool.clear()
        from . import aio
        aio.close_client(self)


class RPCRegistry:
    """Server side: named services exposing methods.

    A service is an object; exposed methods take (args: dict,
    payload: bytes) and return (result: dict, body: bytes).
    """

    def __init__(self, cluster_key: bytes):
        self.cluster_key = cluster_key
        self._services: dict[str, object] = {}

    def register(self, name: str, service: object) -> None:
        self._services[name] = service

    def handle(self, path: str, headers: dict[str, str],
               body: bytes) -> tuple[int, dict[str, str], bytes]:
        """Dispatch an RPC HTTP request; returns (status, headers, body)."""
        if not path.startswith(RPC_PREFIX + "/"):
            return 404, {}, b"not found"
        rest = path[len(RPC_PREFIX) + 1:]
        if "/" not in rest:
            return 404, {}, b"bad rpc path"
        service_name, method = rest.split("/", 1)
        try:
            args_bytes, payload = unframe(body)
        except ValueError:
            return 400, {}, b"bad rpc frame"
        ts = headers.get("x-mtpu-ts", "")
        try:
            if abs(time.time() - int(ts)) > MAX_SKEW:
                return 403, {}, b"rpc timestamp out of window"
        except ValueError:
            return 403, {}, b"bad rpc timestamp"
        args_json = args_bytes.decode("utf-8", "replace")
        want = sign(self.cluster_key, f"{service_name}/{method}", ts,
                    args_json, payload)
        if not hmac.compare_digest(want,
                                   headers.get("x-mtpu-auth", "")):
            return 403, {}, b"bad rpc signature"
        service = self._services.get(service_name)
        fn = getattr(service, f"rpc_{method}", None) if service else None
        if fn is None:
            return 404, {}, f"no method {service_name}/{method}".encode()
        try:
            args = json.loads(args_json)
            from ..obs.metrics2 import METRICS2
            METRICS2.inc("minio_tpu_v2_rpc_requests_total",
                         {"service": service_name, "method": method})
            # Remaining-budget propagation: refuse work whose caller
            # can no longer use the answer, and re-open the budget so
            # anything this handler calls in turn (disk I/O, nested
            # RPC) keeps decrementing the SAME deadline.
            ddl = None
            ddl_hdr = headers.get(H_DEADLINE, "")
            if ddl_hdr:
                try:
                    rem_ms = float(ddl_hdr)
                except ValueError:
                    rem_ms = None
                if rem_ms is not None:
                    if rem_ms <= 0:
                        record_expiry("rpc-server")
                        raise DeadlineExceeded(
                            f"{service_name}/{method}: caller deadline "
                            "already expired")
                    ddl = Deadline.from_remaining_ms(rem_ms)
            srv_span = None
            trace_hdr = headers.get("x-mtpu-trace", "")
            if trace_hdr and ":" in trace_hdr:
                # Server-side span under the caller's context; its
                # subtree (including local disk-op children) returns in
                # the reserved result key and grafts onto the caller's
                # tree (RPCClient.call pops it).
                from ..obs.span import Span
                tid, _, pid = trace_hdr.partition(":")
                srv_span = Span(f"rpc.server.{service_name}.{method}",
                                tid[:64], pid[:32])
            with deadline_scope(ddl):
                if srv_span is not None:
                    with srv_span:
                        result, rbody = fn(args, payload)
                    if isinstance(result, dict):
                        result = dict(result)
                        result["_trace_spans"] = [srv_span.to_dict()]
                else:
                    result, rbody = fn(args, payload)
            out = frame(json.dumps(result).encode(), rbody)
            return 200, {}, out
        except BaseException as e:  # noqa: BLE001 — serialized to peer
            status, ebody = error_to_wire(e)
            return status, {}, ebody
