"""S3-compatible API surface: SigV4 auth, routers, handlers, XML wire
format, error codes (ref cmd/api-router.go, cmd/object-handlers.go,
cmd/signature-v4.go)."""
