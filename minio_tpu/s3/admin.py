"""Admin API + health checks + Prometheus metrics
(ref cmd/admin-router.go, cmd/admin-handlers.go, cmd/healthcheck-router.go,
cmd/metrics-v2.go).

Routes (same port as S3, non-S3 prefixes):
    /minio-tpu/admin/v1/...    root-credential SigV4 JSON API
    /minio-tpu/health/live     liveness (200 always once HTTP is up)
    /minio-tpu/health/ready    readiness (object layer attached)
    /minio-tpu/health/cluster  quorum-aware (every set readable)
    /minio-tpu/metrics         Prometheus text exposition
"""

from __future__ import annotations

import json
import threading
import time

from .. import __version__


class Metrics:
    """Request/error/byte counters (ref cmd/http-stats.go,
    metrics-v2 collectors)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.start_time = time.time()
        self.requests: dict[str, int] = {}
        self.errors: dict[str, int] = {}
        self.rx_bytes = 0
        self.tx_bytes = 0

    def record(self, api: str, status: int, rx: int, tx: int) -> None:
        with self._mu:
            self.requests[api] = self.requests.get(api, 0) + 1
            if status >= 400:
                key = f"{api}:{status}"
                self.errors[key] = self.errors.get(key, 0) + 1
            self.rx_bytes += rx
            self.tx_bytes += tx

    def prometheus(self, layer) -> str:
        lines = [
            "# HELP minio_tpu_uptime_seconds Server uptime.",
            "# TYPE minio_tpu_uptime_seconds gauge",
            f"minio_tpu_uptime_seconds "
            f"{time.time() - self.start_time:.1f}",
            "# TYPE minio_tpu_rx_bytes_total counter",
            f"minio_tpu_rx_bytes_total {self.rx_bytes}",
            "# TYPE minio_tpu_tx_bytes_total counter",
            f"minio_tpu_tx_bytes_total {self.tx_bytes}",
            "# TYPE minio_tpu_requests_total counter",
        ]
        with self._mu:
            for api, n in sorted(self.requests.items()):
                lines.append(
                    f'minio_tpu_requests_total{{api="{api}"}} {n}')
            lines.append("# TYPE minio_tpu_errors_total counter")
            for key, n in sorted(self.errors.items()):
                api, _, status = key.rpartition(":")
                lines.append(
                    f'minio_tpu_errors_total{{api="{api}",'
                    f'status="{status}"}} {n}')
        if layer is not None:
            lines.append("# TYPE minio_tpu_disk_online gauge")
            lines.append("# TYPE minio_tpu_disk_total_bytes gauge")
            lines.append("# TYPE minio_tpu_disk_free_bytes gauge")
            for p_i, pool in enumerate(_pools(layer)):
                for s_i, es in enumerate(pool.sets):
                    for d_i, disk in enumerate(es.disks):
                        lbl = (f'pool="{p_i}",set="{s_i}",'
                               f'disk="{d_i}"')
                        try:
                            info = disk.disk_info()
                            lines.append(
                                f"minio_tpu_disk_online{{{lbl}}} 1")
                            lines.append(
                                f"minio_tpu_disk_total_bytes{{{lbl}}} "
                                f"{info.get('total', 0)}")
                            lines.append(
                                f"minio_tpu_disk_free_bytes{{{lbl}}} "
                                f"{info.get('free', 0)}")
                        except Exception:
                            lines.append(
                                f"minio_tpu_disk_online{{{lbl}}} 0")
        # Codec dispatch honesty counters: which device actually did the
        # RS math and the bitrot hashing (ops/batching.STATS/HH_STATS).
        from ..ops import batching
        for prefix, stats in (("rs", batching.STATS),
                              ("bitrot", batching.HH_STATS)):
            snap = stats.snapshot()
            for key, val in sorted(snap.items()):
                lines.append(
                    f"# TYPE minio_tpu_{prefix}_{key} counter")
                lines.append(f"minio_tpu_{prefix}_{key} {val}")
        return "\n".join(lines) + "\n"


def _pools(layer):
    if hasattr(layer, "pools"):
        return layer.pools
    if hasattr(layer, "sets"):
        class _P:
            sets = layer.sets
        return [_P]
    class _S:
        sets = [layer]
    return [_S]


class AdminHandlers:
    """JSON admin API over the object layer + IAM (root only)."""

    def __init__(self, server):
        self.server = server  # S3Server
        self._heal_seqs: dict[str, dict] = {}

    def handle(self, method: str, path: str, params: dict,
               body: bytes, access_key: str) -> tuple[int, bytes]:
        if access_key != self.server.access_key:
            return 403, json.dumps({"error": "admin requires root"
                                    }).encode()
        route = path.removeprefix("/minio-tpu/admin/v1/")
        fn = getattr(self, f"h_{route.replace('-', '_')}", None)
        if fn is None:
            return 404, json.dumps({"error": f"unknown: {route}"}).encode()
        try:
            out = fn(params, body)
            return 200, json.dumps(out, default=str).encode()
        except KeyError as e:
            return 404, json.dumps({"error": f"not found: {e}"}).encode()
        except (ValueError, TypeError) as e:
            return 400, json.dumps({"error": str(e)}).encode()

    # -- info / usage ---------------------------------------------------

    def h_info(self, p, body):
        layer = self.server.layer
        pools = []
        for pool in _pools(layer):
            sets = []
            for es in pool.sets:
                online = 0
                total = free = 0
                for d in es.disks:
                    try:
                        info = d.disk_info()
                        online += 1
                        total += info.get("total", 0)
                        free += info.get("free", 0)
                    except Exception:
                        pass
                sets.append({"disks": len(es.disks), "online": online,
                             "data": es.k, "parity": es.m,
                             "totalBytes": total, "freeBytes": free})
            pools.append({"sets": sets})
        from ..ops import batching
        out = {"version": __version__, "mode": "erasure",
               "pools": pools,
               "uptime": time.time() - self.server.metrics.start_time,
               # Device-vs-host dispatch honesty counters for the two
               # halves of the TPU data plane (RS coding + bitrot).
               "tpu": {"rs": batching.STATS.snapshot(),
                       "bitrot": batching.HH_STATS.snapshot()}}
        notif = self.server.notification
        if notif is not None:
            out["peers"] = notif.server_info_all()
        return out

    def h_datausage(self, p, body):
        # Serve the crawler's persisted cache when scanning runs
        # (ref DataUsageInfoHandler reading dataUsageCache); buckets
        # newer than the last cycle (and the no-crawler fallback) get a
        # synchronous walk producing the SAME entry shape.
        layer = self.server.layer
        crawler = getattr(self.server, "crawler", None)
        cached = crawler.data_usage() if crawler is not None else {}
        buckets: dict[str, dict] = dict(cached.get("buckets", {}))
        for b in layer.list_buckets():
            if b["name"] in buckets:
                continue
            objs = layer.list_objects(b["name"], max_keys=1_000_000)
            buckets[b["name"]] = {
                "objects": len(objs),
                "versions": len(objs),
                "size": sum(o.size for o in objs),
                "histogram": {},
            }
        return {"lastUpdate": cached.get("lastUpdate", 0.0),
                "buckets": buckets}

    def h_top(self, p, body):
        """`mc admin top` analog (obs/usage.py): ranked buckets and
        tenants over the usage windows, per-class top-K object keys
        and client addresses from the heavy-hitter sketches — joined
        with the crawler's at-rest census (`storedBytes`, so live
        traffic and footprint land in one report) and with the PR-4
        slowlog: a bucket's worst-request trace-id exemplar is
        annotated with its slowlog blame when the capture ring still
        holds it.  Root-only, so tenants/clients are un-redacted
        (the anonymous /minio-tpu/v2/usage surface redacts them)."""
        from ..obs.slowlog import SLOWLOG
        from ..obs.usage import USAGE
        n = int(p.get("n", "0") or 0)
        doc = USAGE.top(n if n > 0 else None)
        crawler = getattr(self.server, "crawler", None)
        sizes = crawler.bucket_sizes() if crawler is not None else {}
        captured = {e.get("requestID"): e
                    for e in SLOWLOG.entries(n=SLOWLOG.RING_SIZE)}
        for row in doc["buckets"]:
            if row["name"] in sizes:
                row["storedBytes"] = sizes[row["name"]]
            worst = row.get("worst")
            if worst:
                hit = captured.get(worst.get("traceId"))
                if hit is not None:
                    worst["slowlog"] = {
                        "blamedLayer": hit.get("blamedLayer", ""),
                        "statusCode": hit.get("statusCode", 0)}
        return doc

    # -- users / policies ----------------------------------------------

    def _iam(self):
        if self.server.iam is None:
            raise ValueError("IAM not configured")
        return self.server.iam

    def h_add_user(self, p, body):
        doc = json.loads(body)
        self._iam().add_user(doc["accessKey"], doc["secretKey"],
                             doc.get("policies", []))
        return {"ok": True}

    def h_list_users(self, p, body):
        return {"users": self._iam().list_users()}

    def h_remove_user(self, p, body):
        self._iam().remove_user(p["accessKey"])
        return {"ok": True}

    def h_set_user_policy(self, p, body):
        self._iam().set_user_policy(p["accessKey"],
                                    p["policies"].split(","))
        return {"ok": True}

    def h_add_policy(self, p, body):
        self._iam().set_policy(p["name"], json.loads(body))
        return {"ok": True}

    def h_list_policies(self, p, body):
        return {"policies": self._iam().list_policies()}

    def h_remove_policy(self, p, body):
        self._iam().delete_policy(p["name"])
        return {"ok": True}

    def h_set_sts_policy_map(self, p, body):
        """Map an external identity (ldap:<dn> / oidc:<sub>) to canned
        policies (ref mc admin policy attach --ldap; PolicyDBSet).
        Empty policies clears the mapping."""
        doc = json.loads(body)
        self._iam().set_sts_policy_map(doc["identity"],
                                       doc.get("policies", []))
        return {"ok": True}

    def h_get_sts_policy_map(self, p, body):
        return {"map": dict(self._iam().sts_policy_map)}

    def h_add_group(self, p, body):
        doc = json.loads(body)
        self._iam().add_group(doc["group"], doc.get("members", []),
                              doc.get("policies"))
        return {"ok": True}

    # -- heal -----------------------------------------------------------

    @staticmethod
    def _heal_sweep(layer, bucket: str, prefix: str, dry: bool):
        """Yield one result dict per healed object — shared by the
        synchronous handler and async sequences (ref healSequence's
        traverseAndHeal)."""
        def as_dict(r, name):
            out = {"object": name, "beforeOk": r.before_ok,
                   "afterOk": r.after_ok,
                   "healedDisks": r.healed_disks,
                   "dangling": r.dangling}
            if getattr(r, "skipped_lock", False):
                # Contended object (long-lived stream holds its lock):
                # requeued via MRF; reported so operators see it.
                out["skipped"] = "lock timeout"
            return out
        if bucket:
            layer.healer.heal_bucket(bucket)
            for o in layer.list_objects(bucket, prefix=prefix,
                                        max_keys=1_000_000):
                yield as_dict(layer.healer.heal_object_or_queue(
                    bucket, o.name, dry_run=dry), o.name)
        else:
            for r in layer.healer.heal_all():
                yield as_dict(r, f"{r.bucket}/{r.object_name}")

    def h_heal(self, p, body):
        return {"items": list(self._heal_sweep(
            self.server.layer, p.get("bucket", ""), p.get("prefix", ""),
            p.get("dryRun") == "true"))}

    # -- bucket quota (ref PutBucketQuotaConfigHandler,
    # cmd/admin-bucket-handlers.go) ------------------------------------

    def h_set_bucket_quota(self, p, body):
        doc = json.loads(body) if body else {}
        bm = self.server.bucket_meta
        if not doc.get("quota"):
            bm.update(p["bucket"], quota=None)  # clear
        else:
            bm.update(p["bucket"], quota={
                "quota": int(doc["quota"]),
                "quotaType": doc.get("quotaType", "hard")})
        return {"ok": True}

    def h_get_bucket_quota(self, p, body):
        return self.server.bucket_meta.get(p["bucket"]).quota or {}

    # -- replication remote targets (ref SetRemoteTargetHandler etc.,
    # cmd/admin-bucket-handlers.go) ------------------------------------

    def _replication(self):
        return self.server.handlers.replication

    def h_set_remote_target(self, p, body):
        doc = json.loads(body)
        arn = self._replication().targets.set_target(
            p["bucket"], doc["endpoint"], doc["target_bucket"],
            doc["access_key"], doc["secret_key"],
            bandwidth_limit=int(doc.get("bandwidth_limit") or 0))
        return {"arn": arn}

    def h_set_target_bandwidth(self, p, body):
        """Edit a target's replication rate cap (bytes/sec, 0 lifts
        it) — `mc admin bucket remote edit --bandwidth` analog (ref
        pkg/bandwidth LimitInBytesPerSecond)."""
        doc = json.loads(body)
        self._replication().targets.set_target_bandwidth(
            p["bucket"], doc["arn"], int(doc["bandwidth_limit"]))
        return {"ok": True}

    def h_list_remote_targets(self, p, body):
        targets = self._replication().targets.list_targets(p["bucket"])
        # Never return secrets over the wire (parity with madmin's
        # redacted listing).
        return {"targets": [{k: v for k, v in t.items()
                             if k != "secret_key"} for t in targets]}

    def h_remove_remote_target(self, p, body):
        self._replication().targets.remove_target(p["bucket"], p["arn"])
        return {"ok": True}

    def h_replication_stats(self, p, body):
        return dict(self._replication().stats)

    # -- heal sequences (ref healSequence state machine,
    # cmd/admin-heal-ops.go:353, allHealState:89) -----------------------

    MAX_HEAL_SEQS = 16          # finished sequences kept around
    MAX_SEQ_ITEMS = 10_000      # per-sequence result ring

    def _prune_heal_seqs(self) -> None:
        """Drop the oldest FINISHED sequences over the cap (the
        reference purges after keepHealSeqStateDuration)."""
        done = [(seq["finished"], tok) for tok, seq in
                self._heal_seqs.items() if seq["status"] != "running"]
        done.sort()
        while len(self._heal_seqs) > self.MAX_HEAL_SEQS and done:
            _, tok = done.pop(0)
            self._heal_seqs.pop(tok, None)

    def h_heal_start(self, p, body):
        """Kick off an async heal sweep; poll with heal-status?token=.
        The reference's POST /heal/... returns a clientToken the same
        way (ref cmd/admin-heal-ops.go:353)."""
        import threading
        import uuid as _uuid
        self._prune_heal_seqs()
        token = _uuid.uuid4().hex[:12]
        seq = {"status": "running", "items": [], "error": "",
               "scanned": 0, "healed": 0,
               "started": time.time(), "finished": 0.0}
        self._heal_seqs[token] = seq
        layer = self.server.layer
        bucket, prefix = p.get("bucket", ""), p.get("prefix", "")
        dry = p.get("dryRun") == "true"

        def run():
            try:
                for item in self._heal_sweep(layer, bucket, prefix, dry):
                    seq["scanned"] += 1
                    if item["healedDisks"]:
                        seq["healed"] += 1
                    seq["items"].append(item)
                    if len(seq["items"]) > self.MAX_SEQ_ITEMS:
                        del seq["items"][:self.MAX_SEQ_ITEMS // 2]
                seq["status"] = "done"
            except Exception as e:  # noqa: BLE001
                seq["status"] = "failed"
                seq["error"] = str(e)
            seq["finished"] = time.time()

        # mtpu-lint: disable=R1 -- heal sequence outlives the admin request that started it (polled via clientToken)
        threading.Thread(target=run, daemon=True,
                         name=f"heal-seq-{token}").start()
        return {"clientToken": token}

    def h_heal_status(self, p, body):
        seq = self._heal_seqs[p["token"]]  # KeyError -> 404
        return {"status": seq["status"], "error": seq["error"],
                "itemsScanned": seq["scanned"],
                "itemsHealed": seq["healed"],
                "items": seq["items"][-1000:]}

    # -- OBD / health info (ref cmd/healthinfo.go, admin /obdinfo;
    # pkg/smart, pkg/disk) ---------------------------------------------

    def h_obd_info(self, p, body):
        """Hardware + perf diagnostics bundle: cpu/mem/os plus a small
        per-disk write+read latency probe (ref the drive perf section
        of the OBD handler)."""
        import os as _os
        import platform

        info = {
            "os": {"platform": platform.platform(),
                   "python": platform.python_version()},
            "cpu": {"count": _os.cpu_count(),
                    "loadavg": list(_os.getloadavg())},
            "mem": self._meminfo(),
            "drives": [],
        }
        layer = self.server.layer
        probe = p.get("drivePerf") == "true"
        for pool in _pools(layer):
            for es in pool.sets:
                for d in es.disks:
                    ent = {"endpoint": getattr(d, "root",
                                               str(d))}
                    try:
                        ent.update(d.disk_info())
                        ent["online"] = True
                        if probe:
                            ent["perf"] = self._drive_perf(d)
                    except Exception as e:  # noqa: BLE001
                        ent["online"] = False
                        ent["error"] = str(e)
                    info["drives"].append(ent)
        return info

    @staticmethod
    def _meminfo() -> dict:
        out = {}
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    if k in ("MemTotal", "MemAvailable"):
                        out[k] = int(v.strip().split()[0]) * 1024
        except OSError:
            pass
        return out

    @staticmethod
    def _drive_perf(disk) -> dict:
        """4KiB write+read latency probe on one drive (ref the
        dperf-style measurement in the OBD drive section)."""
        payload = b"\0" * 4096
        path = "obd-perf-probe"
        t0 = time.perf_counter()
        disk.write_all(".minio.sys", f"tmp/{path}", payload)
        w_ms = (time.perf_counter() - t0) * 1000
        t0 = time.perf_counter()
        disk.read_all(".minio.sys", f"tmp/{path}")
        r_ms = (time.perf_counter() - t0) * 1000
        try:
            disk.delete(".minio.sys", f"tmp/{path}")
        except Exception:
            pass
        return {"writeLatencyMs": round(w_ms, 3),
                "readLatencyMs": round(r_ms, 3)}

    # -- profiling (ref admin /profiling/start, cmd/utils.go:230
    # globalProfiler — Python analog: cProfile) ------------------------

    def h_profiling_start(self, p, body):
        from ..utils.profiler import SamplingProfiler
        if getattr(self, "_profiler", None) is not None:
            raise ValueError("profiling already running")
        prof = SamplingProfiler(
            interval=float(p.get("intervalMs", "5")) / 1000.0)
        prof.start()
        self._profiler = prof
        out = {"ok": True}
        notif = self.server.notification
        if p.get("cluster") == "true" and notif is not None:
            # Cluster-wide profiling (ref peerRESTMethodStartProfiling).
            # A raising fan-out must not strand the local profiler in a
            # stuck "profiling already running" state: per-peer errors
            # degrade inside profiling_start_all, so anything RAISING
            # here is a caller-side fault — undo the local start.
            try:
                out["peers"] = notif.profiling_start_all(
                    float(p.get("intervalMs", "5")))
            except BaseException:
                prof.stop()
                self._profiler = None
                raise
        return out

    def h_profiling_stop(self, p, body):
        prof = getattr(self, "_profiler", None)
        if prof is None:
            raise ValueError("profiling not running")
        self._profiler = None
        out = {"profile": prof.stop()}
        notif = self.server.notification
        if p.get("cluster") == "true" and notif is not None:
            out["peers"] = notif.profiling_stop_all()
        return out

    def h_profile(self, p, body):
        """Continuous profiler (obs/loopmon.py): the always-on ~1%
        duty-cycle sampler's per-minute aggregate — top-N self-time
        rows plus pprof-style folded stacks ("f1;f2;f3 N", feed
        straight to flamegraph.pl), and the loopmon per-loop health
        census so a loop-stall investigation starts from ONE page.
        ``?n=`` rows (default 50), ``?minutes=`` window (default 5)."""
        from ..obs.loopmon import LOOPMON, ContinuousProfiler
        n = min(500, max(1, int(p.get("n", "50") or 50)))
        minutes = min(ContinuousProfiler.MINUTES_KEPT,
                      max(1, int(p.get("minutes", "5") or 5)))
        out = LOOPMON.profiler.report(top=n, minutes=minutes)
        out["loops"] = LOOPMON.snapshot()
        return out

    # -- bandwidth (ref pkg/bandwidth, admin /bandwidth route,
    # cmd/admin-router.go:217) -----------------------------------------

    def h_bandwidth(self, p, body):
        report = self.server.bandwidth.report()
        bucket = p.get("bucket", "")
        if bucket:
            report = {bucket: report.get(bucket, {
                "rxBytesWindow": 0, "txBytesWindow": 0,
                "rxRateBps": 0.0, "txRateBps": 0.0})}
        return {"buckets": report, "windowSeconds": 60}

    # -- remote tiers (ref admin tier APIs, cmd/tier.go) ---------------

    def _tiers(self):
        return self.server.handlers.tiers

    def h_add_tier(self, p, body):
        from ..bucket.tiering import TierError
        doc = json.loads(body)
        try:
            self._tiers().add(doc["name"], doc["endpoint"],
                              doc["bucket"], doc["access_key"],
                              doc["secret_key"],
                              doc.get("prefix", ""))
        except TierError as e:
            raise ValueError(str(e))
        return {"ok": True}

    def h_list_tiers(self, p, body):
        return {"tiers": self._tiers().list()}

    def h_remove_tier(self, p, body):
        from ..bucket.tiering import TierError
        try:
            self._tiers().remove(p["name"], layer=self.server.layer)
        except TierError as e:
            raise ValueError(str(e))
        return {"ok": True}

    # -- hot-object cache ----------------------------------------------

    def h_cache_stats(self, p, body):
        """Hot-object serving tier stats (cache/hotcache.py): tier
        occupancy, hit ratio, fill/invalidation counters."""
        from ..cache.hotcache import HOTCACHE
        return HOTCACHE.snapshot()

    # -- config KV (ref admin config APIs, cmd/admin-handlers-config-kv.go)

    def _config(self):
        if self.server.config is None:
            raise ValueError("config system not ready")
        return self.server.config

    def h_get_config(self, p, body):
        return {"config": self._config().dump()}

    def h_set_config_kv(self, p, body):
        # Unknown names / rejected values raise ValueError subclasses,
        # which handle() maps to 400.
        self._config().set_kv(body.decode("utf-8"))
        return {"ok": True, "restart": False}

    def h_del_config_kv(self, p, body):
        self._config().del_kv(body.decode("utf-8").strip())
        return {"ok": True}

    def h_config_history(self, p, body):
        return {"entries": self._config().history_ids()}

    def h_restore_config(self, p, body):
        self._config().restore(p["id"])
        return {"ok": True}

    # -- trace / console log (ref admin /trace streaming,
    # cmd/admin-router.go:199; console cmd/consolelogger.go) -----------

    def h_trace(self, p, body):
        """Long-poll: subscribe to the request-trace hub and collect
        entries for up to `timeout` seconds (default 3, cap 30). The
        reference streams indefinitely over chunked HTTP; a bounded
        collect keeps the admin API request/response.

        cluster=true additionally collects from every peer over the
        same window (ref peerRESTMethodTrace fan-in,
        cmd/admin-router.go:199)."""
        import threading as _threading
        timeout = min(float(p.get("timeout", "3") or 3), 30.0)
        notif = self.server.notification
        peer_entries: list = []
        collector = None
        if p.get("cluster") == "true" and notif is not None:
            # mtpu-lint: disable=R1 -- trace collection window is its own explicit timeout, not the request budget
            collector = _threading.Thread(
                target=lambda: peer_entries.extend(
                    notif.trace_all(timeout)), daemon=True)
            collector.start()
        entries = self.server.trace_hub.collect(timeout)
        if collector is not None:
            collector.join(timeout=timeout + 5)
            entries.extend(peer_entries)
            entries.sort(key=lambda e: e.get("time", 0)
                         if isinstance(e, dict) else 0)
        return {"entries": entries}

    def h_console_log(self, p, body):
        from ..logger import Logger
        n = min(int(p.get("n", "100") or 100), 10_000)
        return {"entries": [
            {"level": e.level, "time": e.time, "message": e.message,
             "source": e.source} for e in Logger.get().ring.tail(n)]}

    def h_audit_status(self, p, body):
        a = self.server.audit
        if a is None:
            return {"configured": False}
        return {"configured": True, "endpoint": a.endpoint,
                "sent": a.sent, "failed": a.failed,
                "dropped": a.dropped,
                "queued": a.queued() if hasattr(a, "queued") else 0}

    # -- slow-request log (obs/slowlog.py) ------------------------------

    def h_slowlog(self, p, body):
        """Tail the slow-request capture ring, filtered by blamed
        layer (`blame=disk`) and/or API class or name (`api=write`,
        `api=PUT-object`). Each entry carries the request's full span
        tree, its QoS admission/deadline data, and the per-layer blame
        breakdown — plus the last profile-on-slow burst when one ran."""
        from ..obs.slowlog import SLOWLOG
        # Clamp below too: n=0 would slice [-0:] (the whole ring) and
        # negative n an oldest-first head slice.
        n = min(max(1, int(p.get("n", "50") or 50)), SLOWLOG.RING_SIZE)
        out = {
            "entries": SLOWLOG.entries(n=n, blame=p.get("blame", ""),
                                       api=p.get("api", "")),
            "total": SLOWLOG.total,
            "thresholdsMs": SLOWLOG.thresholds(),
            "profileOnSlow": SLOWLOG.profile_on_slow,
        }
        if SLOWLOG.last_profile is not None:
            out["profile"] = SLOWLOG.last_profile
        return out

    def h_kernel_health(self, p, body):
        """Kernel dispatch health (obs/kernprof.py): per-backend state
        machine (device/native/xla-cpu/host with fail streaks + last
        failure cause) and cumulative dispatch/byte mix.  ``?probe=
        true`` runs one recovery probe per backend first — the manual
        'is the relay back yet?' lever (probes are tiny real
        dispatches; root-only surface, so no amplification risk)."""
        from ..obs.kernprof import KERNPROF
        out: dict = {}
        if p.get("probe") == "true":
            out["probed"] = KERNPROF.probe_all()
        out.update(KERNPROF.snapshot())
        return out

    def h_codec_plan(self, p, body):
        """Codec dispatch planner (ops/autotune.py): the live plan per
        (kernel, batch-size bucket), the measured per-lane crossover
        table (GiB/s + sample counts), probe-ladder results, backend
        health states, and the per-set device-affinity map with its
        per-device dispatch census (parallel/mesh.py).  ``?probe=
        true`` re-runs the probe ladder synchronously first — the
        manual 'is the crossover still right?' lever (probes are tiny
        real dispatches; root-only surface, no amplification risk)."""
        from ..ops.autotune import AUTOTUNE
        out: dict = {}
        if p.get("probe") == "true":
            # Keyed apart from snapshot()'s boolean "probed" flag.
            out["probeResults"] = AUTOTUNE.probe_ladder()
        out.update(AUTOTUNE.snapshot())
        try:
            from ..parallel.mesh import MESH_AFFINITY
            out["affinity"] = MESH_AFFINITY.snapshot()
        except Exception:
            out["affinity"] = {"nDevices": 1, "assignments": {},
                               "dispatches": {}}
        return out

    def h_incidents(self, p, body):
        """Incident bundles (obs/incidents.py): auto-frozen diagnosis
        state for every alert that reached firing.  Bare GET lists the
        ring (id + headline); ``?id=`` fetches one full JSON bundle —
        timeline window, slowlog entries + worst span tree, drive/MRF/
        backend census, fault plan, effective (redacted) config.
        Root-only, so drive endpoints stay un-redacted here."""
        from ..obs.incidents import INCIDENTS
        if p.get("id"):
            return INCIDENTS.get(p["id"])  # KeyError -> 404
        return {"incidents": INCIDENTS.list(),
                "captured": INCIDENTS.captured_total}

    def h_drive_health(self, p, body):
        """Admin view of the drive-health monitor (same shape as the
        unauthenticated /minio-tpu/v2/health/drives node endpoint, but
        with FULL drive endpoints — this surface is root-only)."""
        from ..obs.drivemon import DRIVEMON
        out = DRIVEMON.snapshot()
        out["mrf"] = self.server._mrf_stats()
        return out

    def h_recovery(self, p, body):
        """Boot-time crash-recovery report (storage/recovery.py): per
        erasure set, the staging residue found/cleaned, objects
        requeued for heal, MRF journal entries replayed, and the sweep
        duration — plus the journal's live census so an operator can
        see the durable backlog draining."""
        journals = []
        if self.server.layer is not None:
            for pool in _pools(self.server.layer):
                for es in pool.sets:
                    mrf = getattr(es, "mrf", None)
                    if mrf is not None and hasattr(mrf, "journal"):
                        journals.append(mrf.journal.stats())
        # Heal repair-traffic ledger: bytes moved per repair mode
        # (rs vs regen) and source (disk vs net) since boot — the
        # paired counters behind the REGEN class's bandwidth claim.
        from ..erasure.regen.repair import REPAIR_BYTES
        return {"sweeps": getattr(self.server, "recovery_reports", []),
                "journals": journals,
                "repair": REPAIR_BYTES.snapshot()}

    # -- runtime fault injection (minio_tpu/faultinject) ---------------

    def h_fault_inject(self, p, body):
        """Manage the runtime fault-injection plan.

        POST with a JSON plan body loads (replaces) the plan;
        ``?clear=true`` clears it; a bare GET/POST returns the active
        plan with per-rule seen/fired counters — the scenario
        matrices in tests/test_fault_harness.py drive exactly this
        surface."""
        from ..faultinject import FAULTS, FaultPlanError
        if p.get("clear") == "true":
            FAULTS.clear()
            return {"ok": True, "active": False}
        if body:
            try:
                doc = json.loads(body)
            except json.JSONDecodeError as e:
                raise ValueError(f"fault plan: {e}")
            try:
                FAULTS.load_plan(doc)
            except FaultPlanError as e:
                raise ValueError(str(e))
            return {"ok": True, "active": FAULTS.enabled,
                    "rules": len(doc.get("rules", []))}
        return FAULTS.snapshot()

    # -- locks ----------------------------------------------------------

    def h_top_locks(self, p, body):
        out = []
        reg = self.server.rpc_registry
        if reg is not None:
            svc = reg._services.get("lock")
            if svc is not None:
                out = svc.locker.top_locks()
        return {"locks": out}
