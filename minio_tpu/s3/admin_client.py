"""Admin API client SDK — the `madmin` analog (ref pkg/madmin, 5856
LoC: the Go client the reference's `mc admin` is built on). Wraps the
SigV4 S3Client against the `/minio-tpu/admin/v1/*` JSON routes so
tools and tests never hand-roll admin requests.
"""

from __future__ import annotations

import json
import urllib.parse

from .client import S3Client


class AdminError(Exception):
    def __init__(self, status: int, body: bytes):
        super().__init__(f"admin API {status}: {body[:200]!r}")
        self.status = status
        self.body = body


class AdminClient:
    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str):
        self._c = S3Client(host, port, access_key, secret_key)

    def _call(self, method: str, route: str, params: dict | None = None,
              body: bytes = b"") -> dict:
        query = urllib.parse.urlencode(params or {})
        r = self._c.request(method, f"/minio-tpu/admin/v1/{route}",
                            query=query, body=body)
        if r.status != 200:
            raise AdminError(r.status, r.body)
        return json.loads(r.body) if r.body else {}

    # -- info / usage ---------------------------------------------------

    def server_info(self) -> dict:
        return self._call("GET", "info")

    def data_usage(self) -> dict:
        return self._call("GET", "datausage")

    def top(self, n: int = 0) -> dict:
        """Workload attribution report (`mc admin top` analog): ranked
        buckets/tenants, per-class top-K keys/clients, stored-bytes
        join, worst-request trace exemplars."""
        return self._call("GET", "top", {"n": str(n)} if n else {})

    def obd_info(self, drive_perf: bool = False) -> dict:
        return self._call("GET", "obd-info",
                          {"drivePerf": "true"} if drive_perf else {})

    # -- users / policies -----------------------------------------------

    def add_user(self, access_key: str, secret_key: str,
                 policies: list[str] | None = None) -> None:
        self._call("POST", "add-user", body=json.dumps({
            "accessKey": access_key, "secretKey": secret_key,
            "policies": policies or []}).encode())

    def list_users(self) -> list:
        return self._call("GET", "list-users")["users"]

    def remove_user(self, access_key: str) -> None:
        self._call("POST", "remove-user", {"accessKey": access_key})

    def add_policy(self, name: str, policy: dict) -> None:
        self._call("POST", "add-policy", {"name": name},
                   json.dumps(policy).encode())

    def list_policies(self) -> list:
        return self._call("GET", "list-policies")["policies"]

    def set_user_policy(self, access_key: str,
                        policies: list[str]) -> None:
        self._call("POST", "set-user-policy",
                   {"accessKey": access_key,
                    "policies": ",".join(policies)})

    def set_sts_policy_map(self, identity: str,
                           policies: list[str]) -> None:
        """Attach canned policies to an external STS identity
        (``ldap:<dn>`` or ``oidc:<sub>``) — the `mc admin policy
        attach --ldap` analog. Empty list clears the mapping."""
        self._call("POST", "set-sts-policy-map", body=json.dumps({
            "identity": identity, "policies": policies}).encode())

    def get_sts_policy_map(self) -> dict:
        return self._call("GET", "get-sts-policy-map")["map"]

    # -- heal -----------------------------------------------------------

    def heal(self, bucket: str = "", prefix: str = "",
             dry_run: bool = False) -> list:
        p = {}
        if bucket:
            p["bucket"] = bucket
        if prefix:
            p["prefix"] = prefix
        if dry_run:
            p["dryRun"] = "true"
        return self._call("POST", "heal", p)["items"]

    def heal_start(self, bucket: str = "", prefix: str = "") -> str:
        p = {}
        if bucket:
            p["bucket"] = bucket
        if prefix:
            p["prefix"] = prefix
        return self._call("POST", "heal-start", p)["clientToken"]

    def heal_status(self, token: str) -> dict:
        return self._call("GET", "heal-status", {"token": token})

    # -- config ---------------------------------------------------------

    def get_config(self) -> dict:
        return self._call("GET", "get-config")["config"]

    def set_config_kv(self, line: str) -> None:
        self._call("POST", "set-config-kv", body=line.encode())

    def del_config_kv(self, spec: str) -> None:
        self._call("POST", "del-config-kv", body=spec.encode())

    def config_history(self) -> list:
        return self._call("GET", "config-history")["entries"]

    def restore_config(self, history_id: str) -> None:
        self._call("POST", "restore-config", {"id": history_id})

    # -- quota / replication / tiers ------------------------------------

    def set_bucket_quota(self, bucket: str, quota_bytes: int,
                         quota_type: str = "hard") -> None:
        body = b"{}" if not quota_bytes else json.dumps(
            {"quota": quota_bytes, "quotaType": quota_type}).encode()
        self._call("POST", "set-bucket-quota", {"bucket": bucket}, body)

    def get_bucket_quota(self, bucket: str) -> dict:
        return self._call("GET", "get-bucket-quota", {"bucket": bucket})

    def set_remote_target(self, bucket: str, endpoint: str,
                          target_bucket: str, access_key: str,
                          secret_key: str,
                          bandwidth_limit: int = 0) -> str:
        return self._call("POST", "set-remote-target",
                          {"bucket": bucket}, json.dumps({
                              "endpoint": endpoint,
                              "target_bucket": target_bucket,
                              "access_key": access_key,
                              "secret_key": secret_key,
                              "bandwidth_limit": bandwidth_limit,
                          }).encode())["arn"]

    def set_target_bandwidth(self, bucket: str, arn: str,
                             bandwidth_limit: int) -> None:
        """Replication bytes/sec cap for one target (0 lifts it)."""
        self._call("POST", "set-target-bandwidth", {"bucket": bucket},
                   json.dumps({"arn": arn,
                               "bandwidth_limit": bandwidth_limit,
                               }).encode())

    def list_remote_targets(self, bucket: str) -> list:
        return self._call("GET", "list-remote-targets",
                          {"bucket": bucket})["targets"]

    def remove_remote_target(self, bucket: str, arn: str) -> None:
        self._call("POST", "remove-remote-target",
                   {"bucket": bucket, "arn": arn})

    def add_tier(self, name: str, endpoint: str, bucket: str,
                 access_key: str, secret_key: str,
                 prefix: str = "") -> None:
        self._call("POST", "add-tier", body=json.dumps({
            "name": name, "endpoint": endpoint, "bucket": bucket,
            "access_key": access_key, "secret_key": secret_key,
            "prefix": prefix}).encode())

    def list_tiers(self) -> list:
        return self._call("GET", "list-tiers")["tiers"]

    def remove_tier(self, name: str) -> None:
        self._call("POST", "remove-tier", {"name": name})

    # -- observability --------------------------------------------------

    def trace(self, timeout: float = 3.0) -> list:
        return self._call("GET", "trace",
                          {"timeout": str(timeout)})["entries"]

    def console_log(self, n: int = 100) -> list:
        return self._call("GET", "console-log",
                          {"n": str(n)})["entries"]

    def profiling_start(self, interval_ms: float = 5.0) -> None:
        self._call("POST", "profiling-start",
                   {"intervalMs": str(interval_ms)})

    def profiling_stop(self) -> dict:
        return self._call("POST", "profiling-stop")["profile"]

    def bandwidth(self, bucket: str = "") -> dict:
        p = {"bucket": bucket} if bucket else {}
        return self._call("GET", "bandwidth", p)

    def cache_stats(self) -> dict:
        return self._call("GET", "cache-stats")

    def codec_plan(self, probe: bool = False) -> dict:
        """Codec dispatch planner view (ops/autotune.py): live plan,
        measured crossover table, probe results, device-affinity map.
        probe=True re-runs the probe ladder synchronously first."""
        p = {"probe": "true"} if probe else {}
        return self._call("GET", "codec-plan", p)

    def replication_stats(self) -> dict:
        return self._call("GET", "replication-stats")

    def top_locks(self) -> list:
        return self._call("GET", "top-locks")["locks"]

    # -- robustness -----------------------------------------------------

    def fault_inject(self, plan: dict | None = None,
                     clear: bool = False) -> dict:
        """Load (POST), clear (?clear=true), or inspect (bare GET —
        rules with seen/fired counters plus the registered crash-point
        inventory) the runtime fault plan."""
        if clear:
            return self._call("POST", "fault-inject", {"clear": "true"})
        if plan is not None:
            import json as _json
            return self._call("POST", "fault-inject",
                              body=_json.dumps(plan).encode())
        return self._call("GET", "fault-inject")

    def recovery(self) -> dict:
        """Boot-time crash-recovery report: per-set sweep results
        (staging residue GC'd, objects requeued, journal entries
        replayed) + the live durable-MRF journal census."""
        return self._call("GET", "recovery")
