"""Event-loop S3 front door: accept/parse/keep-alive for 10k+ sockets
on a handful of loop threads, with request EXECUTION handed to a worker
pool so every handler in ``s3/server.py`` (and the storage/erasure/
kernel layers below) stays synchronous and semantically unchanged.

The thread-per-connection front end (``ThreadingHTTPServer``) costs one
OS stack per socket — idle keep-alive connections are exactly as
expensive as active ones, which caps realistic concurrency in the low
thousands.  This module replaces only L1: the listener, HTTP/1.1
framing, and body/response streaming live on asyncio event loops; the
moment a request head is parsed the connection hands an ``_AsyncTxn``
to the shared request core (``S3Server._serve_one``), which runs on a
bounded ``ThreadPoolExecutor`` exactly like a handler thread used to.

Key boundaries (why each piece looks the way it does):

- **BodyBridge** (async→sync): request bodies stream from the socket
  into the erasure pipeline through a bounded chunk queue.  The loop
  feeds chunks as they arrive and pauses the transport past the high
  water mark, so backpressure propagates to the client socket instead
  of buffering the object in memory; the worker blocks on a condition
  variable with the same 120s stall deadline the threaded server's
  socket timeout enforced.  Chunks pass through as the ``bytes``
  objects asyncio delivered (split via memoryview) — no re-buffering.

- **Expect: 100-continue**: a request carrying it dispatches BEFORE the
  body exists; the interim 100 goes out lazily on the bridge's first
  read.  QoS admission (``route_qos``) therefore runs — and can shed —
  before the client uploads a byte.

- **Slot release is tied to connection teardown**: ``connection_lost``
  abandons the bridge (a worker blocked mid-body wakes with
  ``ConnectionResetError``, unwinds through the core's finally, and
  releases its admission slot) and fails the response-drain waiters
  (a detached streaming response runs its finish callback).  An
  aborted client can never leak a slot.

- **Streaming responses park a connection, not a thread**: when a
  handler returns an iterator body, the worker detaches and the
  connection's loop pulls each chunk via ``run_in_executor`` under the
  request's copied contextvars (deadline/lane/span parent survive the
  hop); between chunks a slow reader holds only the connection and its
  bounded write buffer.

- **Keep-alive hygiene after an early response** (shed, burnt
  deadline, auth failure): the connection is left in a READABLE state
  per Content-Length — small unread remainders are discarded by the
  loop before the next request parses; large ones answer with
  ``Connection: close``; an Expect body that was never solicited
  closes too (the only framing-safe option once the client may or may
  not send it).  Nothing desyncs the next pipelined request.

Tuning knobs (env):
- ``MINIO_FRONT_DOOR``          async (default) | threaded
- ``MINIO_FRONT_DOOR_WORKERS``  request-execution threads (default 64)
- ``MINIO_LOOP_THREADS``        event-loop threads (default 1)
- ``MINIO_SHUTDOWN_DRAIN``      SIGTERM drain seconds (default 10)
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from email.utils import formatdate
from http.client import responses as _REASONS

# Bridge flow control: pause the transport past HIGH, resume at LOW.
BRIDGE_HIGH_WATER = 512 * 1024
BRIDGE_LOW_WATER = 128 * 1024
# Pipelined bytes buffered while a request executes, before the
# transport pauses (the next request's head + change).
PIPELINE_BUF_MAX = 1 * 1024 * 1024
# A request head larger than this is an attack or a bug.
MAX_HEAD_BYTES = 64 * 1024
# Chunked requests that are NOT streamed object PUTs (sub-resource
# writes, POSTs) buffer to completion like their Content-Length twins;
# with no declared length this cap is what bounds them.
CHUNKED_BUF_MAX = 64 * 1024 * 1024
# Same stall deadline the threaded server's socket timeout enforced.
STALL_TIMEOUT_S = 120.0
# Idle keep-alive reaper period (sweep granularity, not precision).
SWEEP_PERIOD_S = 15.0
# Lingering-close window: how long a half-closed connection keeps
# discarding an abandoned body before the socket is cut.
LINGER_S = 3.0

_ALLOWED_METHODS = ("GET", "PUT", "POST", "DELETE", "HEAD", "OPTIONS")


def _metrics():
    from ..obs.metrics2 import METRICS2
    return METRICS2


class BodyBridge:
    """Bounded async→sync reader: the loop feeds socket chunks, the
    worker consumes them with ``read(n)`` (the repo's ``Reader``
    contract: up to n bytes, ``b""`` at EOF).  Implements the lazy
    100-continue and the backpressure handshake."""

    def __init__(self, conn: "_HttpConn", length: int,
                 expect_continue: bool):
        """length < 0 means UNKNOWN (chunked Transfer-Encoding): EOF is
        decoder-driven via finish() instead of a byte countdown."""
        self._conn = conn
        self.length = length
        self.expect = expect_continue
        self._chunks: collections.deque = collections.deque()
        self._buffered = 0
        self.received = 0     # wire bytes fed by the loop
        self._consumed = 0    # bytes handed to the worker
        self._cv = threading.Condition()
        self._eof = length == 0
        self._error: BaseException | None = None
        self._pause_hint = False
        self.continue_requested = False
        self.started = False  # any body byte arrived

    # -- loop side -----------------------------------------------------

    def feed(self, data) -> bool:
        """Append a chunk; returns True when the transport should
        pause (buffered past the high water mark)."""
        with self._cv:
            self.started = True
            self._chunks.append(data)
            self._buffered += len(data)
            self.received += len(data)
            if 0 <= self.length <= self.received:
                self._eof = True
            pause = self._buffered >= BRIDGE_HIGH_WATER
            if pause:
                self._pause_hint = True
            self._cv.notify_all()
            return pause

    def fail(self, exc: BaseException) -> None:
        """Abandon (connection teardown): wake readers with the error."""
        with self._cv:
            if self._error is None:
                self._error = exc
            self._cv.notify_all()

    # -- worker side ---------------------------------------------------

    @property
    def touched(self) -> bool:
        """A body byte arrived, or we solicited one with a 100."""
        return self.started or self.continue_requested

    def finish(self) -> None:
        """Chunked bodies: the loop-side decoder saw the terminal
        chunk — every wire byte of this body has been fed (the
        length countdown in feed() cannot apply when length < 0)."""
        with self._cv:
            self._eof = True
            self._cv.notify_all()

    def unread(self) -> int:
        """Body bytes the worker has not consumed (buffered or still
        on the wire)."""
        if self.length < 0:
            # Chunked: either the wire framing completed (reuse-safe —
            # a buffered-but-unconsumed remainder dies with the bridge,
            # the socket stream itself is clean) or the remainder is
            # unknowable and the connection must close.
            with self._cv:
                return 0 if self._eof else (1 << 30)
        return max(0, self.length - self._consumed)

    def read(self, n: int) -> bytes:
        if n <= 0:
            return b""
        want_continue = False
        with self._cv:
            if self.expect and not self.started \
                    and not self.continue_requested:
                self.continue_requested = True
                want_continue = True
        if want_continue:
            # Lazy 100: admission/shed already happened (or the caller
            # is the handler proper) — only now solicit the body.
            self._conn.send_continue_threadsafe()
        deadline = time.monotonic() + STALL_TIMEOUT_S
        with self._cv:
            while True:
                # Buffered data and a completed body are served even
                # after teardown (a drain of an already-received tail
                # must not fail); the error only gates WAITING.
                if self._chunks:
                    chunk = self._chunks.popleft()
                    if len(chunk) > n:
                        mv = memoryview(chunk)
                        self._chunks.appendleft(mv[n:])
                        chunk = mv[:n]
                    self._buffered -= len(chunk)
                    self._consumed += len(chunk)
                    resume = (self._pause_hint
                              and self._buffered <= BRIDGE_LOW_WATER)
                    if resume:
                        self._pause_hint = False
                    out = chunk if isinstance(chunk, bytes) \
                        else bytes(chunk)
                    if resume:
                        self._conn.resume_rx_threadsafe()
                    return out
                if self._eof:
                    return b""
                if self._error is not None:
                    err = self._error
                    raise ConnectionResetError(
                        f"client body aborted: {err}") from err
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        "client stopped sending the request body")
                self._cv.wait(min(left, 5.0))


class _ChunkedTooLarge(ValueError):
    """Decoded chunked body exceeded the caller's cap."""


class _ChunkedTEParser:
    """Incremental HTTP/1.1 chunked Transfer-Encoding decoder (loop
    side): feed() takes wire bytes and returns zero-copy memoryview
    slices of the DECODED payload (the views alias the fed ``bytes``
    object, so no copy happens until a consumer materializes one).

    Raises ValueError on framing violations and _ChunkedTooLarge when
    the decoded size passes ``max_decoded`` — an unbounded chunked
    upload must not get unbounded buffering just because it never
    declared a Content-Length."""

    MAX_LINE = 8192          # size-line bytes (hex size + extensions)
    MAX_TRAILER = 16 * 1024  # total trailer-section bytes

    def __init__(self, max_decoded: int):
        self._max = max_decoded
        self._line = bytearray()   # partial size/trailer line
        self._state = "size"       # size | data | data_end | trailer
        self._left = 0             # payload bytes still owed this chunk
        self._end_cr = False       # saw the CR of a chunk's CRLF tail
        self._trailer_len = 0
        self.decoded = 0
        self.done = False

    def feed(self, data: bytes) -> tuple[list, bytes]:
        """-> (decoded_slices, leftover): leftover is the wire tail
        past the terminal CRLF (the next pipelined request's bytes),
        always b"" until ``done``."""
        out: list = []
        mv = memoryview(data)
        i, n = 0, len(data)
        while i < n and not self.done:
            if self._state == "size":
                nl = data.find(b"\n", i)
                if nl < 0:
                    self._line += data[i:]
                    if len(self._line) > self.MAX_LINE:
                        raise ValueError("chunk size line too long")
                    return out, b""
                self._line += data[i:nl]
                i = nl + 1
                line = bytes(self._line).strip()
                self._line.clear()
                if len(line) > self.MAX_LINE:
                    raise ValueError("chunk size line too long")
                size_s = line.split(b";", 1)[0].strip()
                if not size_s:
                    raise ValueError("empty chunk size")
                size = int(size_s, 16)  # ValueError on junk
                if size == 0:
                    self._state = "trailer"
                else:
                    if self.decoded + size > self._max:
                        raise _ChunkedTooLarge(
                            "chunked body exceeds cap")
                    self._left = size
                    self._state = "data"
            elif self._state == "data":
                take = min(self._left, n - i)
                out.append(mv[i:i + take])
                self.decoded += take
                self._left -= take
                i += take
                if self._left == 0:
                    self._state = "data_end"
            elif self._state == "data_end":
                c = data[i]
                i += 1
                if c == 0x0A:
                    self._end_cr = False
                    self._state = "size"
                elif c == 0x0D and not self._end_cr:
                    self._end_cr = True
                else:
                    raise ValueError("bad chunk data terminator")
            else:  # trailer
                nl = data.find(b"\n", i)
                if nl < 0:
                    self._line += data[i:]
                    self._bound_trailer(n - i)
                    return out, b""
                line = bytes(self._line) + data[i:nl]
                self._bound_trailer(nl + 1 - i)
                self._line.clear()
                i = nl + 1
                if not line.strip():
                    self.done = True
        return out, bytes(data[i:]) if self.done else b""

    def _bound_trailer(self, grew: int) -> None:
        self._trailer_len += grew
        if self._trailer_len > self.MAX_TRAILER:
            raise ValueError("chunked trailer too large")


class _AsyncTxn:
    """The transport adapter ``S3Server._serve_one`` drives for one
    request on an async connection.  Writes are threadsafe enqueues to
    the loop; backpressure blocks the worker (with the stall deadline)
    via the protocol's pause/resume_writing callbacks."""

    DRAIN_MAX = 1 * 1024 * 1024

    def __init__(self, conn: "_HttpConn", command: str, raw_path: str,
                 query: str, headers: dict, body: bytes,
                 body_stream: BodyBridge | None, content_length: int):
        self.conn = conn
        self.command = command
        self.raw_path = raw_path
        self.query = query
        self.headers = headers
        self.body = body
        self.body_stream = body_stream
        self.content_length = content_length  # -1 = chunked (unknown)
        self.rx_length = max(content_length, 0)
        self.client_ip = conn.client_ip
        self.close_after = False
        self.detached = False
        self._pending_head: bytes | None = None

    # -- body hygiene --------------------------------------------------

    def prepare_body_cleanup(self) -> bool:
        """Decide how the unconsumed body tail keeps the connection
        framed; returns True when the response must carry
        ``Connection: close``.  The actual discard (when safe) happens
        on the loop after the response completes."""
        br = self.body_stream
        if br is None:
            return False
        left = br.unread()
        if left <= 0:
            return False
        if br.expect and not br.touched:
            # We never sent 100 and no byte arrived: the client MAY
            # still send the body (RFC 7231 allows it), so the only
            # framing-safe reuse answer is no reuse at all.
            self.close_after = True
            return True
        if left > self.DRAIN_MAX:
            self.close_after = True
            return True
        # Small tail: the loop discards it before parsing the next
        # request (conn.request_complete).
        return False

    def set_close(self) -> None:
        self.close_after = True

    # -- response plumbing ---------------------------------------------

    def send_head(self, status: int, headers: list) -> None:
        reason = _REASONS.get(status, "Unknown")
        out = [f"HTTP/1.1 {status} {reason}\r\n"
               f"Date: {formatdate(usegmt=True)}\r\n"]
        for k, v in headers:
            out.append(f"{k}: {v}\r\n")
        out.append("\r\n")
        # Held back until the first body write (or request end): head
        # + buffered body leave as ONE loop enqueue and one TCP
        # segment — at 10k connections the cross-thread wakeups are a
        # real cost.
        self._pending_head = "".join(out).encode("latin-1", "replace")

    def flush_head(self) -> None:
        head, self._pending_head = self._pending_head, None
        if head is not None:
            self.conn.send_from_worker(head)

    # Small buffered responses coalesce into the COMPLETION enqueue
    # (one cross-thread signal per request instead of two — futex
    # wakeups are expensive on this class of sandboxed kernel).
    COALESCE_MAX = 256 * 1024

    def write(self, data) -> None:
        if not data:
            return
        head, self._pending_head = self._pending_head, None
        if head is not None:
            data = head + (data if isinstance(data, bytes)
                           else bytes(data))
            if len(data) <= self.COALESCE_MAX:
                self._pending_head = data  # ride the completion
                return
        self.conn.send_from_worker(data)

    def stream_response(self, resp, raw_path: str, finish_fn,
                        root_span) -> bool:
        """Hand the iterator body to the connection's loop: the loop
        pulls chunks through the worker pool under the request's
        copied context, so a slow reader parks this connection — not
        the worker thread that built the response.  Returns True
        (detached); the drain task owns finish_fn from here."""
        self.flush_head()
        ctx = contextvars.copy_context()
        # This pooled worker thread is about to return to the pool:
        # clear the root span's contextvar token HERE (same thread
        # that set it) so the span context cannot leak into the next
        # request this thread serves; the copied `ctx` above still
        # carries the span for the chunk pulls.
        if root_span is not None:
            root_span.detach_context()
        self.detached = True
        self.conn.start_drain_threadsafe(resp.body, raw_path, finish_fn,
                                         ctx, self.close_after)
        return True


def _next_chunk(it):
    """One producer step, run on the worker pool under the request's
    copied context; None marks exhaustion (StopIteration must not
    cross the executor boundary)."""
    try:
        return next(it)
    except StopIteration:
        return None


class _HttpConn(asyncio.Protocol):
    """One keep-alive client connection: HTTP/1.1 head parsing, body
    framing (buffered / bridged), response sequencing, pipelining
    buffer, and teardown-tied cleanup."""

    def __init__(self, front: "AsyncFrontDoor", loop):
        self.front = front
        self._loop = loop
        self.transport = None
        self.client_ip = "?"
        self._buf = bytearray()
        self._state = "head"          # head | body | stream | wait
        self._head: tuple | None = None  # (method, path, query, headers)
        self._need = 0                # buffered-body bytes still wanted
        self._bridge: BodyBridge | None = None
        self._body_left = 0           # wire bytes of the current body
        self._chunked: _ChunkedTEParser | None = None
        self._chunk_acc: bytearray | None = None  # buffered-mode body
        self._discard_left = 0        # post-response tail to discard
        self._continue_sent = False
        self._closed = False
        self._draining = False        # close after the current response
        self._rx_paused = False
        self._writable = threading.Event()
        self._writable.set()
        self._paused = False
        self._drain_waiters: list = []
        self._in_flight = False
        self._finish_cb = None        # teardown safety for detached fns
        self._peer_eof = False        # half-closed with a response owed
        self.last_activity = time.monotonic()

    # ---- asyncio.Protocol callbacks (loop thread) --------------------

    def connection_made(self, transport) -> None:
        self.transport = transport
        peer = transport.get_extra_info("peername")
        if peer:
            self.client_ip = peer[0]
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                1)
            except OSError:
                pass
        transport.set_write_buffer_limits(high=1 << 20, low=1 << 18)
        self.front.conn_opened(self)

    def connection_lost(self, exc) -> None:
        self._closed = True
        self._writable.set()  # unblock any worker mid-write
        if self._bridge is not None:
            self._bridge.fail(exc or ConnectionResetError(
                "connection closed"))
        for fut in self._drain_waiters:
            if not fut.done():
                fut.set_exception(ConnectionResetError(
                    "connection closed"))
        self._drain_waiters.clear()
        self._paused = False
        # Teardown safety net: a DETACHED streaming response whose
        # drain task already died (or never ran) must still account
        # its request and release its admission slot.
        cb, self._finish_cb = self._finish_cb, None
        if cb is not None:
            # mtpu-lint: disable=R1 -- request context died with the connection; finish_fn only accounts and releases
            self.front.stream_pool.submit(_safe_call, cb)
        self.front.conn_closed(self)

    def pause_writing(self) -> None:
        self._paused = True
        self._writable.clear()

    def resume_writing(self) -> None:
        self._paused = False
        self._writable.set()
        waiters, self._drain_waiters = self._drain_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    def data_received(self, data: bytes) -> None:
        self.last_activity = time.monotonic()
        if self._state == "linger":
            return  # closing: the tail is discarded wholesale
        if self._discard_left > 0:
            if len(data) <= self._discard_left:
                self._discard_left -= len(data)
                return
            data = data[self._discard_left:]
            self._discard_left = 0
        if self._chunked is not None:
            self._feed_chunked(data)
            return
        if self._body_left > 0 and self._bridge is not None:
            if len(data) <= self._body_left:
                self._body_left -= len(data)
                if self._bridge.feed(data) and not self._rx_paused:
                    self._rx_paused = True
                    self.transport.pause_reading()
                return
            head, rest = data[:self._body_left], data[self._body_left:]
            self._body_left = 0
            self._bridge.feed(head)
            data = rest
        self._buf += data
        if self._state in ("head", "body"):
            self._process_buf()
        elif len(self._buf) > PIPELINE_BUF_MAX and not self._rx_paused:
            # Pipelined bytes beyond the cap: make the client wait for
            # the current response instead of buffering its backlog.
            self._rx_paused = True
            self.transport.pause_reading()

    def eof_received(self):
        if self._chunked is not None:
            # Torn mid-chunk: a streamed PUT's reader gets the error
            # (its worker answers and releases the slot); a buffered
            # chunked request never dispatched — just close.
            self._chunked = None
            self._chunk_acc = None
            if self._bridge is not None:
                self._bridge.fail(ConnectionResetError(
                    "client half-closed mid-body"))
            return False
        if self._bridge is not None and self._body_left > 0:
            self._bridge.fail(ConnectionResetError(
                "client half-closed mid-body"))
            return False
        if self._in_flight or self._buf:
            # Half-close AFTER a complete request (shutdown(SHUT_WR)
            # then read — Go clients' CloseWrite): the response is
            # still owed; keep the transport open and close once the
            # request completes.
            self._peer_eof = True
            return True
        return False  # idle half-close: just close

    # ---- parsing (loop thread) ---------------------------------------

    def _process_buf(self) -> None:
        while True:
            if self._state == "head":
                idx = self._buf.find(b"\r\n\r\n")
                if idx < 0:
                    if len(self._buf) > MAX_HEAD_BYTES:
                        self._reject(431, "request head too large")
                    elif self._buf[:1] and not self._buf[:1].isalpha():
                        self._reject(400, "malformed request line")
                    return
                head = bytes(self._buf[:idx])
                del self._buf[:idx + 4]
                if not self._parse_head(head):
                    return
                if self._state != "body":
                    return  # dispatched (stream or empty body)
            if self._state == "body":
                if len(self._buf) < self._need:
                    return
                body = bytes(self._buf[:self._need])
                del self._buf[:self._need]
                self._need = 0
                method, path, query, headers, cl = self._head
                self._dispatch(method, path, query, headers, body,
                               None, cl)
                return

    def _parse_head(self, head: bytes) -> bool:
        """Parse one request head from `head`; returns False when the
        connection was rejected."""
        try:
            text = head.decode("latin-1")
            lines = text.split("\r\n")
            method, target, version = lines[0].split(" ", 2)
        except (ValueError, IndexError):
            self._reject(400, "malformed request line")
            return False
        if not version.startswith("HTTP/1."):
            self._reject(505, "unsupported HTTP version")
            return False
        if method not in _ALLOWED_METHODS:
            self._reject(501, f"method {method} not implemented")
            return False
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, sep, v = line.partition(":")
            if not sep:
                self._reject(400, "malformed header line")
                return False
            headers[k.strip().lower()] = v.strip()
        raw_path, _, query = target.partition("?")
        try:
            cl = int(headers.get("content-length", 0) or 0)
            if cl < 0:
                raise ValueError
        except ValueError:
            self._reject(400, "bad Content-Length")
            return False
        te = headers.get("transfer-encoding", "").strip().lower()
        chunked = te == "chunked"
        if te and not chunked:
            # Only the terminal "chunked" coding is implemented (what
            # real SDKs send; gzip'd request bodies are not a thing S3
            # clients do).
            self._reject(501, f"transfer encoding {te} unsupported")
            return False
        if chunked and "content-length" in headers:
            # RFC 7230 §3.3.3: a message with both is a smuggling
            # vector — never guess, reject.
            self._reject(400, "both Content-Length and "
                              "Transfer-Encoding")
            return False
        if chunked and version == "HTTP/1.0":
            self._reject(400, "chunked framing requires HTTP/1.1")
            return False
        if version == "HTTP/1.0" and \
                headers.get("connection", "").lower() != "keep-alive":
            self._draining = True
        if headers.get("connection", "").lower() == "close":
            self._draining = True
        expect = "100-continue" in headers.get("expect", "").lower()
        server = self.front.server
        is_s3 = not raw_path.startswith("/minio-tpu/")
        if chunked:
            return self._begin_chunked(method, raw_path, query,
                                       headers, expect, is_s3)
        # Bridge (stream) only object PUTs: large ones like the
        # threaded path, plus ANY carrying Expect (admission must run
        # before the upload). Everything else — STS POSTs, multipart
        # completes, sub-resource writes — buffers exactly like the
        # threaded front end, so handlers that read req.body before
        # route()'s drain point keep their semantics.
        want_stream = (is_s3 and cl > 0 and method == "PUT"
                       and "/" in raw_path.lstrip("/")
                       and (expect
                            or cl >= server.stream_threshold))
        if want_stream:
            self._bridge = BodyBridge(self, cl, expect)
            self._body_left = cl
            self._continue_sent = False
            # Bytes already buffered (client didn't wait) feed through.
            if self._buf:
                take = min(len(self._buf), self._body_left)
                self._body_left -= take
                self._bridge.feed(bytes(self._buf[:take]))
                del self._buf[:take]
            self._dispatch(method, raw_path, query, headers, b"",
                           self._bridge, cl)
            return True
        if cl > 0:
            if expect:
                # Buffered mode still honors the handshake — solicit
                # the body now, before waiting for it.
                self._send_continue()
            self._head = (method, raw_path, query, headers, cl)
            self._need = cl
            self._state = "body"
            return True
        self._dispatch(method, raw_path, query, headers, b"", None, 0)
        return True

    def _begin_chunked(self, method: str, raw_path: str, query: str,
                       headers: dict, expect: bool, is_s3: bool) -> bool:
        """Set up chunked-body decode. Object PUTs stream through the
        BodyBridge with length -1 (the decoder drives EOF) straight
        into the erasure pipeline — the zero-copy path real SDKs'
        streaming-SigV4 uploads take. Everything else buffers the
        decoded body to completion (capped) and dispatches exactly
        like a Content-Length request."""
        stream = (is_s3 and method == "PUT"
                  and "/" in raw_path.lstrip("/"))
        if stream:
            from .server import MAX_OBJECT_SIZE
            self._chunked = _ChunkedTEParser(MAX_OBJECT_SIZE + 1)
            self._chunk_acc = None
            self._bridge = BodyBridge(self, -1, expect)
            self._continue_sent = False
            self._dispatch(method, raw_path, query, headers, b"",
                           self._bridge, -1)
        else:
            if expect:
                self._send_continue()
            self._chunked = _ChunkedTEParser(CHUNKED_BUF_MAX)
            self._chunk_acc = bytearray()
            self._head = (method, raw_path, query, headers, -1)
            self._state = "chunk"
        if self._buf:
            # Bytes the client sent behind the head feed through.
            data0 = bytes(self._buf)
            self._buf.clear()
            self._feed_chunked(data0)
        return True

    def _feed_chunked(self, data: bytes) -> None:
        """Run wire bytes through the chunked decoder (loop thread)."""
        parser = self._chunked
        try:
            slices, leftover = parser.feed(data)
        except ValueError as e:
            self._chunked = None
            if self._chunk_acc is not None or self._bridge is None:
                # Nothing dispatched yet: protocol-level reject.
                self._chunk_acc = None
                status = 413 if isinstance(e, _ChunkedTooLarge) else 400
                self._reject(status, f"bad chunked framing: {e}")
            else:
                # A streamed PUT is mid-flight: fail its body reader
                # (the worker answers the error and releases its slot)
                # and stop trusting this connection's framing.
                self._bridge.fail(e)
                self._draining = True
            return
        if self._chunk_acc is not None:
            for piece in slices:
                self._chunk_acc += piece
        elif self._bridge is not None:
            pause = False
            for piece in slices:
                # memoryview slices of `data`: the bridge consumer
                # materializes exactly once, on read.
                if self._bridge.feed(piece):
                    pause = True
            if pause and not self._rx_paused:
                self._rx_paused = True
                self.transport.pause_reading()
        if parser.done:
            self._chunked = None
            if leftover:
                self._buf += leftover  # next pipelined request
            if self._chunk_acc is not None:
                body = bytes(self._chunk_acc)
                self._chunk_acc = None
                method, raw_path, query, headers, _cl = self._head
                self._dispatch(method, raw_path, query, headers, body,
                               None, len(body))
            elif self._bridge is not None:
                self._bridge.finish()

    def _reject(self, status: int, why: str) -> None:
        """Protocol-level error: answer (when possible) and close."""
        _metrics().inc("minio_tpu_v2_conn_parse_errors_total")
        reason = _REASONS.get(status, "Bad Request")
        body = f"{why}\n".encode()
        try:
            self.transport.write(
                (f"HTTP/1.1 {status} {reason}\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 "Connection: close\r\n\r\n").encode() + body)
            self.transport.close()
        except Exception:  # noqa: BLE001 - already tearing down
            pass
        self._state = "closed"

    # ---- dispatch to the worker pool (loop thread) -------------------

    def _dispatch(self, method, raw_path, query, headers, body,
                  bridge, cl) -> None:
        self._state = "wait"
        self._in_flight = True
        txn = _AsyncTxn(self, method, raw_path, query, headers, body,
                        bridge, cl)
        pool = (self.front.rpc_pool
                if raw_path.startswith("/minio-tpu/rpc/")
                else self.front.pool)
        try:
            # mtpu-lint: disable=R1 -- front-door boundary: a FRESH request context is opened inside _serve_one, there is none to carry
            pool.submit(self.front.run_request, self, txn)
        except RuntimeError:  # pool shut down mid-accept
            self._in_flight = False
            self._reject(503, "server shutting down")

    # ---- worker-facing plumbing (worker thread) ----------------------

    # One enqueue never exceeds this: a multi-MiB buffered body (hot
    # cache hit) written in one transport.write() would land in the
    # write buffer WHOLE before pause_writing can matter — at 10k
    # connections a fleet of slow readers would pin conns x body-size
    # of RSS. Chunking with a writability wait between chunks bounds
    # each connection near the transport's high-water mark (the
    # threaded path got the same bound from blocking socket writes).
    WRITE_CHUNK = 256 * 1024

    def send_from_worker(self, data) -> None:
        if len(data) <= self.WRITE_CHUNK:
            self._send_one(data)
            return
        mv = memoryview(data)
        for off in range(0, len(mv), self.WRITE_CHUNK):
            self._send_one(bytes(mv[off:off + self.WRITE_CHUNK]))

    def _send_one(self, data) -> None:
        if not self._writable.wait(STALL_TIMEOUT_S):
            raise ConnectionResetError("client stopped reading "
                                       "(write stalled)")
        if self._closed:
            raise ConnectionResetError("connection closed")
        try:
            self._loop.call_soon_threadsafe(self._tx, data)
        except RuntimeError:
            raise ConnectionResetError("event loop stopped")

    def send_continue_threadsafe(self) -> None:
        try:
            self._loop.call_soon_threadsafe(self._send_continue)
        except RuntimeError:
            pass

    def resume_rx_threadsafe(self) -> None:
        try:
            self._loop.call_soon_threadsafe(self._maybe_resume)
        except RuntimeError:
            pass

    def complete_from_worker(self, close: bool,
                             tail: bytes | None = None) -> None:
        try:
            self._loop.call_soon_threadsafe(
                self._finish_and_complete, close, tail)
        except RuntimeError:
            pass

    def _finish_and_complete(self, close: bool,
                             tail: bytes | None) -> None:
        if tail:
            self._tx(tail)
        self.request_complete(close)

    def start_drain_threadsafe(self, body_iter, raw_path, finish_fn,
                               ctx, close_after) -> None:
        self._finish_cb = finish_fn
        try:
            self._loop.call_soon_threadsafe(
                self._spawn_drain, body_iter, raw_path, finish_fn, ctx,
                close_after)
        except RuntimeError:
            # Loop gone: account the request here; connection is dead.
            self._finish_cb = None
            _safe_call(getattr(body_iter, "close", lambda: None))
            _safe_call(finish_fn)

    # ---- loop-side helpers -------------------------------------------

    def _tx(self, data) -> None:
        if not self._closed and self.transport is not None:
            self.transport.write(data)

    def _send_continue(self) -> None:
        if not self._continue_sent and not self._closed:
            self._continue_sent = True
            if self._bridge is not None:
                self._bridge.started = True
            self.transport.write(b"HTTP/1.1 100 Continue\r\n\r\n")

    def _maybe_resume(self) -> None:
        if self._rx_paused and not self._closed:
            self._rx_paused = False
            self.transport.resume_reading()

    def _force_close(self) -> None:
        if not self._closed:
            try:
                self.transport.abort()
            except Exception:  # noqa: BLE001
                pass

    def request_complete(self, close: bool) -> None:
        """The response for the in-flight request is fully queued:
        restore framing (discard any small body tail), then either
        close or go parse the next pipelined request."""
        self._in_flight = False
        self._finish_cb = None
        if self._closed:
            return
        tail = 0
        if self._bridge is not None:
            # Wire bytes still owed for this body; anything the loop
            # already fed the bridge left the socket stream, so only
            # the un-received remainder threatens the framing.
            tail = self._body_left
            if self._bridge.length < 0 and self._bridge.unread() > 0:
                # Chunked body not fully framed: the remainder is
                # unknowable, so the only safe exit is the lingering
                # close below (prepare_body_cleanup already forced
                # Connection: close for this case).
                tail = max(tail, 1)
            self._bridge = None
        self._chunked = None
        self._chunk_acc = None
        self._body_left = 0
        if self._peer_eof and (tail > 0 or not self._buf):
            # The peer already half-closed and nothing of use remains:
            # finish the write side and be done. (With a complete
            # PIPELINED request still buffered — sendall(A+B) then
            # CloseWrite — fall through and answer it first; a body
            # tail, by contrast, can never complete after EOF.)
            self.transport.close()
            self._state = "closed"
            return
        if close or self._draining:
            if tail > 0:
                # Lingering close: the client may still be sending the
                # body — an immediate close() would turn its unread
                # bytes into a TCP RST that can destroy the queued
                # response. Half-close (FIN after the response
                # flushes), discard whatever still arrives, and cut
                # the cord shortly after.
                self._state = "linger"
                try:
                    if self.transport.can_write_eof():
                        self.transport.write_eof()
                except (OSError, RuntimeError):
                    pass
                self._maybe_resume()
                self._loop.call_later(LINGER_S, self._force_close)
                return
            self.transport.close()
            self._state = "closed"
            return
        if tail > 0:
            self._discard_left = tail
        self._continue_sent = False
        self._state = "head"
        self.last_activity = time.monotonic()
        self._maybe_resume()
        if self._buf:
            self._process_buf()

    def _spawn_drain(self, body_iter, raw_path, finish_fn, ctx,
                     close_after) -> None:
        task = self._loop.create_task(self._drain_response(
            body_iter, raw_path, finish_fn, ctx, close_after))
        self.front.track_task(task)

    async def _drain_response(self, body_iter, raw_path, finish_fn,
                              ctx, close_after) -> None:
        # `finish_fn` ownership: this task and connection_lost's
        # safety net both run on THIS loop, so whoever still finds
        # self._finish_cb set owns the accounting call — exactly one
        # of them submits it (a double finish would double-release
        # the admission slot).
        """Pump a streaming response body to the socket: each chunk is
        produced on the worker pool under the request's copied context
        (shard-read spans still attach, deadline/lane semantics hold),
        written, then awaited against the transport's flow control —
        a slow reader parks here, holding no thread."""
        loop = self._loop
        ok = True
        pending = None
        try:
            while True:
                pending = loop.run_in_executor(
                    self.front.stream_pool, ctx.run, _next_chunk,
                    body_iter)
                chunk = await pending
                pending = None
                if chunk is None:
                    break
                if not chunk:
                    continue
                if self._closed:
                    raise ConnectionResetError("connection closed")
                self.transport.write(chunk)
                await self._wait_writable()
        except (BrokenPipeError, ConnectionResetError):
            ok = False
        except asyncio.CancelledError:
            ok = False
        except Exception as e:  # noqa: BLE001
            # Mid-stream decode/auth failure AFTER the 200 went out:
            # abort the connection so the client sees a short body,
            # never a clean success (same policy as the threaded path).
            ok = False
            from ..logger import Logger
            Logger.get().log_once(
                f"streaming GET {raw_path} aborted mid-body: "
                f"{type(e).__name__}: {e}", "s3-stream-abort")
        finally:
            owns_finish = self._finish_cb is not None
            self._finish_cb = None
            # Producer cleanup + request accounting run OFF the loop:
            # generator close walks engine finally blocks (disk I/O,
            # pipeline teardown) and finish_fn records slowlog/trace.
            # mtpu-lint: disable=R1 -- cleanup of a finished request; its context is carried inside the closure via ctx
            self.front.stream_pool.submit(
                _close_and_finish, pending, body_iter,
                finish_fn if owns_finish else None)
            if ok:
                self.request_complete(close_after)
            elif not self._closed:
                # abort(), not close(): a peer that stopped READING is
                # the usual reason we are here, and close() waits for
                # the unflushable write buffer — the connection would
                # sit in the census forever (reap skips in-flight).
                self.transport.abort()
                self._state = "closed"

    async def _wait_writable(self) -> None:
        if not self._paused or self._closed:
            return
        fut = self._loop.create_future()
        self._drain_waiters.append(fut)
        await asyncio.wait_for(fut, STALL_TIMEOUT_S)

    # ---- sweep hooks (loop thread) -----------------------------------

    def idle_for(self, now: float) -> float:
        return now - self.last_activity

    def reap_if_idle(self, now: float, timeout: float) -> None:
        """Close connections with nothing in flight that have been
        silent past the keep-alive timeout (the threaded server's
        idle reaper, amortized into a periodic sweep)."""
        if self._closed or self._in_flight:
            return
        if self.idle_for(now) > timeout:
            try:
                self.transport.close()
            except Exception:  # noqa: BLE001
                pass


def raise_nofile_limit(cap: int = 65536) -> int:
    """Best-effort RLIMIT_NOFILE soft→hard raise: a 10k-connection
    front door (or loadgen fleet) dies at the default 1024 soft limit
    otherwise. Returns the effective soft limit (0 = unknown)."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = cap if hard == resource.RLIM_INFINITY else min(cap, hard)
        if want > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
            soft = want
        return soft
    except (ImportError, ValueError, OSError):
        return 0


def _safe_call(fn) -> None:
    try:
        fn()
    except Exception:  # noqa: BLE001 - teardown best effort
        pass


def _close_and_finish(pending, body_iter, finish_fn) -> None:
    """Off-loop cleanup for a detached streaming response: wait out a
    producer step still running (a generator cannot be closed while
    executing), close it, then run the request-finish accounting
    (None when connection teardown already owns that call)."""
    if pending is not None:
        try:
            pending.result(timeout=STALL_TIMEOUT_S)
        except Exception:  # noqa: BLE001 - producer died; close anyway
            pass
    close = getattr(body_iter, "close", None)
    if close is not None:
        _safe_call(close)
    if finish_fn is not None:
        _safe_call(finish_fn)


class AsyncFrontDoor:
    """Owns the listen socket, the loop threads, the worker pool, and
    the connection census; ``S3Server.start`` boots one of these unless
    ``MINIO_FRONT_DOOR=threaded``."""

    def __init__(self, server, cert_manager=None, workers: int = 0,
                 loop_threads: int = 0, keepalive_timeout: float = 120.0):
        import os
        self.server = server
        self.cert_manager = cert_manager
        self.keepalive_timeout = keepalive_timeout
        workers = workers or int(os.environ.get(
            "MINIO_FRONT_DOOR_WORKERS", "0") or 0) or 64
        loop_threads = loop_threads or int(os.environ.get(
            "MINIO_LOOP_THREADS", "0") or 0) or 1
        self.pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="s3-worker")
        # Peer RPC (storage reads, locks, control plane) rides the
        # same port but NOT the same executor: the RPC client's
        # self-tuning timeout shrinks toward 1s against fast local
        # peers, so a storage RPC queued behind a burst of S3 work
        # would time out, trip the peer health gate, and fast-fail a
        # whole node's shards for the retry window — a distributed
        # GET's parity fallback must never starve behind front-door
        # load.
        self.rpc_pool = ThreadPoolExecutor(
            max_workers=max(8, workers // 4),
            thread_name_prefix="s3-rpc")
        # Detached streaming-response chunk pulls get their own small
        # pool too: under a read burst every `pool` worker can be
        # parked in a QoS admission WAIT — if the chunk pulls queued
        # behind them, the streaming GETs HOLDING the contended slots
        # could not progress to release them (priority inversion; the
        # waiters would burn their deadlines and shed).
        self.stream_pool = ThreadPoolExecutor(
            max_workers=max(8, workers // 4),
            thread_name_prefix="s3-stream")
        self._n_loops = max(1, loop_threads)
        self._loops: list = []
        self._threads: list[threading.Thread] = []
        self._tasks: list = []
        self._lsock: socket.socket | None = None
        self._lsocks: list[socket.socket] = []  # SO_REUSEPORT, per loop
        self.reuseport = False
        self._mu = threading.Lock()
        self._conns: set[_HttpConn] = set()
        self._accept_pending = 0
        self._accepted_total = 0
        self._next_loop = 0
        self._running = False

    # -- lifecycle ------------------------------------------------------

    def start(self, host: str, port: int) -> int:
        import os
        raise_nofile_limit()
        # Multi-loop accept via SO_REUSEPORT: each loop thread owns
        # its OWN listen socket bound to the same port, so the KERNEL
        # load-spreads incoming connections across loops — no accept
        # handoff, no cross-loop self-pipe wakeup per connection.
        # Falls back to the single-socket round-robin accept loop when
        # the option is unavailable (or MINIO_REUSEPORT=off).
        want_reuseport = (
            hasattr(socket, "SO_REUSEPORT")
            and os.environ.get("MINIO_REUSEPORT", "on").strip().lower()
            not in ("off", "0", "no"))
        if want_reuseport:
            try:
                bind_port = port
                for _ in range(self._n_loops):
                    s = socket.socket(socket.AF_INET,
                                      socket.SOCK_STREAM)
                    try:
                        s.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
                        s.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEPORT, 1)
                        s.bind((host, bind_port))
                        s.listen(1024)
                        s.setblocking(False)
                    except OSError:
                        s.close()
                        raise
                    self._lsocks.append(s)
                    # port 0: later sockets join the resolved port.
                    bind_port = self._lsocks[0].getsockname()[1]
            except OSError:
                for s in self._lsocks:
                    try:
                        s.close()
                    except OSError:
                        pass
                self._lsocks = []
        self.reuseport = bool(self._lsocks)
        if not self._lsocks:
            self._lsock = socket.socket(socket.AF_INET,
                                        socket.SOCK_STREAM)
            self._lsock.setsockopt(socket.SOL_SOCKET,
                                   socket.SO_REUSEADDR, 1)
            self._lsock.bind((host, port))
            self._lsock.listen(1024)
            self._lsock.setblocking(False)
        bound = (self._lsocks[0] if self._lsocks
                 else self._lsock).getsockname()[1]
        self._running = True
        ready = threading.Barrier(self._n_loops + 1)
        for i in range(self._n_loops):
            loop = asyncio.new_event_loop()
            self._loops.append(loop)
            # mtpu-lint: disable=R1 -- long-lived event-loop thread; request context is opened per request on the worker pool
            t = threading.Thread(target=self._run_loop,
                                 args=(loop, ready), daemon=True,
                                 name=f"s3-loop-{i}")
            t.start()
            self._threads.append(t)
        ready.wait(timeout=10)
        if self._lsocks:
            # Every loop accepts from its own socket into itself.
            for i in range(self._n_loops):
                self._call_on(i, self._start_accept_on, i)
        else:
            # Loop 0 owns accept; connections spread round-robin.
            self._call_on(0, self._start_accept)
        for i in range(self._n_loops):
            self._call_on(i, self._start_sweep, self._loops[i])
        # Health plane: every front-door loop heartbeats under loopmon
        # (obs/loopmon.py) — scheduling lag, census, stall captures.
        from ..obs.loopmon import LOOPMON
        for i in range(self._n_loops):
            LOOPMON.register(f"s3-{i}", self._loops[i])
        return bound

    def _run_loop(self, loop, ready) -> None:
        asyncio.set_event_loop(loop)
        try:
            ready.wait(timeout=10)
        except threading.BrokenBarrierError:
            pass
        loop.run_forever()
        # Drain callbacks scheduled during shutdown, then close.
        try:
            loop.run_until_complete(asyncio.sleep(0))
        except Exception:  # noqa: BLE001
            pass
        loop.close()

    def _call_on(self, idx: int, fn, *args) -> None:
        self._loops[idx].call_soon_threadsafe(fn, *args)

    def _start_accept(self) -> None:
        loop = self._loops[0]
        self.track_task(loop.create_task(
            self._accept_loop(loop, self._lsock, pinned=False)))

    def _start_accept_on(self, idx: int) -> None:
        loop = self._loops[idx]
        self.track_task(loop.create_task(
            self._accept_loop(loop, self._lsocks[idx], pinned=True)))

    def _start_sweep(self, loop) -> None:
        self.track_task(loop.create_task(self._sweep_loop(loop)))

    async def _accept_loop(self, loop, lsock, pinned: bool) -> None:
        """`pinned`: SO_REUSEPORT mode — this loop owns `lsock` and
        every connection it accepts; otherwise the single listener
        round-robins accepted sockets across all loops."""
        while self._running:
            try:
                sock, _addr = await loop.sock_accept(lsock)
            except asyncio.CancelledError:
                break
            except OSError as e:
                if not self._running:
                    break
                # Transient accept errors (EMFILE under a connection
                # burst, ECONNABORTED from a racing RST) must not kill
                # the front door — log, breathe, retry. Only a closed
                # listener (shutdown) exits.
                import errno
                if e.errno in (errno.EBADF, errno.ENOTSOCK):
                    break
                from ..logger import Logger
                Logger.get().log_once(
                    f"front door: accept failed: {e}", "fd-accept")
                await asyncio.sleep(0.05)
                continue
            with self._mu:
                self._accept_pending += 1
                self._accepted_total += 1
            _metrics().inc("minio_tpu_v2_connections_accepted_total")
            self._publish_gauges()
            if pinned:
                # The kernel already picked this loop: establish
                # in-place, zero handoff.  track_task keeps a strong
                # reference — the loop holds tasks only weakly, and an
                # untracked _establish could be garbage-collected
                # mid-handshake with its exception never observed.
                self.track_task(loop.create_task(
                    self._establish(sock, loop)))
                continue
            target = self._loops[self._next_loop % self._n_loops]
            self._next_loop += 1
            if target is loop:
                # Same loop (the 1-loop default): a direct task skips
                # the threadsafe self-pipe round trip per accept.
                self.track_task(loop.create_task(
                    self._establish(sock, target)))
            else:
                self.track_task(asyncio.run_coroutine_threadsafe(
                    self._establish(sock, target), target))

    async def _establish(self, sock, loop) -> None:
        """Runs on the connection's OWN loop: TLS handshake (when
        configured) + protocol hookup.  The ssl context is read at
        accept time so certificate hot-reload keeps working."""
        try:
            ssl_ctx = (self.cert_manager.context
                       if self.cert_manager is not None else None)
            await loop.connect_accepted_socket(
                lambda: _HttpConn(self, loop), sock, ssl=ssl_ctx,
                ssl_handshake_timeout=10.0 if ssl_ctx else None)
        except Exception:  # noqa: BLE001 - bad handshake/racing close
            _metrics().inc("minio_tpu_v2_conn_parse_errors_total")
            try:
                sock.close()
            except OSError:
                pass
        finally:
            with self._mu:
                self._accept_pending -= 1
            self._publish_gauges()

    async def _sweep_loop(self, loop) -> None:
        while self._running:
            await asyncio.sleep(SWEEP_PERIOD_S)
            now = time.monotonic()
            with self._mu:
                mine = [c for c in self._conns if c._loop is loop]
            for conn in mine:
                conn.reap_if_idle(now, self.keepalive_timeout)
            # Pool gauges go stale without connection churn (they only
            # publish on open/close); the sweep keeps them honest on
            # an idle server (rate-limiter dedupes across loops).
            self._publish_gauges()

    # -- request execution (worker pool) -------------------------------

    def run_request(self, conn: _HttpConn, txn: _AsyncTxn) -> None:
        try:
            self.server._serve_one(txn)
        except Exception as e:  # noqa: BLE001 - never kill the worker
            from ..logger import Logger
            Logger.get().log_once(
                f"front door: request crashed: "
                f"{type(e).__name__}: {e}", "front-door")
            txn.close_after = True
        finally:
            if not txn.detached:
                # Anything still held back (coalesced small response,
                # HEAD-only head) rides the completion enqueue: one
                # cross-thread signal finishes the request.
                tail, txn._pending_head = txn._pending_head, None
                conn.complete_from_worker(txn.close_after, tail)

    # -- census ---------------------------------------------------------

    def conn_opened(self, conn: _HttpConn) -> None:
        with self._mu:
            self._conns.add(conn)
        self._publish_gauges()

    def conn_closed(self, conn: _HttpConn) -> None:
        with self._mu:
            self._conns.discard(conn)
        self._publish_gauges()

    def open_connections(self) -> int:
        with self._mu:
            return len(self._conns)

    # Gauge publishing is rate-limited: at connection-churn rates the
    # two registry writes per open/close event are measurable, and a
    # gauge only needs to be right when somebody reads it.
    GAUGE_PUBLISH_S = 0.1

    def _publish_gauges(self, force: bool = False) -> None:
        now = time.monotonic()
        schedule_flush = False
        with self._mu:
            limited = (not force
                       and now - getattr(self, "_gauges_at", 0.0)
                       < self.GAUGE_PUBLISH_S)
            if limited:
                # Trailing flush so the LAST event of a churn burst
                # still lands (a gauge stuck on a pre-close value
                # would read as leaked connections).
                if not getattr(self, "_flush_scheduled", False):
                    self._flush_scheduled = True
                    schedule_flush = True
            else:
                self._gauges_at = now
                n, pend = len(self._conns), self._accept_pending
        if limited:
            if schedule_flush:
                try:
                    self._loops[0].call_soon_threadsafe(
                        self._loops[0].call_later,
                        self.GAUGE_PUBLISH_S * 1.2, self._flush_gauges)
                except (RuntimeError, IndexError):
                    with self._mu:
                        self._flush_scheduled = False
            return
        m = _metrics()
        m.set_gauge("minio_tpu_v2_open_connections", None, n)
        m.set_gauge("minio_tpu_v2_accept_queue_depth", None, pend)
        # Per-pool thread census: splits the timeline's flat thread
        # count so a stalled loop and an exhausted pool read
        # differently in mtpu_top.  Busy = spawned threads minus the
        # executor's idle semaphore (CPython internals, guarded — a
        # missing attribute reads as an all-idle pool, never a crash).
        for pname, pool in (("worker", self.pool),
                            ("rpc", self.rpc_pool),
                            ("stream", self.stream_pool)):
            threads = len(getattr(pool, "_threads", ()) or ())
            sem = getattr(pool, "_idle_semaphore", None)
            idle = getattr(sem, "_value", threads)
            m.set_gauge("minio_tpu_v2_pool_threads",
                        {"pool": pname}, threads)
            m.set_gauge("minio_tpu_v2_pool_threads_busy",
                        {"pool": pname},
                        max(0, threads - min(idle, threads)))

    def _flush_gauges(self) -> None:
        with self._mu:
            self._flush_scheduled = False
        self._publish_gauges(force=True)

    def track_task(self, task) -> None:
        with self._mu:
            self._tasks = [t for t in self._tasks if not t.done()]
            self._tasks.append(task)

    # -- shutdown -------------------------------------------------------

    def stop(self, drain_s: float = 10.0) -> None:
        """Graceful drain: stop accepting, let in-flight requests
        finish within ``drain_s``, then abort stragglers and stop the
        loops."""
        self._running = False
        # Heartbeats first: a loopmon task still pending when a loop
        # closes would log "Task was destroyed but it is pending!".
        from ..obs.loopmon import LOOPMON
        for i in range(len(self._loops)):
            LOOPMON.unregister(f"s3-{i}")
        for s in [*self._lsocks, self._lsock]:
            if s is None:
                continue
            try:
                s.close()
            except OSError:
                pass
        self._lsocks = []
        # Close idle connections now; flag busy ones to close on
        # response completion.
        with self._mu:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn._loop.call_soon_threadsafe(self._drain_conn, conn)
            except RuntimeError:
                pass
        deadline = time.monotonic() + max(0.0, drain_s)
        while time.monotonic() < deadline:
            with self._mu:
                busy = any(c._in_flight for c in self._conns)
            if not busy:
                break
            time.sleep(0.05)
        with self._mu:
            leftovers = list(self._conns)
        for conn in leftovers:
            try:
                conn._loop.call_soon_threadsafe(self._abort_conn, conn)
            except RuntimeError:
                pass
        for loop in self._loops:
            try:
                loop.call_soon_threadsafe(self._shutdown_loop, loop)
            except RuntimeError:
                pass
        for t in self._threads:
            t.join(timeout=5)
        self.pool.shutdown(wait=False, cancel_futures=True)
        self.rpc_pool.shutdown(wait=False, cancel_futures=True)
        self.stream_pool.shutdown(wait=False, cancel_futures=True)
        self._publish_gauges()

    @staticmethod
    def _drain_conn(conn: _HttpConn) -> None:
        conn._draining = True
        if not conn._in_flight and not conn._closed:
            try:
                conn.transport.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _abort_conn(conn: _HttpConn) -> None:
        if not conn._closed:
            try:
                conn.transport.abort()
            except Exception:  # noqa: BLE001
                pass

    def _shutdown_loop(self, loop) -> None:
        with self._mu:
            mine = [t for t in self._tasks
                    if getattr(t, "get_loop", lambda: None)() is loop]
        for task in mine:
            task.cancel()
        loop.stop()
