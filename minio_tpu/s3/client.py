"""Minimal SigV4 S3 client over stdlib http.client — used by the test
suite (the reference drives its API tests with signed requests from
cmd/test-utils_test.go) and by tools; intentionally independent from the
server-side request path except for sigv4.sign_request."""

from __future__ import annotations

import http.client
import urllib.parse
from dataclasses import dataclass

from . import sigv4


@dataclass
class S3ClientResponse:
    status: int
    headers: dict[str, str]
    body: bytes


class S3Client:
    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 tls: "object | None" = None):
        """tls: an ssl.SSLContext (see utils.certs.client_context) to
        speak HTTPS; None = plaintext."""
        self.host = host
        self.port = port
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.tls = tls

    def request(self, method: str, path: str, query: str = "",
                body: bytes = b"",
                headers: dict[str, str] | None = None,
                sign: bool = True) -> S3ClientResponse:
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"{self.host}:{self.port}"
        if sign:
            hdrs = sigv4.sign_request(method, path, query, hdrs, body,
                                      self.access_key, self.secret_key,
                                      self.region)
        if self.tls is not None:
            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=60, context=self.tls)
        else:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=60)
        try:
            url = path + (f"?{query}" if query else "")
            conn.request(method, url, body=body, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            return S3ClientResponse(resp.status,
                                    {k.lower(): v for k, v in
                                     resp.getheaders()}, data)
        finally:
            conn.close()

    # --- convenience ops ---

    def make_bucket(self, bucket: str) -> S3ClientResponse:
        return self.request("PUT", f"/{bucket}")

    def delete_bucket(self, bucket: str) -> S3ClientResponse:
        return self.request("DELETE", f"/{bucket}")

    def put_object(self, bucket: str, key: str, data: bytes,
                   headers: dict[str, str] | None = None,
                   ) -> S3ClientResponse:
        return self.request("PUT", self._key_path(bucket, key), body=data,
                            headers=headers)

    def get_object(self, bucket: str, key: str,
                   headers: dict[str, str] | None = None,
                   query: str = "") -> S3ClientResponse:
        return self.request("GET", self._key_path(bucket, key),
                            query=query, headers=headers)

    def head_object(self, bucket: str, key: str) -> S3ClientResponse:
        return self.request("HEAD", self._key_path(bucket, key))

    def delete_object(self, bucket: str, key: str) -> S3ClientResponse:
        return self.request("DELETE", self._key_path(bucket, key))

    def list_objects_v2(self, bucket: str, prefix: str = "",
                        delimiter: str = "",
                        max_keys: int = 1000) -> S3ClientResponse:
        q = {"list-type": "2", "max-keys": str(max_keys)}
        if prefix:
            q["prefix"] = prefix
        if delimiter:
            q["delimiter"] = delimiter
        return self.request("GET", f"/{bucket}",
                            query=urllib.parse.urlencode(q))

    @staticmethod
    def _key_path(bucket: str, key: str) -> str:
        enc = urllib.parse.quote(key, safe="/-_.~")
        return f"/{bucket}/{enc}"
