"""Browser console: a single-file SPA served at /minio-tpu/console
over the existing JSON-RPC web backend (ref browser/ — the reference
ships a 131-file React app; the rebuild keeps the same capabilities —
login, bucket CRUD, object browse/upload/download/delete, server
info — as one dependency-free page talking to s3/webrpc.py)."""

CONSOLE_HTML = r"""<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>minio-tpu console</title>
<style>
:root { --bg:#101418; --panel:#1a2026; --edge:#2a323b; --fg:#e6edf3;
        --dim:#8b98a5; --acc:#4da3ff; --bad:#ff6b6b; --ok:#51cf66; }
* { box-sizing:border-box; margin:0; }
body { background:var(--bg); color:var(--fg);
       font:14px/1.5 system-ui,-apple-system,Segoe UI,sans-serif; }
header { display:flex; align-items:center; gap:12px; padding:10px 18px;
         background:var(--panel); border-bottom:1px solid var(--edge); }
header h1 { font-size:16px; font-weight:600; }
header .spacer { flex:1; }
main { display:grid; grid-template-columns:260px 1fr; gap:0;
       height:calc(100vh - 49px); }
#buckets { background:var(--panel); border-right:1px solid var(--edge);
           overflow:auto; padding:10px; }
#buckets .bucket { padding:7px 10px; border-radius:6px; cursor:pointer;
                   display:flex; justify-content:space-between; }
#buckets .bucket:hover { background:var(--edge); }
#buckets .bucket.active { background:var(--acc); color:#04121f; }
#objects { overflow:auto; padding:14px 18px; }
table { width:100%; border-collapse:collapse; }
th, td { text-align:left; padding:6px 10px;
         border-bottom:1px solid var(--edge); }
th { color:var(--dim); font-weight:500; }
button, input { font:inherit; border-radius:6px;
                border:1px solid var(--edge);
                background:var(--bg); color:var(--fg);
                padding:6px 10px; }
button { cursor:pointer; background:var(--edge); }
button.primary { background:var(--acc); color:#04121f;
                 border-color:var(--acc); }
button.danger { color:var(--bad); }
#login { max-width:360px; margin:12vh auto; background:var(--panel);
         padding:26px; border-radius:10px;
         border:1px solid var(--edge); display:flex;
         flex-direction:column; gap:12px; }
#msg { color:var(--bad); min-height:1.2em; }
.toolbar { display:flex; gap:8px; margin-bottom:12px;
           align-items:center; }
.dim { color:var(--dim); }
#drop.drag { outline:2px dashed var(--acc); outline-offset:-6px; }
.hidden { display:none !important; }
#info { font-size:12px; color:var(--dim); }
</style>
</head>
<body>
<div id="login">
  <h1>minio-tpu console</h1>
  <input id="user" placeholder="access key" autocomplete="username">
  <input id="pass" placeholder="secret key" type="password"
         autocomplete="current-password">
  <button class="primary" id="loginBtn">Sign in</button>
  <div id="msg"></div>
</div>
<div id="app" class="hidden">
<header>
  <h1>minio-tpu</h1>
  <span id="info"></span>
  <span class="spacer"></span>
  <button id="logout">Sign out</button>
</header>
<main>
  <div id="buckets">
    <div class="toolbar">
      <input id="newBucket" placeholder="new bucket"
             style="width:140px">
      <button class="primary" id="mkBucket">+</button>
    </div>
    <div id="bucketList"></div>
  </div>
  <div id="objects">
    <div class="toolbar">
      <strong id="curBucket" class="dim">select a bucket</strong>
      <span class="spacer" style="flex:1"></span>
      <input id="fileInput" type="file" multiple class="hidden">
      <button class="primary" id="uploadBtn" disabled>Upload</button>
      <button class="danger" id="rmBucket" disabled>Delete bucket</button>
    </div>
    <div id="drop">
      <table>
        <thead><tr><th>Object</th><th>Size</th><th>Modified</th>
        <th></th></tr></thead>
        <tbody id="objList"></tbody>
      </table>
    </div>
  </div>
</main>
</div>
<script>
"use strict";
let token = sessionStorage.getItem("mtpu-token") || "";
let bucket = "";
const $ = id => document.getElementById(id);

async function rpc(method, params) {
  const r = await fetch("/minio-tpu/webrpc", {
    method: "POST",
    headers: {"Content-Type": "application/json",
              "Authorization": "Bearer " + token},
    body: JSON.stringify({jsonrpc: "2.0", id: 1,
                          method: "web." + method,
                          params: params || {}})});
  const doc = await r.json();
  if (doc.error) throw new Error(doc.error.message || "rpc failed");
  return doc.result;
}

// UI actions surface failures instead of rejecting silently; an
// auth-sounding failure bounces back to the login screen.
function act(fn) {
  return (...args) => Promise.resolve(fn(...args)).catch(e => {
    const m = String(e.message || e);
    if (/token|auth|expired/i.test(m)) {
      token = "";
      sessionStorage.removeItem("mtpu-token");
      show(false);
      $("msg").textContent = "session expired — sign in again";
      return;
    }
    alert(m);
  });
}

function fmtSize(n) {
  if (n < 1024) return n + " B";
  const u = ["KiB", "MiB", "GiB", "TiB"];
  let i = -1;
  do { n /= 1024; i++; } while (n >= 1024 && i < u.length - 1);
  return n.toFixed(1) + " " + u[i];
}

function show(loggedIn) {
  $("login").classList.toggle("hidden", loggedIn);
  $("app").classList.toggle("hidden", !loggedIn);
}

async function login() {
  $("msg").textContent = "";
  try {
    const res = await rpc("Login", {username: $("user").value,
                                    password: $("pass").value});
    token = res.token;
    sessionStorage.setItem("mtpu-token", token);
    show(true);
    await refresh();
  } catch (e) { $("msg").textContent = e.message; }
}

async function refresh() {
  try {
    const info = await rpc("ServerInfo", {});
    $("info").textContent =
      (info.version ? "v" + info.version : "") +
      (info.mode ? " · " + info.mode : "");
  } catch (e) { /* non-fatal */ }
  const res = await rpc("ListBuckets", {});
  const list = $("bucketList");
  list.innerHTML = "";
  (res.buckets || []).forEach(b => {
    const el = document.createElement("div");
    el.className = "bucket" + (b.name === bucket ? " active" : "");
    el.textContent = b.name;
    el.onclick = act(() => {
      bucket = b.name;
      $("uploadBtn").disabled = $("rmBucket").disabled = false;
      $("curBucket").textContent = bucket;
      list.querySelectorAll(".bucket").forEach(
        x => x.classList.toggle("active", x === el));
      return listObjects();
    });
    list.appendChild(el);
  });
  $("uploadBtn").disabled = $("rmBucket").disabled = !bucket;
  $("curBucket").textContent = bucket || "select a bucket";
}

async function listObjects() {
  if (!bucket) return;
  const res = await rpc("ListObjects", {bucketName: bucket});
  const tb = $("objList");
  tb.innerHTML = "";
  (res.objects || []).forEach(o => {
    const tr = document.createElement("tr");
    const dl = document.createElement("button");
    dl.textContent = "download";
    dl.onclick = act(() => download(o.name));
    const rm = document.createElement("button");
    rm.textContent = "delete";
    rm.className = "danger";
    rm.onclick = act(async () => {
      await rpc("RemoveObject", {bucketName: bucket,
                                 objects: [o.name]});
      return listObjects();
    });
    const cells = [o.name, fmtSize(o.size || 0),
                   o.lastModified
                     ? new Date(o.lastModified).toLocaleString()
                     : ""];
    cells.forEach(t => {
      const td = document.createElement("td");
      td.textContent = t;
      tr.appendChild(td);
    });
    const actTd = document.createElement("td");
    actTd.appendChild(dl);
    actTd.appendChild(document.createTextNode(" "));
    actTd.appendChild(rm);
    tr.appendChild(actTd);
    tb.appendChild(tr);
  });
}

async function download(key) {
  const res = await rpc("CreateURLToken", {});
  const url = "/minio-tpu/web/download/" + bucket + "/" +
      encodeURIComponent(key).replace(/%2F/g, "/") +
      "?token=" + encodeURIComponent(res.token);
  const a = document.createElement("a");
  a.href = url;
  a.download = key.split("/").pop();
  a.click();
}

async function uploadFiles(files) {
  for (const f of files) {
    const r = await fetch("/minio-tpu/web/upload/" + bucket + "/" +
                encodeURIComponent(f.name), {
      method: "PUT",
      headers: {"Authorization": "Bearer " + token,
                "Content-Type": f.type || "application/octet-stream"},
      body: f});
    if (!r.ok) {
      let why = "HTTP " + r.status;
      try { why = (await r.json()).error || why; } catch (e) {}
      alert("upload of " + f.name + " failed: " + why);
    }
  }
  listObjects();
}

$("loginBtn").onclick = login;
$("pass").addEventListener("keydown",
                           e => { if (e.key === "Enter") login(); });
$("logout").onclick = () => {
  token = ""; bucket = "";
  sessionStorage.removeItem("mtpu-token");
  show(false);
};
$("mkBucket").onclick = act(async () => {
  const name = $("newBucket").value.trim();
  if (!name) return;
  await rpc("MakeBucket", {bucketName: name});
  $("newBucket").value = "";
  return refresh();
});
$("rmBucket").onclick = async () => {
  if (!bucket || !confirm("Delete bucket " + bucket + "?")) return;
  try { await rpc("DeleteBucket", {bucketName: bucket}); }
  catch (e) { alert(e.message); return; }
  bucket = "";
  refresh();
  $("objList").innerHTML = "";
};
$("uploadBtn").onclick = () => $("fileInput").click();
$("fileInput").onchange = act(async e => {
  await uploadFiles(e.target.files);
  e.target.value = "";   // same file re-selected must re-fire
});
const drop = $("drop");
drop.addEventListener("dragover",
                      e => { e.preventDefault();
                             drop.classList.add("drag"); });
drop.addEventListener("dragleave",
                      () => drop.classList.remove("drag"));
drop.addEventListener("drop", act(e => {
  e.preventDefault();
  drop.classList.remove("drag");
  if (bucket) return uploadFiles(e.dataTransfer.files);
}));

if (token) {
  show(true);
  refresh().catch(() => show(false));
}
</script>
</body>
</html>
"""


def console_response() -> tuple[int, str, bytes]:
    return 200, "text/html; charset=utf-8", CONSOLE_HTML.encode()
