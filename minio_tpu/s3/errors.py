"""S3 API error codes and XML error responses (ref cmd/api-errors.go —
the reference carries ~400 codes; this registry holds the actively-used
subset and grows with the handlers)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class APIError(Exception):
    # NOT frozen: contextlib's generator-contextmanager __exit__ assigns
    # exc.__traceback__ in pure Python, which a frozen dataclass rejects
    # (FrozenInstanceError shadowing the real error).
    code: str
    description: str
    http_status: int
    # Throttling family: seconds the client should back off before
    # retrying; rendered as a Retry-After response header (ref the
    # reference's 503 SlowDown responses, cmd/generic-handlers.go).
    retry_after: int | None = None

    def xml(self, resource: str = "", request_id: str = "") -> bytes:
        from .xmlutil import Element
        e = Element("Error")
        e.child("Code", self.code)
        e.child("Message", self.description)
        e.child("Resource", resource)
        e.child("RequestId", request_id)
        return e.tobytes()

    def headers(self) -> dict[str, str]:
        """Extra response headers this error carries."""
        if self.retry_after is not None:
            return {"Retry-After": str(self.retry_after)}
        return {}

    def with_retry_after(self, seconds: int) -> "APIError":
        """A copy carrying a Retry-After hint (module-level error
        singletons stay immutable-in-practice)."""
        return APIError(self.code, self.description, self.http_status,
                        retry_after=max(1, int(seconds)))


def _e(code: str, desc: str, status: int) -> APIError:
    return APIError(code, desc, status)


ERR_ACCESS_DENIED = _e("AccessDenied", "Access Denied.", 403)
ERR_BAD_DIGEST = _e("BadDigest",
                    "The Content-Md5 you specified did not match what we "
                    "received.", 400)
ERR_BUCKET_ALREADY_EXISTS = _e(
    "BucketAlreadyOwnedByYou",
    "Your previous request to create the named bucket succeeded and you "
    "already own it.", 409)
ERR_BUCKET_NOT_EMPTY = _e("BucketNotEmpty",
                          "The bucket you tried to delete is not empty.",
                          409)
ERR_NO_SUCH_BUCKET = _e("NoSuchBucket",
                        "The specified bucket does not exist.", 404)
ERR_NO_SUCH_KEY = _e("NoSuchKey", "The specified key does not exist.", 404)
ERR_NO_SUCH_VERSION = _e(
    "NoSuchVersion",
    "Indicates that the version ID specified in the request does not "
    "match an existing version.", 404)
ERR_NO_SUCH_UPLOAD = _e(
    "NoSuchUpload",
    "The specified multipart upload does not exist.", 404)
ERR_INVALID_BUCKET_NAME = _e("InvalidBucketName",
                             "The specified bucket is not valid.", 400)
ERR_INVALID_ARGUMENT = _e("InvalidArgument", "Invalid Argument", 400)
ERR_INVALID_RANGE = _e("InvalidRange",
                       "The requested range is not satisfiable", 416)
ERR_INVALID_PART = _e(
    "InvalidPart",
    "One or more of the specified parts could not be found.", 400)
ERR_INVALID_PART_ORDER = _e(
    "InvalidPartOrder",
    "The list of parts was not in ascending order.", 400)
ERR_ENTITY_TOO_SMALL = _e(
    "EntityTooSmall",
    "Your proposed upload is smaller than the minimum allowed object "
    "size.", 400)
ERR_ENTITY_TOO_LARGE = _e(
    "EntityTooLarge",
    "Your proposed upload exceeds the maximum allowed object size.", 400)
ERR_METHOD_NOT_ALLOWED = _e(
    "MethodNotAllowed",
    "The specified method is not allowed against this resource.", 405)
ERR_MALFORMED_XML = _e(
    "MalformedXML",
    "The XML you provided was not well-formed or did not validate "
    "against our published schema.", 400)
ERR_MISSING_CONTENT_LENGTH = _e("MissingContentLength",
                                "You must provide the Content-Length HTTP "
                                "header.", 411)
ERR_INTERNAL_ERROR = _e(
    "InternalError",
    "We encountered an internal error, please try again.", 500)
ERR_SLOW_DOWN = _e("SlowDown", "Please reduce your request rate", 503)
ERR_SERVICE_UNAVAILABLE = _e(
    "ServiceUnavailable",
    "The service is unavailable. Please retry.", 503)
ERR_REQUEST_TIMEOUT = _e(
    "RequestTimeout",
    "A timeout occurred while trying to process the request, please "
    "reduce your request rate", 503)
ERR_NOT_IMPLEMENTED = _e("NotImplemented",
                         "A header you provided implies functionality "
                         "that is not implemented", 501)
ERR_PARENT_IS_OBJECT = _e(
    "XMinioParentIsObject",
    "Object-prefix is already an object, please choose a different "
    "object-prefix name.", 400)
ERR_SIGNATURE_DOES_NOT_MATCH = _e(
    "SignatureDoesNotMatch",
    "The request signature we calculated does not match the signature "
    "you provided. Check your key and signing method.", 403)
ERR_INVALID_ACCESS_KEY_ID = _e(
    "InvalidAccessKeyId",
    "The Access Key Id you provided does not exist in our records.", 403)
ERR_MISSING_AUTH = _e(
    "AccessDenied", "Request is missing authentication credentials.", 403)
ERR_REQUEST_TIME_TOO_SKEWED = _e(
    "RequestTimeTooSkewed",
    "The difference between the request time and the server's time is "
    "too large.", 403)
ERR_AUTHORIZATION_HEADER_MALFORMED = _e(
    "AuthorizationHeaderMalformed",
    "The authorization header is malformed.", 400)
ERR_EXPIRED_PRESIGN = _e("AccessDenied", "Request has expired", 403)
ERR_PRECONDITION_FAILED = _e(
    "PreconditionFailed",
    "At least one of the pre-conditions you specified did not hold", 412)
ERR_NO_SUCH_BUCKET_POLICY = _e(
    "NoSuchBucketPolicy", "The bucket policy does not exist", 404)
ERR_NO_SUCH_TAG_SET = _e("NoSuchTagSet",
                         "The TagSet does not exist", 404)
ERR_NO_SUCH_LIFECYCLE = _e(
    "NoSuchLifecycleConfiguration",
    "The lifecycle configuration does not exist", 404)
ERR_NO_SUCH_LIFECYCLE_CONFIG = ERR_NO_SUCH_LIFECYCLE
ERR_MALFORMED_POLICY = _e(
    "MalformedPolicy", "Policy has invalid resource", 400)
ERR_NO_SUCH_SSE_CONFIG = _e(
    "ServerSideEncryptionConfigurationNotFoundError",
    "The server side encryption configuration was not found", 404)
ERR_NO_SUCH_OBJECT_LOCK_CONFIG = _e(
    "ObjectLockConfigurationNotFoundError",
    "Object Lock configuration does not exist for this bucket", 404)
ERR_NO_SUCH_REPLICATION_CONFIG = _e(
    "ReplicationConfigurationNotFoundError",
    "The replication configuration was not found", 404)
ERR_NO_SUCH_CORS_CONFIG = _e(
    "NoSuchCORSConfiguration",
    "The CORS configuration does not exist", 404)
ERR_SSE_KEY_REQUIRED = _e(
    "InvalidRequest",
    "The object was stored using a form of Server Side Encryption. The "
    "correct parameters must be provided to retrieve the object.", 400)
ERR_SSE_KEY_MISMATCH = _e(
    "AccessDenied",
    "The calculated MD5 hash of the key did not match the hash that "
    "was provided.", 403)
ERR_INVALID_SSE_PARAMS = _e(
    "InvalidArgument",
    "Invalid server side encryption parameters", 400)
ERR_INVALID_BUCKET_STATE = _e(
    "InvalidBucketState",
    "Object Lock configuration cannot be enabled on existing buckets", 409)
ERR_OBJECT_LOCKED = _e(
    "AccessDenied",
    "Object is WORM protected and cannot be overwritten or deleted", 403)
ERR_PAST_OBJECT_LOCK_RETAIN_DATE = _e(
    "InvalidRequest",
    "the retain until date must be in the future", 400)
ERR_INVALID_RETENTION_MODE = _e(
    "InvalidRequest",
    "invalid retention mode, expected GOVERNANCE or COMPLIANCE", 400)
ERR_NO_SUCH_RETENTION = _e(
    "NoSuchObjectLockConfiguration",
    "The specified object does not have a ObjectLock configuration", 404)
ERR_INVALID_STORAGE_CLASS = _e(
    "InvalidStorageClass", "Invalid storage class.", 400)
ERR_QUOTA_EXCEEDED = _e(
    "QuotaExceeded", "Bucket quota exceeded", 409)
ERR_STORAGE_FULL = _e(
    "XMinioStorageFull",
    "Storage backend has reached its minimum free disk threshold. "
    "Please delete a few objects to proceed.", 507)
ERR_OBJECT_CORRUPT = _e(
    "XMinioObjectCorrupted",
    "The object failed integrity verification and could not be "
    "reconstructed from parity.", 500)


# Safety-net mapping for per-disk storage errors that escape the engine
# (ref cmd/object-api-errors.go toObjectErr + cmd/api-errors.go
# toAPIErrorCode). The engine normally reduces per-disk errors into its
# own typed errors (ObjectNotFound, BucketNotFound, ...) which handlers
# map individually; a raw StorageError reaching the top-level handler
# used to answer an opaque 500 InternalError — this map keeps the
# 404/409/503 retry semantics instead. Lint rule R5 (tools/mtpu_lint)
# enforces that every storage/errors.py exception class has an entry,
# so the safety net stays total as the taxonomy grows. (storage/errors
# imports nothing, so this import cannot cycle.)
from ..storage.errors import (DiskFull, DiskNotFound,  # noqa: E402
                              DriveQuarantined, FaultyDisk, FileCorrupt,
                              FileNotFound, RegenRepairFailed,
                              StorageError, VersionNotFound,
                              VolumeExists, VolumeNotFound)

STORAGE_ERROR_MAP = {
    StorageError: ERR_INTERNAL_ERROR,
    DiskNotFound: ERR_SLOW_DOWN,
    FaultyDisk: ERR_SLOW_DOWN,
    VolumeNotFound: ERR_NO_SUCH_BUCKET,
    VolumeExists: ERR_BUCKET_ALREADY_EXISTS,
    FileNotFound: ERR_NO_SUCH_KEY,
    VersionNotFound: ERR_NO_SUCH_VERSION,
    FileCorrupt: ERR_OBJECT_CORRUPT,
    DiskFull: ERR_STORAGE_FULL,
    # A quarantine marker surfacing alone means the engine could not
    # find enough healthy drives either — retryable unavailability.
    DriveQuarantined: ERR_SLOW_DOWN,
    # A failed REGEN repair is a transient helper shortfall, not data
    # loss: the object still decodes from any k nodes.
    RegenRepairFailed: ERR_SLOW_DOWN,
}


def storage_api_error(exc: BaseException) -> APIError | None:
    """The typed S3 APIError for a storage-layer exception, walking the
    MRO so subclasses inherit their base mapping; None for non-storage
    errors."""
    if not isinstance(exc, StorageError):
        return None
    for cls in type(exc).__mro__:
        if cls in STORAGE_ERROR_MAP:
            return STORAGE_ERROR_MAP[cls]
    return ERR_INTERNAL_ERROR
