"""Browser POST form uploads with policy conditions (ref
cmd/postpolicyform.go ~300 LoC + PostPolicyBucketHandler routed at
cmd/api-router.go:304).

A POST to the bucket URL carries multipart/form-data: a base64 policy
document, a SigV4 signature over that exact base64 string, form fields,
and the file payload. The policy lists conditions (eq / starts-with /
content-length-range) that the form fields must satisfy.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field


class FormError(Exception):
    pass


class PolicyViolation(Exception):
    pass


# ---------------------------------------------------------------------------
# multipart/form-data parsing (no cgi module in modern Python)
# ---------------------------------------------------------------------------


@dataclass
class FormData:
    fields: dict[str, str] = field(default_factory=dict)
    file_name: str = ""
    file_data: bytes = b""
    file_content_type: str = ""
    has_file: bool = False


def parse_multipart(content_type: str, body: bytes) -> FormData:
    """Minimal RFC7578 parser: boundary-split, per-part headers, one
    `file` part, everything else text fields."""
    if "boundary=" not in content_type:
        raise FormError("no boundary in content-type")
    boundary = content_type.split("boundary=", 1)[1].strip().strip('"')
    delim = b"--" + boundary.encode()
    out = FormData()
    # Parts sit between delimiters; final delimiter ends with "--".
    chunks = body.split(delim)
    for chunk in chunks[1:-1] if len(chunks) > 2 else chunks[1:]:
        if chunk in (b"--\r\n", b"--"):
            continue
        part = chunk.lstrip(b"\r\n")
        head, sep, payload = part.partition(b"\r\n\r\n")
        if not sep:
            continue
        if payload.endswith(b"\r\n"):
            payload = payload[:-2]
        name = filename = ctype = ""
        for line in head.split(b"\r\n"):
            ls = line.decode("utf-8", "replace")
            low = ls.lower()
            if low.startswith("content-disposition:"):
                for item in ls.split(";")[1:]:
                    k, _, v = item.strip().partition("=")
                    v = v.strip('"')
                    if k == "name":
                        name = v
                    elif k == "filename":
                        filename = v
            elif low.startswith("content-type:"):
                ctype = ls.split(":", 1)[1].strip()
        if name.lower() == "file":
            out.has_file = True
            out.file_name = filename
            out.file_data = payload
            out.file_content_type = ctype
        elif name:
            out.fields[name] = payload.decode("utf-8", "replace")
    return out


# ---------------------------------------------------------------------------
# policy document (ref PostPolicyForm parsing, cmd/postpolicyform.go)
# ---------------------------------------------------------------------------


@dataclass
class PolicyCondition:
    op: str       # "eq" | "starts-with" | "content-length-range"
    name: str     # normalized, no "$", lowercase
    value: str = ""
    range_min: int = 0
    range_max: int = 0


@dataclass
class PostPolicy:
    expiration: float = 0.0
    conditions: list[PolicyCondition] = field(default_factory=list)

    @classmethod
    def from_json(cls, raw: bytes) -> "PostPolicy":
        try:
            doc = json.loads(raw)
        except ValueError:
            raise FormError("policy is not valid JSON")
        p = cls()
        exp = doc.get("expiration", "")
        if not exp:
            # A policy with no expiry would be a permanent upload
            # credential; AWS and the reference both reject it.
            raise FormError("policy must carry an expiration")
        from ..bucket.objectlock import parse_iso8601
        try:
            p.expiration = parse_iso8601(exp)
        except ValueError:
            raise FormError(f"bad expiration {exp!r}")
        for cond in doc.get("conditions", []):
            if isinstance(cond, dict):  # {"bucket": "b"} = eq shorthand
                for k, v in cond.items():
                    p.conditions.append(PolicyCondition(
                        "eq", k.lower(), str(v)))
            elif isinstance(cond, list) and len(cond) == 3:
                op = str(cond[0]).lower()
                if op == "content-length-range":
                    p.conditions.append(PolicyCondition(
                        op, "", range_min=int(cond[1]),
                        range_max=int(cond[2])))
                elif op in ("eq", "starts-with"):
                    name = str(cond[1]).lstrip("$").lower()
                    p.conditions.append(PolicyCondition(
                        op, name, str(cond[2])))
                else:
                    raise FormError(f"unknown condition op {op!r}")
            else:
                raise FormError(f"malformed condition {cond!r}")
        return p

    # Form fields that need no policy condition (ref checkPostPolicy's
    # skip list: the signature machinery itself + file + x-ignore-*).
    SKIP_FIELDS = {"policy", "x-amz-signature", "file", "bucket"}

    def check(self, fields: dict[str, str], size: int,
              now: float | None = None) -> None:
        """Enforce every policy condition against the submitted form,
        AND require every submitted field to be covered by a condition
        — otherwise a signed form becomes a vehicle for arbitrary
        attacker-chosen fields (ref checkPostPolicy,
        cmd/postpolicyform.go)."""
        now = time.time() if now is None else now
        if now > self.expiration:
            raise PolicyViolation("policy has expired")
        lower = {k.lower(): v for k, v in fields.items()}
        covered = {c.name for c in self.conditions if c.name}
        for name in lower:
            if name in self.SKIP_FIELDS or name.startswith("x-ignore-"):
                continue
            if name not in covered:
                raise PolicyViolation(
                    f"form field {name!r} not covered by any policy "
                    "condition")
        # interpolated key: browsers send key templates w/ ${filename}
        for c in self.conditions:
            if c.op == "content-length-range":
                if not (c.range_min <= size <= c.range_max):
                    raise PolicyViolation(
                        f"size {size} outside "
                        f"[{c.range_min},{c.range_max}]")
                continue
            got = lower.get(c.name, "")
            if c.op == "eq":
                if got != c.value:
                    raise PolicyViolation(
                        f"{c.name}: {got!r} != {c.value!r}")
            elif c.op == "starts-with":
                if not got.startswith(c.value):
                    raise PolicyViolation(
                        f"{c.name}: {got!r} !startswith {c.value!r}")


def verify_post_signature(policy_b64: str, fields: dict[str, str],
                          lookup_secret) -> str:
    """SigV4 POST-policy signature: HMAC(signing key, base64 policy)
    (ref doesPolicySignatureV4Match, cmd/signature-v4.go). Returns the
    access key."""
    from . import sigv4
    from .errors import (ERR_INVALID_ACCESS_KEY_ID, ERR_MISSING_AUTH,
                         ERR_SIGNATURE_DOES_NOT_MATCH)
    lower = {k.lower(): v for k, v in fields.items()}
    algo = lower.get("x-amz-algorithm", "")
    if algo != sigv4.SIGN_V4_ALGORITHM:
        raise ERR_MISSING_AUTH
    cred_s = lower.get("x-amz-credential", "")
    signature = lower.get("x-amz-signature", "")
    cred = sigv4._parse_credential(cred_s)
    secret = lookup_secret(cred.access_key)
    if secret is None:
        raise ERR_INVALID_ACCESS_KEY_ID
    key = sigv4._signing_key(secret, cred.date, cred.region, cred.service)
    want = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise ERR_SIGNATURE_DOES_NOT_MATCH
    return cred.access_key


def build_post_form(bucket: str, key: str, data: bytes, access_key: str,
                    secret_key: str, region: str = "us-east-1",
                    conditions: list | None = None,
                    expires_in: int = 3600,
                    extra_fields: dict | None = None,
                    ) -> tuple[str, bytes]:
    """Client/test helper: a signed multipart form for POST upload.
    Returns (content_type, body)."""
    from . import sigv4
    t = time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    date = time.strftime("%Y%m%d", t)
    cred = f"{access_key}/{date}/{region}/s3/aws4_request"
    exp = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                        time.gmtime(time.time() + expires_in))
    # Templated keys can't be eq-pinned (the browser substitutes the
    # filename); use starts-with on the static prefix, as AWS docs do.
    if "${filename}" in key:
        key_cond = ["starts-with", "$key",
                    key.split("${filename}", 1)[0]]
    else:
        key_cond = ["eq", "$key", key]
    conds = [{"bucket": bucket}, key_cond,
             ["eq", "$x-amz-algorithm", sigv4.SIGN_V4_ALGORITHM],
             ["eq", "$x-amz-credential", cred],
             ["eq", "$x-amz-date", amz_date]]
    conds += conditions or []
    policy_b64 = base64.b64encode(json.dumps(
        {"expiration": exp, "conditions": conds}).encode()).decode()
    key_sig = sigv4._signing_key(secret_key, date, region, "s3")
    signature = hmac.new(key_sig, policy_b64.encode(),
                         hashlib.sha256).hexdigest()
    fields = {
        "key": key, "policy": policy_b64,
        "x-amz-algorithm": sigv4.SIGN_V4_ALGORITHM,
        "x-amz-credential": cred, "x-amz-date": amz_date,
        "x-amz-signature": signature,
    }
    fields.update(extra_fields or {})
    boundary = "----minio-tpu-form-boundary"
    parts = []
    for k, v in fields.items():
        parts.append(
            f"--{boundary}\r\nContent-Disposition: form-data; "
            f'name="{k}"\r\n\r\n{v}\r\n'.encode())
    parts.append(
        f"--{boundary}\r\nContent-Disposition: form-data; "
        f'name="file"; filename="upload"\r\n'
        f"Content-Type: application/octet-stream\r\n\r\n".encode()
        + data + b"\r\n")
    parts.append(f"--{boundary}--\r\n".encode())
    return (f"multipart/form-data; boundary={boundary}",
            b"".join(parts))
