"""S3-compatible HTTP server: router + object/bucket handlers.

The analog of the reference's L1/L2 (ref cmd/routers.go:86 middleware
chain, cmd/api-router.go:82 route table, cmd/object-handlers.go,
cmd/bucket-handlers.go), over Python stdlib http.server (threaded) with
the erasure object engine as the ObjectLayer.
"""

from __future__ import annotations

import base64
import email.utils
import hashlib
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..erasure.engine import (BucketExists, BucketNotFound, ErasureObjects,
                              MethodNotAllowed, ObjectInfo, ObjectNotFound)
from ..fs.backend import ParentIsObject
from ..parallel.quorum import QuorumError
from . import errors as s3err
from . import sigv4
from .errors import APIError
from .xmlutil import S3_XMLNS, Element, parse

MAX_OBJECT_SIZE = 5 * 1024 * 1024 * 1024  # single-PUT cap (5 GiB)


def _drain_stream(stream) -> bytes:
    """Fully buffer a body stream (paths that still need whole-body
    transforms: SSE, compression, signature fallback)."""
    parts = []
    while chunk := stream.read(1 << 20):
        parts.append(chunk)
    return b"".join(parts)


def _trim_iter(it, skip: int, limit: int):
    """Yield exactly `limit` bytes of `it` after dropping `skip`."""
    for chunk in it:
        if skip:
            if len(chunk) <= skip:
                skip -= len(chunk)
                continue
            chunk = chunk[skip:]
            skip = 0
        if limit <= 0:
            break
        if len(chunk) > limit:
            chunk = chunk[:limit]
        yield chunk
        limit -= len(chunk)
        if limit <= 0:
            break


def _mime_for(key: str) -> str:
    """Content type from the key's extension (ref pkg/mimedb — the
    reference ships a 4.6k-line codegen table; Python's mimetypes
    covers the same registry)."""
    import mimetypes
    return mimetypes.guess_type(key)[0] or "application/octet-stream"


def _iso8601(t: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(t))


def _http_date(t: float) -> str:
    return email.utils.formatdate(t, usegmt=True)


def _parse_range(header: str, size: int) -> tuple[int, int] | None:
    """Parse 'bytes=a-b' -> (offset, length); None = whole object.
    Raises InvalidRange when unsatisfiable (ref cmd/httprange.go)."""
    if not header:
        return None
    if not header.startswith("bytes="):
        return None
    spec = header[len("bytes="):]
    if "," in spec:  # multiple ranges unsupported, serve whole object
        return None
    start_s, _, end_s = spec.partition("-")
    try:
        if start_s == "":
            n = int(end_s)  # suffix: last n bytes
            if n <= 0:
                raise s3err.ERR_INVALID_RANGE
            n = min(n, size)
            return size - n, n
        start = int(start_s)
        if end_s == "":
            if start >= size:
                raise s3err.ERR_INVALID_RANGE
            return start, size - start
        end = int(end_s)
        if start > end or start >= size:
            raise s3err.ERR_INVALID_RANGE
        return start, min(end, size - 1) - start + 1
    except ValueError:
        return None


class S3Request:
    """Parsed request context."""

    def __init__(self, method: str, raw_path: str, query: str,
                 headers: dict[str, str], body: bytes):
        self.method = method
        self.raw_path = raw_path
        self.query = query
        self.headers = headers  # lowercase keys
        self.body = body
        # Large object PUTs arrive as a chunk reader instead of bytes
        # (body stays b""): the handler pipes it into the engine's
        # block pipeline without ever buffering the object.
        self.body_stream = None
        self.content_length = len(body)
        self.params = dict(urllib.parse.parse_qsl(
            query, keep_blank_values=True))
        path = urllib.parse.unquote(raw_path)
        parts = path.lstrip("/").split("/", 1)
        self.bucket = parts[0] if parts[0] else ""
        self.key = parts[1] if len(parts) > 1 else ""
        self.request_id = uuid.uuid4().hex[:16].upper()
        # QoS/slowlog annotations, stamped by route_qos: admission
        # class, measured queue wait, opened budget, and whether this
        # request was DELIBERATE backpressure (shed / burnt deadline)
        # — exempt from slow-request capture by design.
        self.qos_class = ""
        self.qos_wait_ms = 0.0
        self.qos_deadline_s = 0.0
        self.slowlog_exempt = False


class S3Response:
    def __init__(self, status: int = 200, body: bytes = b"",
                 headers: dict[str, str] | None = None):
        self.status = status
        self.body = body
        self.headers = headers or {}


def check_preconditions(req: "S3Request", info: "ObjectInfo",
                        prefix: str = "") -> int:
    """Evaluate conditional headers against the object; returns 0 (ok),
    304 or 412 (ref checkPreconditions, cmd/object-handlers-common.go;
    copy-source variants use the x-amz-copy-source-if-* names)."""
    h = req.headers
    etag = info.etag
    not_modified = (304 if req.method in ("GET", "HEAD") and not prefix
                    else 412)
    if_match = h.get(f"{prefix}if-match", "")
    if if_match:
        if if_match.strip('"') != etag and if_match != "*":
            return 412
        # A passing If-Match supersedes If-Unmodified-Since (RFC 7232
        # §6 / ref checkPreconditions ordering).
    elif (ius := h.get(f"{prefix}if-unmodified-since", "")):
        try:
            t = email.utils.parsedate_to_datetime(ius).timestamp()
            if info.mod_time > t:
                return 412
        except (TypeError, ValueError):
            pass
    if_none = h.get(f"{prefix}if-none-match", "")
    if if_none:
        if if_none == "*" or if_none.strip('"') == etag:
            return not_modified
        # If-None-Match present: If-Modified-Since is IGNORED.
    elif (ims := h.get(f"{prefix}if-modified-since", "")):
        try:
            t = email.utils.parsedate_to_datetime(ims).timestamp()
            if info.mod_time <= t:
                return not_modified
        except (TypeError, ValueError):
            pass
    return 0


class S3ApiHandlers:
    """S3 operations over an ObjectLayer (duck-typed ErasureObjects)."""

    def __init__(self, layer: ErasureObjects, region: str = "us-east-1",
                 bucket_meta=None, notifier=None):
        self.layer = layer
        self.region = region
        self.server = None  # S3Server backref (set by set_layer)
        if bucket_meta is None:
            from ..bucket.metadata import BucketMetadataSys
            bucket_meta = BucketMetadataSys.for_layer(layer)
        self.bucket_meta = bucket_meta
        import os as _os
        self.compress_enabled = _os.environ.get(
            "MINIO_COMPRESS", "") == "on"
        if notifier is None:
            from ..event.notifier import NotificationSys
            notifier = NotificationSys(bucket_meta, region)
        self.notifier = notifier
        from ..crypto.sse import LocalKMS
        self.kms = LocalKMS.from_env()
        # External KMS (KES): SSE-S3 object keys seal under per-object
        # data keys the KMS generates; the local master is then unused
        # (ref cmd/crypto/kms.go KES integration).
        from ..crypto.kms import KESClient
        self.kes = KESClient.from_env()
        from ..bucket.replication import ReplicationPool
        self.replication = ReplicationPool(
            self.bucket_meta, self.read_for_replication, layer)
        from ..bucket.tiering import TierManager
        self.tiers = TierManager(self.bucket_meta.store)
        from ..config.storageclass import StorageClassConfig
        self.storage_class = StorageClassConfig.from_env()
        self._usage_cache: dict[str, tuple[float, int]] = {}
        self._usage_mu = threading.Lock()
        # Federation (ref globalDNSConfig): BucketDNS + this cluster's
        # public address, set by server boot when etcd is configured.
        self.bucket_dns = None
        self.public_addr: tuple[str, int] | None = None

    # ---------------- storage class / quota ----------------

    def _parity_for_request(self, req: S3Request) -> int | None:
        """Parity override from x-amz-storage-class (ref the
        GetParityForSC call in putObject, cmd/erasure-object.go:597);
        None = layer default (also for FS, which has no shards)."""
        from ..config import storageclass as sc
        sc_hdr = req.headers.get("x-amz-storage-class", "")
        n = getattr(self.layer, "k", 0) + getattr(self.layer, "m", 0)
        if n < 2:
            # FS layer: REGEN needs erasure shards, so it is invalid
            # here just like any unknown class.
            if sc_hdr and sc_hdr not in (sc.STANDARD, sc.RRS):
                raise s3err.ERR_INVALID_STORAGE_CLASS
            return None
        try:
            return self.storage_class.parity_for(
                sc_hdr, n, getattr(self.layer, "m", 0))
        except sc.InvalidStorageClass:
            raise s3err.ERR_INVALID_STORAGE_CLASS

    def _regen_algorithm_for_request(self, req: S3Request) -> str | None:
        """The erasure algorithm stamp for this PUT: pm-mbr-rbt when
        the REGEN class applies (per-request header or the bucket's
        regen_buckets config default), None otherwise.  Only erasure
        layers qualify; multipart uploads stay plain-RS (the part
        pipeline re-splits on byte boundaries the regen stripe layout
        does not honor)."""
        n = getattr(self.layer, "k", 0) + getattr(self.layer, "m", 0)
        if n < 2:
            return None
        sc_hdr = req.headers.get("x-amz-storage-class", "")
        if self.storage_class.use_regen(sc_hdr, req.bucket):
            from ..storage.metadata import REGEN_ALGORITHM
            return REGEN_ALGORITHM
        return None

    # A full listing re-baselines a bucket's usage counter at most
    # this often; between reconciles the counter moves incrementally
    # with each write/delete, so quota PUT latency is independent of
    # object count (round-3 verdict weak #5; ref enforceBucketQuota's
    # crawler dataUsageCache, cmd/bucket-quota.go).
    USAGE_RECONCILE_TTL = 300.0

    def _usage_baseline(self, bucket: str, newer_than: float = 0.0,
                        ) -> int:
        """Authoritative re-count: the crawler's usage tree when it has
        scanned this bucket SINCE the previous baseline (an older crawl
        would erase writes the counter already tracked), else one full
        listing."""
        crawler = getattr(self.server, "crawler", None)
        if crawler is not None:
            cached = crawler.data_usage()
            entry = cached.get("buckets", {}).get(bucket)
            if entry is not None and cached.get("lastUpdate",
                                                0) >= newer_than:
                return int(entry.get("size", 0))
        meta = self.bucket_meta.get(bucket)
        if meta.versioning:  # every stored version consumes quota
            infos = self.layer.list_object_versions(bucket,
                                                    max_keys=1_000_000)
        else:
            infos = self.layer.list_objects(bucket, max_keys=1_000_000)
        return sum(i.size for i in infos)

    def _bucket_usage(self, bucket: str) -> int:
        """Incrementally tracked total stored bytes: baseline once (or
        after the reconcile TTL / a version-state change), then moved
        by _usage_add on every handler write/delete."""
        with self._usage_mu:
            hit = self._usage_cache.get(bucket)
            if hit and time.time() - hit[0] < self.USAGE_RECONCILE_TTL:
                return hit[1]
        total = self._usage_baseline(bucket,
                                     newer_than=hit[0] if hit else 0.0)
        with self._usage_mu:
            self._usage_cache[bucket] = (time.time(), total)
        return total

    def _usage_add(self, bucket: str, delta: int) -> None:
        """Move the tracked counter; no-op until the baseline exists
        (quota-less buckets never pay for tracking)."""
        with self._usage_mu:
            hit = self._usage_cache.get(bucket)
            if hit is not None:
                self._usage_cache[bucket] = (hit[0],
                                             max(0, hit[1] + delta))

    def _usage_replaced_size(self, bucket: str, key: str,
                             versioned: bool) -> int:
        """Bytes an unversioned overwrite is about to free (0 when the
        counter is inactive, the bucket versions writes, or the key is
        new) — overwrites must not inflate tracked usage."""
        if versioned or self._usage_cache.get(bucket) is None:
            return 0
        try:
            return self.layer.get_object_info(bucket, key).size
        except Exception:
            return 0

    def _check_quota(self, bucket: str, incoming: int) -> None:
        q = self.bucket_meta.get(bucket).quota
        if not q or not q.get("quota"):
            return
        if q.get("quotaType", "hard") != "hard":
            return  # FIFO/soft quotas don't reject writes
        if self._bucket_usage(bucket) + incoming > int(q["quota"]):
            raise s3err.ERR_QUOTA_EXCEEDED

    # ---------------- replication plumbing ----------------

    def read_for_replication(self, bucket: str, key: str,
                             version_id: str = ""):
        """Logical object bytes + info for the replication worker —
        SSE-S3 decrypts under the local KMS, SSE-C is unreadable
        server-side (the reference likewise skips SSE-C sources)."""
        from ..crypto import sse
        from ..utils import compress
        info = self.layer.get_object_info(bucket, key, version_id)
        mode = sse.is_encrypted(info.metadata)
        if mode == sse.SSE_C:
            raise ValueError("SSE-C objects cannot be replicated")
        from ..bucket import tiering as tier_mod
        if tier_mod.needs_tier_read(info.metadata):
            fake = S3Request("GET", f"/{bucket}", "", {}, b"")
            return self._transitioned_plain(fake, info), info
        if mode:
            okey = sse.unseal_key(
                self._sse_s3_master(info.metadata, bucket, key),
                info.metadata[sse.META_SEALED_KEY], mode, bucket, key)
            data = self._sse_decrypt_read(version_id, info, okey, 0,
                                          info.size)
        else:
            data, info = self.layer.get_object(bucket, key,
                                               version_id=version_id)
        if info.metadata.get(compress.META_COMPRESSION):
            data = compress.decompress_stream(data)
        return data, info

    def _replication_decision(self, req: S3Request, meta: dict) -> None:
        """Stamp the new object's replication status before the write:
        REPLICA for incoming replica traffic, PENDING when a rule
        matches (ref mustReplicate, cmd/bucket-replication.go:100)."""
        from ..bucket.replication import (META_REPLICATION_STATUS,
                                          PENDING, REPLICA)
        if req.headers.get(META_REPLICATION_STATUS) == REPLICA:
            meta[META_REPLICATION_STATUS] = REPLICA
        elif self.replication.must_replicate(req.bucket, req.key):
            meta[META_REPLICATION_STATUS] = PENDING

    def _queue_replication(self, req: S3Request, info: ObjectInfo,
                           meta: dict) -> None:
        from ..bucket.replication import META_REPLICATION_STATUS, PENDING
        if meta.get(META_REPLICATION_STATUS) == PENDING:
            self.replication.queue_task(req.bucket, req.key,
                                        info.version_id, "put")

    def _notify(self, event_name: str, bucket: str, key: str,
                info: ObjectInfo | None = None,
                user: str = "") -> None:
        """Fire a bucket event (ref sendEvent calls at the end of every
        object handler, cmd/object-handlers.go)."""
        from ..event.event import Event
        self.notifier.send(Event(
            event_name=event_name, bucket=bucket, key=key,
            size=info.size if info else 0,
            etag=info.etag if info else "",
            version_id=info.version_id if info else "",
            region=self.region, user_identity=user))

    def _versioned(self, bucket: str) -> bool:
        return self.bucket_meta.versioning_enabled(bucket)

    @staticmethod
    def _version_param(req: S3Request) -> str:
        """The literal 'null' addresses the null (unversioned) version,
        which is the empty id internally (ref nullVersionID handling)."""
        vid = req.params.get("versionId", "")
        return "" if vid == "null" else vid

    # ---------------- service ----------------

    def list_buckets(self, req: S3Request) -> S3Response:
        root = Element("ListAllMyBucketsResult", S3_XMLNS)
        owner = root.child("Owner")
        owner.child("ID", "minio-tpu")
        owner.child("DisplayName", "minio-tpu")
        buckets = root.child("Buckets")
        for b in self.layer.list_buckets():
            e = buckets.child("Bucket")
            e.child("Name", b["name"])
            e.child("CreationDate", _iso8601(b["created"]))
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    # ---------------- bucket ----------------

    def make_bucket(self, req: S3Request) -> S3Response:
        if not (3 <= len(req.bucket) <= 63) or not all(
                c.islower() or c.isdigit() or c in ".-"
                for c in req.bucket):
            raise s3err.ERR_INVALID_BUCKET_NAME
        if self.bucket_dns is not None:
            # Federation namespace is GLOBAL: refuse names another
            # cluster already owns (ref initFederatorBackend +
            # MakeBucket DNS check, cmd/bucket-handlers.go).
            try:
                owners = self.bucket_dns.lookup(req.bucket,
                                                cached=False)
            except Exception:
                owners = []
            if any(o != self.public_addr for o in owners):
                raise s3err.ERR_BUCKET_ALREADY_EXISTS
        try:
            self.layer.make_bucket(req.bucket)
        except BucketExists:
            raise s3err.ERR_BUCKET_ALREADY_EXISTS
        if req.headers.get(
                "x-amz-bucket-object-lock-enabled", "").lower() == "true":
            # Lock can only be enabled at creation; it force-enables
            # versioning (ref MakeBucketWithObjectLock,
            # cmd/bucket-handlers.go).
            if not getattr(self.layer, "supports_versioning", True):
                self.layer.delete_bucket(req.bucket)
                raise s3err.ERR_NOT_IMPLEMENTED  # FS: no versioning
            from ..bucket import objectlock as ol
            self.bucket_meta.update(req.bucket,
                                    object_lock_xml=ol.ENABLED_XML,
                                    versioning="Enabled")
        if self.bucket_dns is not None and self.public_addr:
            # Federation: advertise this bucket cluster-wide (ref
            # bucket DNS add on MakeBucket, cmd/bucket-handlers.go).
            try:
                self.bucket_dns.register(req.bucket, *self.public_addr)
            except Exception:
                from ..logger import Logger
                Logger.get().log_once(
                    f"bucket DNS register failed for {req.bucket}",
                    "bucket-dns")
        return S3Response(200, headers={"Location": f"/{req.bucket}"})

    def head_bucket(self, req: S3Request) -> S3Response:
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET
        return S3Response(200)

    def delete_bucket(self, req: S3Request) -> S3Response:
        try:
            self.layer.delete_bucket(req.bucket)
        except BucketNotFound:
            raise s3err.ERR_NO_SUCH_BUCKET
        except BucketExists:
            raise s3err.ERR_BUCKET_NOT_EMPTY
        # Drop every bucket-scoped config with the bucket — a later
        # bucket of the same name must start clean (ref deleteBucket
        # metadata cleanup, cmd/bucket-metadata-sys.go).
        self.bucket_meta.delete(req.bucket)
        if self.bucket_dns is not None:
            try:
                self.bucket_dns.unregister(req.bucket)
            except Exception:
                pass
        return S3Response(204)

    def get_location(self, req: S3Request) -> S3Response:
        # us-east-1 renders as an empty LocationConstraint.
        body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                b'<LocationConstraint xmlns="' + S3_XMLNS.encode() +
                b'"></LocationConstraint>')
        return S3Response(200, body,
                          {"Content-Type": "application/xml"})

    def list_objects(self, req: S3Request) -> S3Response:
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET
        v2 = req.params.get("list-type") == "2"
        prefix = req.params.get("prefix", "")
        delimiter = req.params.get("delimiter", "")
        max_keys = min(int(req.params.get("max-keys", "1000") or "1000"),
                       1000)
        marker = (req.params.get("continuation-token")
                  or req.params.get("start-after")
                  or req.params.get("marker", ""))
        if req.params.get("continuation-token"):
            marker = base64.b64decode(marker).decode()

        infos = self.layer.list_objects(req.bucket, prefix=prefix,
                                        max_keys=1_000_000)
        contents: list[ObjectInfo] = []
        common: list[str] = []
        seen_prefix: set[str] = set()
        truncated = False
        next_marker = ""
        for info in infos:
            name = info.name
            if marker and name <= marker:
                continue
            if delimiter:
                rest = name[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp not in seen_prefix:
                        if len(contents) + len(seen_prefix) >= max_keys:
                            truncated = True
                            break
                        seen_prefix.add(cp)
                        common.append(cp)
                        next_marker = cp.rstrip(delimiter)
                    continue
            if len(contents) + len(seen_prefix) >= max_keys:
                truncated = True
                break
            contents.append(info)
            next_marker = name

        root = Element("ListBucketResult", S3_XMLNS)
        root.child("Name", req.bucket)
        root.child("Prefix", prefix)
        root.child("MaxKeys", max_keys)
        root.child("Delimiter", delimiter)
        root.child("IsTruncated", truncated)
        if v2:
            root.child("KeyCount", len(contents) + len(common))
            if truncated and next_marker:
                root.child("NextContinuationToken",
                           base64.b64encode(
                               next_marker.encode()).decode())
        elif truncated and next_marker:
            root.child("NextMarker", next_marker)
        for info in contents:
            c = root.child("Contents")
            c.child("Key", info.name)
            c.child("LastModified", _iso8601(info.mod_time))
            c.child("ETag", f'"{info.etag}"')
            c.child("Size", self._actual_size(info))
            c.child("StorageClass", info.metadata.get(
                "x-amz-storage-class", "STANDARD"))
        for cp in common:
            p = root.child("CommonPrefixes")
            p.child("Prefix", cp)
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def delete_multiple(self, req: S3Request) -> S3Response:
        try:
            doc = parse(req.body)
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        quiet = doc.findtext("Quiet") == "true"
        versioned = self._versioned(req.bucket)
        root = Element("DeleteResult", S3_XMLNS)
        for obj in doc.findall("Object"):
            key = obj.findtext("Key") or ""
            vid = obj.findtext("VersionId") or ""
            if vid == "null":
                vid = ""
            try:
                self._check_version_delete_allowed(
                    req.bucket, key, vid,
                    self._can_bypass_governance(req))
                freed = 0
                if (self._usage_cache.get(req.bucket) is not None
                        and not (versioned and not vid)):
                    try:
                        freed = self.layer.get_object_info(
                            req.bucket, key, vid).size
                    except Exception:
                        freed = 0
                deleted = self.layer.delete_object(req.bucket, key, vid,
                                                   versioned=versioned)
                if not deleted.delete_marker and freed:
                    self._usage_add(req.bucket, -freed)
                from ..event import event as ev
                self._notify(
                    ev.OBJECT_REMOVED_DELETE_MARKER
                    if deleted.delete_marker else ev.OBJECT_REMOVED_DELETE,
                    req.bucket, key, deleted)
                if not quiet:
                    d = root.child("Deleted")
                    d.child("Key", key)
                    if vid:
                        d.child("VersionId", vid)
                    if deleted.delete_marker:
                        d.child("DeleteMarker", True)
                        if deleted.version_id:
                            d.child("DeleteMarkerVersionId",
                                    deleted.version_id)
            except ObjectNotFound:
                if not quiet:  # S3 treats missing keys as deleted
                    d = root.child("Deleted")
                    d.child("Key", key)
            except APIError as e2:
                e = root.child("Error")
                e.child("Key", key)
                e.child("Code", e2.code)
            except Exception:
                e = root.child("Error")
                e.child("Key", key)
                e.child("Code", "InternalError")
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    # ---------------- object ----------------

    @staticmethod
    def _object_headers(info: ObjectInfo) -> dict[str, str]:
        h = {
            "ETag": f'"{info.etag}"',
            "Last-Modified": _http_date(info.mod_time),
            "Accept-Ranges": "bytes",
            "Content-Type": info.metadata.get(
                "content-type", "application/octet-stream"),
        }
        if info.version_id:
            h["x-amz-version-id"] = info.version_id
        if "x-amz-replication-status" in info.metadata:
            h["x-amz-replication-status"] = \
                info.metadata["x-amz-replication-status"]
        for k, v in info.metadata.items():
            if k.startswith("x-amz-meta-"):
                h[k] = v
        return h

    # ---------------- compression plumbing ----------------

    def _maybe_compress(self, key: str, body: bytes, meta: dict) -> bytes:
        """Transparent compression before erasure coding when enabled
        and the payload looks compressible (ref isCompressible gate +
        newS2CompressReader wrap, cmd/object-api-utils.go:436,898)."""
        from ..crypto import sse
        from ..utils import compress
        if not getattr(self.layer, "supports_transforms", True):
            return body  # gateway: upstream gets the raw payload
        if not self.compress_enabled:
            return body
        if not compress.is_compressible(
                key, meta.get("content-type", ""), len(body)):
            return body
        meta[compress.META_COMPRESSION] = compress.CODEC_TAG
        meta[sse.META_ACTUAL_SIZE] = str(len(body))
        return compress.compress_stream(body)

    def _wrap_transform_readers(self, req: S3Request, body,
                                meta: dict, size_hint: int):
        """Streaming PUT transform chain: plain -> [compress] ->
        [encrypt], each a Reader emitting the byte-identical format of
        its buffered counterpart. The readers stamp META_ACTUAL_SIZE
        into `meta` at EOF — the engine reads metadata only at commit,
        after the stream is fully consumed."""
        from ..crypto import sse
        from ..utils import compress
        if (self.compress_enabled
                and getattr(self.layer, "supports_transforms", True)
                and compress.is_compressible(
                    req.key, meta.get("content-type", ""), size_hint)):
            meta[compress.META_COMPRESSION] = compress.CODEC_TAG
            body = compress.CompressingReader(body, meta)
        picked = self._sse_mode_for_request(req)
        if picked is not None:
            okey = self._sse_seal_into_meta(req, *picked, meta)
            body = sse.EncryptingReader(body, okey, meta)
        return body

    # ---------------- SSE plumbing ----------------

    def _bucket_default_sse(self, bucket: str) -> bool:
        """Bucket default encryption config requests SSE-S3 (ref
        validateBucketSSEConfig + auto-encrypt on put)."""
        raw = self.bucket_meta.get(bucket).sse_xml
        return bool(raw) and "AES256" in raw

    def _sse_mode_for_request(self, req: S3Request,
                              ) -> tuple[str, bytes] | None:
        """(mode, master-key) the request asks for, None = plain.
        Single source of truth for both single-PUT and multipart."""
        from ..crypto import sse
        try:
            ckey = sse.parse_ssec_key(req.headers)
        except sse.SSEError:
            raise s3err.ERR_INVALID_SSE_PARAMS
        if not getattr(self.layer, "supports_transforms", True):
            if ckey is not None or req.headers.get(sse.H_SSE):
                # No local envelope through a gateway (the reference
                # rejects SSE in gateway mode without backend SSE).
                raise s3err.ERR_NOT_IMPLEMENTED
            return None
        if ckey is not None:
            return sse.SSE_C, ckey
        if (req.headers.get(sse.H_SSE) == "AES256"
                or self._bucket_default_sse(req.bucket)):
            if self.kes is not None:
                # External KMS: the per-object data key is generated at
                # seal time; no local master involved.
                return sse.SSE_S3, b""
            if not self.kms.configured:
                # Never encrypt under an ephemeral master — the data
                # would be unrecoverable after restart (the reference
                # refuses SSE-S3 without a configured KMS).
                raise s3err.ERR_INVALID_SSE_PARAMS
            return sse.SSE_S3, self.kms.master
        return None

    def _sse_seal_into_meta(self, req: S3Request, mode: str,
                            master: bytes, meta: dict) -> bytes:
        """Create the object key, record the envelope; returns the key."""
        from ..crypto import sse
        okey = sse.new_object_key()
        meta[sse.META_ALGORITHM] = mode
        if mode == sse.SSE_S3 and self.kes is not None:
            from ..crypto.kms import KMSError
            try:
                master, wrapped = self.kes.generate_key(req.bucket,
                                                        req.key)
            except KMSError:
                raise s3err.ERR_INTERNAL_ERROR
            meta[sse.META_KMS_DATA_KEY] = wrapped
            meta[sse.META_KMS_KEY_ID] = self.kes.key_id
        elif mode == sse.SSE_S3:
            meta[sse.META_KMS_KEY_ID] = self.kms.key_id
        meta[sse.META_SEALED_KEY] = sse.seal_key(
            master, okey, mode, req.bucket, req.key)
        if mode == sse.SSE_C:
            meta[sse.META_KEY_MD5] = req.headers[sse.H_SSEC_KEY_MD5]
        return okey

    def _sse_s3_master(self, metadata: dict, bucket: str,
                       key: str) -> bytes:
        """The key that sealed an SSE-S3 object's envelope: a KMS data
        key (unwrapped via KES) when the object carries one, else the
        local master."""
        from ..crypto import sse
        wrapped = metadata.get(sse.META_KMS_DATA_KEY, "")
        if wrapped:
            if self.kes is None:
                raise s3err.ERR_INVALID_SSE_PARAMS
            from ..crypto.kms import KMSError
            try:
                return self.kes.decrypt_key(wrapped, bucket, key)
            except KMSError:
                raise s3err.ERR_INTERNAL_ERROR
        return self.kms.master

    def _sse_encrypt_body(self, req: S3Request, body: bytes,
                          meta: dict) -> bytes:
        """Encrypt an incoming object body when the request (or the
        bucket default) asks for SSE; records the envelope in internal
        metadata (ref EncryptRequest, cmd/encryption-v1.go:228)."""
        from ..crypto import sse
        picked = self._sse_mode_for_request(req)
        if picked is None:
            return body
        okey = self._sse_seal_into_meta(req, *picked, meta)
        # Compression may already have recorded the ORIGINAL length.
        meta.setdefault(sse.META_ACTUAL_SIZE, str(len(body)))
        return sse.encrypt_stream(body, okey)

    def _sse_unseal_from_meta(self, req: S3Request, metadata: dict,
                              bucket: str, key: str,
                              copy_source: bool = False) -> bytes | None:
        """Object key from an SSE envelope in metadata (validating
        SSE-C credentials); None when not encrypted (ref
        DecryptObjectInfo, cmd/encryption-v1.go:780)."""
        from ..crypto import sse
        mode = sse.is_encrypted(metadata)
        if not mode:
            return None
        if mode == sse.SSE_C:
            try:
                ckey = sse.parse_ssec_key(req.headers, copy_source)
            except sse.SSEError:
                raise s3err.ERR_SSE_KEY_MISMATCH
            if ckey is None:
                raise s3err.ERR_SSE_KEY_REQUIRED
            master = ckey
        else:
            master = self._sse_s3_master(metadata, bucket, key)
        try:
            return sse.unseal_key(master, metadata[sse.META_SEALED_KEY],
                                  mode, bucket, key)
        except sse.KeyMismatch:
            raise s3err.ERR_SSE_KEY_MISMATCH

    def _sse_unseal_for_read(self, req: S3Request, info: ObjectInfo,
                             copy_source: bool = False) -> bytes | None:
        return self._sse_unseal_from_meta(req, info.metadata,
                                          info.bucket, info.name,
                                          copy_source)

    @staticmethod
    def _sse_response_headers(info: ObjectInfo) -> dict:
        from ..crypto import sse
        mode = sse.is_encrypted(info.metadata)
        if mode == sse.SSE_C:
            return {sse.H_SSEC_ALGO: "AES256",
                    sse.H_SSEC_KEY_MD5:
                        info.metadata.get(sse.META_KEY_MD5, "")}
        if mode == sse.SSE_S3:
            return {sse.H_SSE: "AES256"}
        return {}

    @staticmethod
    def _actual_size(info: ObjectInfo) -> int:
        from ..bucket import tiering
        from ..crypto import sse
        raw = info.metadata.get(sse.META_ACTUAL_SIZE)
        if raw is not None:
            return int(raw)
        tsize = info.metadata.get(tiering.META_TRANSITION_SIZE)
        if tsize is not None and info.size == 0:
            return int(tsize)  # stub: logical size lives in metadata
        return info.size

    def _transitioned_plain(self, req: S3Request, info: ObjectInfo,
                            okey: bytes | None = None,
                            okey_known: bool = False) -> bytes:
        """Full plaintext of a transitioned object, streamed back from
        its tier (ref the transitioned-object read path of
        GetObjectNInfo, cmd/bucket-lifecycle.go). Raises
        tiering.TierError when the tier is unreachable/removed."""
        from ..bucket import tiering
        from ..crypto import sse
        from ..utils import compress
        raw = self.tiers.read(info.metadata)
        if not okey_known:
            okey = self._sse_unseal_for_read(req, info)
        if okey is not None:
            def read_fn(off, ln):
                if off is None:
                    return len(raw)
                return raw[off:off + ln]
            raw = sse.decrypt_range(read_fn, okey, 0, len(raw))
        if info.metadata.get(compress.META_COMPRESSION):
            raw = compress.decompress_stream(raw)
        return raw

    def _sse_decrypt_read(self, version_id: str, info: ObjectInfo,
                          okey: bytes, offset: int,
                          length: int) -> bytes:
        """Read [offset, offset+length) of the PLAINTEXT, touching only
        the parts/packages that cover the range. Multipart ciphertexts
        are per-part DARE streams (per-part derived keys) stitched by
        part sizes (ref DecryptBlocksRequestR part-boundary walk,
        cmd/encryption-v1.go:356)."""
        from ..crypto import sse
        multipart = info.metadata.get(sse.META_SSE_MULTIPART) == "1"

        def ranged_read(base_off, size_limit):
            def read_fn(off, ln):
                if off is None:
                    return size_limit
                data, _ = self.layer.get_object(
                    info.bucket, info.name, offset=base_off + off,
                    length=min(ln, size_limit - off),
                    version_id=version_id)
                return data
            return read_fn

        try:
            if not multipart:
                return sse.decrypt_range(ranged_read(0, info.size),
                                         okey, offset, length)
            # Walk parts by PLAINTEXT offsets; decrypt only coverers.
            out = []
            plain_pos = ct_pos = 0
            want_end = offset + length
            for p in info.parts:
                plain_end = plain_pos + p.actual_size
                if plain_end <= offset:
                    plain_pos, ct_pos = plain_end, ct_pos + p.size
                    continue
                if plain_pos >= want_end:
                    break
                pkey = sse.derive_part_key(okey, p.number)
                sub_off = max(0, offset - plain_pos)
                sub_len = min(plain_end, want_end) - \
                    (plain_pos + sub_off)
                out.append(sse.decrypt_range(
                    ranged_read(ct_pos, p.size), pkey, sub_off,
                    sub_len))
                plain_pos, ct_pos = plain_end, ct_pos + p.size
            return b"".join(out)
        except sse.SSEError:
            raise s3err.ERR_INTERNAL_ERROR

    def put_object(self, req: S3Request) -> S3Response:
        from ..utils import compress, streams
        from ..utils.phasetimer import PUT as _PUT
        if "x-amz-copy-source" in req.headers:
            return self.copy_object(req)
        _t_start = time.perf_counter()
        size_hint = (req.content_length if req.body_stream is not None
                     else len(req.body))
        if size_hint > MAX_OBJECT_SIZE:
            raise s3err.ERR_ENTITY_TOO_LARGE
        meta = {"content-type": req.headers.get("content-type")
                or _mime_for(req.key)}
        # Only non-streaming layers (gateways) buffer the body; SSE and
        # compression run as streaming transform readers in the chain
        # below, so every PUT keeps O(batch) memory (round-3 verdict
        # weak #4; ref sio/S2 reader pipelines, cmd/encryption-v1.go:201,
        # cmd/object-api-utils.go:898).
        if req.body_stream is not None and not getattr(
                self.layer, "supports_streaming_put", False):
            req.body = _drain_stream(req.body_stream)
            req.body_stream = None
            req.content_length = len(req.body)
        md5_header = req.headers.get("content-md5", "")
        want_md5 = base64.b64decode(md5_header) if md5_header else None
        if req.body_stream is None and want_md5 is not None:
            if hashlib.md5(req.body).digest() != want_md5:
                raise s3err.ERR_BAD_DIGEST
        for k, v in req.headers.items():
            if k.startswith("x-amz-meta-"):
                meta[k] = v
        if "x-amz-tagging" in req.headers:
            meta["x-amz-tagging"] = req.headers["x-amz-tagging"]
        self._apply_lock_headers(req, meta)
        parity = self._parity_for_request(req)
        algorithm = self._regen_algorithm_for_request(req)
        if req.headers.get("x-amz-storage-class"):
            meta["x-amz-storage-class"] = req.headers[
                "x-amz-storage-class"]
        self._check_quota(req.bucket, max(size_hint, 0))
        if req.body_stream is not None:
            # Verify declared md5/sha256/length at stream end — a
            # mismatch aborts the engine write before commit (ref
            # pkg/hash/reader.go).
            sha_hdr = req.headers.get("x-amz-content-sha256", "")
            want_sha = sha_hdr if len(sha_hdr) == 64 else ""
            body = streams.HashingReader(
                req.body_stream, want_md5=want_md5,
                want_sha256=want_sha,
                expect_size=req.content_length)
            body = self._wrap_transform_readers(req, body, meta,
                                                max(size_hint, 0))
        else:
            body = self._maybe_compress(req.key, req.body, meta)
            body = self._sse_encrypt_body(req, body, meta)
        self._replication_decision(req, meta)
        versioned = self._versioned(req.bucket)
        replaced = self._usage_replaced_size(req.bucket, req.key,
                                             versioned)
        _PUT.record("transform",
                    (time.perf_counter() - _t_start) * 1e3)
        _t_layer = time.perf_counter()
        try:
            # algorithm only reaches erasure layers (the FS layer's
            # put_object has no such seam, and _regen_algorithm_for_
            # request answers None there).
            extra = {"algorithm": algorithm} if algorithm else {}
            info = self.layer.put_object(
                req.bucket, req.key, body, metadata=meta,
                versioned=versioned,
                parity_shards=parity, **extra)
        except streams.ChecksumError as e:
            if "MD5" in str(e):
                raise s3err.ERR_BAD_DIGEST
            raise s3err.ERR_SIGNATURE_DOES_NOT_MATCH
        except BucketNotFound:
            raise s3err.ERR_NO_SUCH_BUCKET
        except MethodNotAllowed:
            raise s3err.ERR_NOT_IMPLEMENTED
        except ParentIsObject:
            raise s3err.ERR_PARENT_IS_OBJECT
        _t_post = time.perf_counter()
        _PUT.record("layer_total", (_t_post - _t_layer) * 1e3)
        self._usage_add(req.bucket, info.size - replaced)
        h = {"ETag": f'"{info.etag}"'}
        h.update(self._sse_response_headers(info))
        if info.version_id:
            h["x-amz-version-id"] = info.version_id
        from ..event import event as ev
        self._notify(ev.OBJECT_CREATED_PUT, req.bucket, req.key, info)
        self._queue_replication(req, info, meta)
        _PUT.record("post", (time.perf_counter() - _t_post) * 1e3)
        return S3Response(200, headers=h)

    def copy_object(self, req: S3Request) -> S3Response:
        src = urllib.parse.unquote(req.headers["x-amz-copy-source"])
        src = src.lstrip("/")
        if "/" not in src:
            raise s3err.ERR_INVALID_ARGUMENT
        sbucket, skey = src.split("/", 1)
        from ..crypto import sse
        from ..utils import compress
        try:
            data, sinfo = self._read_object_plain(
                req, bucket=sbucket, key=skey, copy_source=True)
        except (ObjectNotFound, BucketNotFound):
            raise s3err.ERR_NO_SUCH_KEY
        if check_preconditions(req, sinfo,
                               prefix="x-amz-copy-source-"):
            raise s3err.ERR_PRECONDITION_FAILED
        meta = dict(sinfo.metadata)
        if req.headers.get("x-amz-metadata-directive") == "REPLACE":
            meta = {"content-type": req.headers.get(
                "content-type", "application/octet-stream")}
            for k, v in req.headers.items():
                if k.startswith("x-amz-meta-"):
                    meta[k] = v
        # The copy re-evaluates encryption/compression for the
        # destination; the source's envelope must never leak across.
        from ..bucket import objectlock as ol
        from ..bucket import tiering as tier_mod
        from ..bucket.replication import META_REPLICATION_STATUS
        for k in (sse.META_ALGORITHM, sse.META_SEALED_KEY,
                  sse.META_KEY_MD5, sse.META_KMS_KEY_ID,
                  sse.META_ACTUAL_SIZE, compress.META_COMPRESSION,
                  META_REPLICATION_STATUS, ol.META_MODE,
                  ol.META_RETAIN_UNTIL, ol.META_LEGAL_HOLD,
                  tier_mod.META_TRANSITION_TIER,
                  tier_mod.META_TRANSITION_KEY,
                  tier_mod.META_TRANSITION_SIZE,
                  tier_mod.META_TRANSITION_ETAG,
                  tier_mod.META_RESTORE, tier_mod.META_RESTORE_EXPIRY,
                  "etag"):
            meta.pop(k, None)
        self._apply_lock_headers(req, meta)
        self._check_quota(req.bucket, len(data))
        data = self._maybe_compress(req.key, data, meta)
        data = self._sse_encrypt_body(req, data, meta)
        self._replication_decision(req, meta)
        versioned = self._versioned(req.bucket)
        replaced = self._usage_replaced_size(req.bucket, req.key,
                                             versioned)
        info = self.layer.put_object(req.bucket, req.key, data,
                                     metadata=meta,
                                     versioned=versioned)
        self._usage_add(req.bucket, info.size - replaced)
        self._queue_replication(req, info, meta)
        root = Element("CopyObjectResult", S3_XMLNS)
        root.child("ETag", f'"{info.etag}"')
        root.child("LastModified", _iso8601(info.mod_time))
        from ..event import event as ev
        self._notify(ev.OBJECT_CREATED_COPY, req.bucket, req.key, info)
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def _read_object_plain(self, req: S3Request, version_id: str = "",
                           bucket: str | None = None,
                           key: str | None = None,
                           copy_source: bool = False,
                           ) -> tuple[bytes, "ObjectInfo"]:
        """Full object bytes after SSE decrypt + decompression — the
        shared tail of CopyObject's source read and SELECT's scan (ref
        the GetObjectNInfo pipeline both reuse)."""
        from ..utils import compress
        bucket = req.bucket if bucket is None else bucket
        key = req.key if key is None else key
        info = self.layer.get_object_info(bucket, key, version_id)
        from ..bucket import tiering as tier_mod
        if tier_mod.needs_tier_read(info.metadata):
            try:
                return self._transitioned_plain(req, info), info
            except tier_mod.TierError as e:
                raise s3err.APIError("XMinioTierError", str(e), 503)
        okey = self._sse_unseal_for_read(req, info,
                                         copy_source=copy_source)
        if okey is not None:
            data = self._sse_decrypt_read(version_id, info, okey, 0,
                                          info.size)
        else:
            data, info = self.layer.get_object(bucket, key,
                                               version_id=version_id)
        if info.metadata.get(compress.META_COMPRESSION):
            try:
                data = compress.decompress_stream(data)
            except ValueError:
                raise s3err.ERR_INTERNAL_ERROR
        return data, info

    def select_object_content(self, req: S3Request) -> S3Response:
        """POST /bucket/key?select&select-type=2 (ref
        SelectObjectContentHandler, cmd/object-handlers.go; routed
        cmd/api-router.go:161)."""
        from ..s3select.select import S3SelectError, parse_request, \
            run_select
        try:
            sel = parse_request(req.body)
        except S3SelectError as e:
            raise s3err.APIError(e.code, e.description, 400)
        version_id = self._version_param(req)
        try:
            data, info = self._read_object_plain(req, version_id)
        except BucketNotFound:
            raise s3err.ERR_NO_SUCH_BUCKET
        except MethodNotAllowed:
            raise s3err.ERR_METHOD_NOT_ALLOWED
        except ObjectNotFound:
            if version_id:
                raise s3err.ERR_NO_SUCH_VERSION
            raise s3err.ERR_NO_SUCH_KEY
        from ..event import event as ev
        self._notify(ev.OBJECT_ACCESSED_GET, req.bucket, req.key, info)
        return S3Response(200, run_select(sel, data),
                          {"Content-Type": "application/octet-stream"})

    def get_object(self, req: S3Request, head: bool = False) -> S3Response:
        version_id = self._version_param(req)
        try:
            from ..utils import compress
            info = self.layer.get_object_info(req.bucket, req.key,
                                              version_id)
            okey = self._sse_unseal_for_read(req, info)
            comp = info.metadata.get(compress.META_COMPRESSION)
            # Ranges address the PLAINTEXT for transformed objects (ref
            # DecryptObjectInfo size rewrite).
            size = self._actual_size(info)
            status = check_preconditions(req, info)
            if status == 304:
                return S3Response(304, b"",
                                  self._object_headers(info))
            if status == 412:
                raise s3err.ERR_PRECONDITION_FAILED
            rng = _parse_range(req.headers.get("range", ""), size)
            data = b""
            from ..bucket import tiering as tier_mod
            if not head and tier_mod.needs_tier_read(info.metadata):
                try:
                    plain = self._transitioned_plain(
                        req, info, okey=okey, okey_known=True)
                except tier_mod.TierError as e:
                    raise s3err.APIError("XMinioTierError", str(e), 503)
                data = (plain if rng is None
                        else plain[rng[0]:rng[0] + rng[1]])
            elif not head:
                stream_fn = getattr(self.layer, "get_object_stream",
                                    None)
                from ..crypto import sse as sse_mod
                # Multipart SSE streams are per-part stitched — the
                # ranged (buffered-per-package-window) path handles
                # them; single-part objects stream end-to-end.
                sse_streamable = (
                    okey is not None and stream_fn is not None
                    and len(info.parts) <= 1
                    and not info.metadata.get(sse_mod.META_SSE_MULTIPART))
                if comp:
                    # SSE's inner plaintext IS the compressed stream;
                    # its length <= stored size, so that bound reads all.
                    if okey is not None:
                        if sse_streamable and info.size > 0:
                            _, ct = stream_fn(req.bucket, req.key,
                                              offset=0,
                                              length=info.size,
                                              version_id=version_id)
                            plain_iter = sse_mod.iter_decrypt(
                                ct, okey, info.size)
                        else:
                            plain_iter = iter([self._sse_decrypt_read(
                                version_id, info, okey, 0, info.size)])
                    elif stream_fn is not None:
                        _, plain_iter = stream_fn(
                            req.bucket, req.key, version_id=version_id)
                    else:
                        blob, _ = self.layer.get_object(
                            req.bucket, req.key, version_id=version_id)
                        plain_iter = iter([blob])
                    try:
                        # Streaming decompress; errors mid-iteration
                        # surface when the response body is consumed.
                        if rng is None:
                            data = compress.iter_decompress(plain_iter)
                        else:
                            data = compress.iter_decompress_range(
                                plain_iter, rng[0], rng[1])
                        if stream_fn is None:
                            data = b"".join(data)
                    except ValueError:
                        raise s3err.ERR_INTERNAL_ERROR
                elif okey is not None:
                    off, ln = rng if rng is not None else (0, size)
                    if ln <= 0:
                        # Still authenticate package 0 (an empty object
                        # has one sealed empty final package — tampering
                        # must surface, not be skipped).
                        data = self._sse_decrypt_read(
                            version_id, info, okey, 0, 0)
                    elif sse_streamable:
                        # Package-aligned ciphertext range -> streaming
                        # decrypt -> trim to the requested plaintext
                        # window. O(package) memory for any size.
                        full = sse_mod.PKG_SIZE + sse_mod.PKG_OVERHEAD
                        first = off // sse_mod.PKG_SIZE
                        last = (off + ln - 1) // sse_mod.PKG_SIZE
                        base_blob, _ = self.layer.get_object(
                            req.bucket, req.key, offset=0, length=8,
                            version_id=version_id)
                        ct_off = 8 + first * full
                        ct_len = min(info.size - ct_off,
                                     (last - first + 1) * full)
                        _, ct = stream_fn(req.bucket, req.key,
                                          offset=ct_off, length=ct_len,
                                          version_id=version_id)
                        import itertools
                        plain = sse_mod.iter_decrypt(
                            itertools.chain([base_blob], ct), okey,
                            info.size, first_pkg=first, last_pkg=last)
                        data = _trim_iter(plain,
                                          off - first * sse_mod.PKG_SIZE,
                                          ln)
                    else:
                        data = self._sse_decrypt_read(
                            version_id, info, okey, off, ln)
                else:
                    # Plain object: stream decoded blocks straight to
                    # the socket when the layer supports it (O(group)
                    # memory for any object size).
                    off, ln = rng if rng is not None else (0, size)
                    stream_fn = getattr(self.layer, "get_object_stream",
                                        None)
                    if stream_fn is not None:
                        info, data = stream_fn(req.bucket, req.key,
                                               offset=off, length=ln,
                                               version_id=version_id)
                    else:
                        data, info = self.layer.get_object(
                            req.bucket, req.key, offset=off, length=ln,
                            version_id=version_id)
        except BucketNotFound:
            raise s3err.ERR_NO_SUCH_BUCKET
        except MethodNotAllowed:
            raise s3err.ERR_METHOD_NOT_ALLOWED
        except ObjectNotFound:
            if version_id:
                raise s3err.ERR_NO_SUCH_VERSION
            raise s3err.ERR_NO_SUCH_KEY

        headers = self._object_headers(info)
        headers.update(self._sse_response_headers(info))
        from ..event import event as ev
        self._notify(ev.OBJECT_ACCESSED_HEAD if head
                     else ev.OBJECT_ACCESSED_GET,
                     req.bucket, req.key, info)
        if head:
            headers["Content-Length"] = str(size)
            return S3Response(200, b"", headers)
        if not isinstance(data, (bytes, bytearray)):
            headers["Content-Length"] = str(
                rng[1] if rng is not None else size)
        if rng is not None:
            off, ln = rng
            headers["Content-Range"] = (
                f"bytes {off}-{off + ln - 1}/{size}")
            return S3Response(206, data, headers)
        return S3Response(200, data, headers)

    # ---------------- multipart ----------------

    def _sse_init_multipart(self, req: S3Request, meta: dict) -> None:
        """Create the upload's SSE envelope at initiate time; each part
        then encrypts under a key DERIVED from this object key by part
        number (ref newMultipartUpload + DerivePartKey)."""
        from ..crypto import sse
        picked = self._sse_mode_for_request(req)
        if picked is None:
            return
        self._sse_seal_into_meta(req, *picked, meta)
        meta[sse.META_SSE_MULTIPART] = "1"

    def _sse_part_key(self, req: S3Request,
                      part_number: int) -> bytes | None:
        """Per-part derived key for an encrypted upload; the per-part
        request must carry SSE-C credentials again (ref PutObjectPart
        SSE checks)."""
        from ..crypto import sse
        from ..erasure.multipart import UploadNotFound
        try:
            meta = self.layer.multipart.get_upload_meta(
                req.bucket, req.key, req.params["uploadId"])
        except UploadNotFound:
            raise s3err.ERR_NO_SUCH_UPLOAD
        okey = self._sse_unseal_from_meta(req, meta, req.bucket, req.key)
        if okey is None:
            return None
        return sse.derive_part_key(okey, part_number)

    def initiate_multipart(self, req: S3Request) -> S3Response:
        from ..erasure.engine import BucketNotFound as BNF
        meta = {"content-type": req.headers.get(
            "content-type", "application/octet-stream")}
        for k, v in req.headers.items():
            if k.startswith("x-amz-meta-"):
                meta[k] = v
        self._apply_lock_headers(req, meta)
        self._sse_init_multipart(req, meta)
        try:
            upload_id = self.layer.multipart.new_multipart_upload(
                req.bucket, req.key, meta)
        except BNF:
            raise s3err.ERR_NO_SUCH_BUCKET
        root = Element("InitiateMultipartUploadResult", S3_XMLNS)
        root.child("Bucket", req.bucket)
        root.child("Key", req.key)
        root.child("UploadId", upload_id)
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def upload_part_copy(self, req: S3Request) -> S3Response:
        """PUT ?partNumber&uploadId with x-amz-copy-source: source
        bytes (optionally x-amz-copy-source-range) become the part
        (ref CopyObjectPartHandler, cmd/object-handlers.go)."""
        from ..erasure.multipart import InvalidPart, UploadNotFound
        src = urllib.parse.unquote(req.headers["x-amz-copy-source"])
        src = src.lstrip("/")
        if "/" not in src:
            raise s3err.ERR_INVALID_ARGUMENT
        sbucket, skey = src.split("/", 1)
        try:
            data, sinfo = self._read_object_plain(
                req, bucket=sbucket, key=skey, copy_source=True)
        except (ObjectNotFound, BucketNotFound):
            raise s3err.ERR_NO_SUCH_KEY
        if check_preconditions(req, sinfo,
                               prefix="x-amz-copy-source-"):
            raise s3err.ERR_PRECONDITION_FAILED
        rng = req.headers.get("x-amz-copy-source-range", "")
        if rng:
            parsed = _parse_range(rng, len(data))
            if parsed is None:
                raise s3err.ERR_INVALID_ARGUMENT
            off, ln = parsed
            data = data[off:off + ln]
        if len(data) > MAX_OBJECT_SIZE:
            raise s3err.ERR_ENTITY_TOO_LARGE
        self._check_quota(req.bucket, len(data))
        part_number = int(req.params["partNumber"])
        body, actual = data, None
        pkey = self._sse_part_key(req, part_number)
        if pkey is not None:
            from ..crypto import sse
            body = sse.encrypt_stream(data, pkey)
            actual = len(data)
        try:
            part = self.layer.multipart.put_object_part(
                req.bucket, req.key, req.params["uploadId"],
                part_number, body, actual_size=actual)
        except UploadNotFound:
            raise s3err.ERR_NO_SUCH_UPLOAD
        except (InvalidPart, ValueError):
            raise s3err.ERR_INVALID_ARGUMENT
        root = Element("CopyPartResult", S3_XMLNS)
        root.child("ETag", f'"{part["etag"]}"')
        root.child("LastModified", _iso8601(time.time()))
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def put_part(self, req: S3Request) -> S3Response:
        from ..erasure.multipart import InvalidPart, UploadNotFound
        from ..utils import streams
        part_number = int(req.params["partNumber"])
        pkey = self._sse_part_key(req, part_number)
        if req.body_stream is not None and (
                pkey is not None
                or not getattr(self.layer, "supports_streaming_put",
                               False)):
            # Encrypted parts (whole-part DARE transform) and
            # non-streaming layers still buffer.
            req.body = _drain_stream(req.body_stream)
            req.body_stream = None
            req.content_length = len(req.body)
        size_hint = (req.content_length if req.body_stream is not None
                     else len(req.body))
        if size_hint > MAX_OBJECT_SIZE:
            raise s3err.ERR_ENTITY_TOO_LARGE
        md5_header = req.headers.get("content-md5", "")
        want_md5 = base64.b64decode(md5_header) if md5_header else None
        if req.body_stream is None and want_md5 is not None:
            if hashlib.md5(req.body).digest() != want_md5:
                raise s3err.ERR_BAD_DIGEST
        self._check_quota(req.bucket, max(size_hint, 0))
        actual = None
        if req.body_stream is not None:
            body = streams.HashingReader(
                req.body_stream, want_md5=want_md5,
                expect_size=req.content_length)
        else:
            body = req.body
            if pkey is not None:
                from ..crypto import sse
                body = sse.encrypt_stream(req.body, pkey)
                actual = len(req.body)
        try:
            part = self.layer.multipart.put_object_part(
                req.bucket, req.key, req.params["uploadId"],
                part_number, body, actual_size=actual)
        except streams.ChecksumError as e:
            if "MD5" in str(e):
                raise s3err.ERR_BAD_DIGEST
            raise s3err.ERR_SIGNATURE_DOES_NOT_MATCH
        except UploadNotFound:
            raise s3err.ERR_NO_SUCH_UPLOAD
        except (InvalidPart, ValueError):
            raise s3err.ERR_INVALID_ARGUMENT
        return S3Response(200, headers={"ETag": f'"{part["etag"]}"'})

    def complete_multipart(self, req: S3Request) -> S3Response:
        from ..erasure.multipart import (InvalidPart, PartTooSmall,
                                         UploadNotFound)
        try:
            doc = parse(req.body)
            parts = [(int(p.findtext("PartNumber")),
                      (p.findtext("ETag") or "").strip('"'))
                     for p in doc.findall("Part")]
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        try:
            staged = self.layer.multipart.list_parts(
                req.bucket, req.key, req.params["uploadId"])
            self._check_quota(req.bucket,
                              sum(p["size"] for p in staged))
            replaced = self._usage_replaced_size(
                req.bucket, req.key, self._versioned(req.bucket))
            info = self.layer.multipart.complete_multipart_upload(
                req.bucket, req.key, req.params["uploadId"], parts)
            self._usage_add(req.bucket, info.size - replaced)
        except UploadNotFound:
            raise s3err.ERR_NO_SUCH_UPLOAD
        except PartTooSmall:
            raise s3err.ERR_ENTITY_TOO_SMALL
        except InvalidPart as e:
            if "ascending" in str(e):
                raise s3err.ERR_INVALID_PART_ORDER
            raise s3err.ERR_INVALID_PART
        except ParentIsObject:
            raise s3err.ERR_PARENT_IS_OBJECT
        root = Element("CompleteMultipartUploadResult", S3_XMLNS)
        root.child("Location",
                   f"http://{req.headers.get('host', '')}"
                   f"/{req.bucket}/{req.key}")
        root.child("Bucket", req.bucket)
        root.child("Key", req.key)
        root.child("ETag", f'"{info.etag}"')
        from ..event import event as ev
        self._notify(ev.OBJECT_CREATED_COMPLETE_MULTIPART,
                     req.bucket, req.key, info)
        # Multipart metadata was fixed at initiate time; stamp + queue
        # the replication AFTER the stitch (ref CompleteMultipartUpload
        # replication hook, cmd/object-handlers.go).
        if self.replication.must_replicate(req.bucket, req.key):
            from ..bucket.replication import (META_REPLICATION_STATUS,
                                              PENDING)
            try:
                self.layer.update_object_metadata(
                    req.bucket, req.key,
                    {META_REPLICATION_STATUS: PENDING}, info.version_id)
            except Exception:
                pass
            self.replication.queue_task(req.bucket, req.key,
                                        info.version_id, "put")
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def abort_multipart(self, req: S3Request) -> S3Response:
        from ..erasure.multipart import UploadNotFound
        try:
            self.layer.multipart.abort_multipart_upload(
                req.bucket, req.key, req.params["uploadId"])
        except UploadNotFound:
            raise s3err.ERR_NO_SUCH_UPLOAD
        return S3Response(204)

    def list_parts(self, req: S3Request) -> S3Response:
        from ..erasure.multipart import UploadNotFound
        try:
            parts = self.layer.multipart.list_parts(
                req.bucket, req.key, req.params["uploadId"])
        except UploadNotFound:
            raise s3err.ERR_NO_SUCH_UPLOAD
        root = Element("ListPartsResult", S3_XMLNS)
        root.child("Bucket", req.bucket)
        root.child("Key", req.key)
        root.child("UploadId", req.params["uploadId"])
        root.child("IsTruncated", False)
        for p in parts:
            e = root.child("Part")
            e.child("PartNumber", p["number"])
            e.child("ETag", f'"{p["etag"]}"')
            # Logical (pre-SSE/compression) size, as AWS reports.
            e.child("Size", p.get("actualSize", p["size"]))
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def list_multipart_uploads(self, req: S3Request) -> S3Response:
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET
        uploads = self.layer.multipart.list_uploads(
            req.bucket, req.params.get("prefix", ""))
        root = Element("ListMultipartUploadsResult", S3_XMLNS)
        root.child("Bucket", req.bucket)
        root.child("IsTruncated", False)
        for u in uploads:
            e = root.child("Upload")
            e.child("Key", u["object"])
            e.child("UploadId", u["upload_id"])
            e.child("Initiated", _iso8601(u["created"]))
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    # ---------------- versioning ----------------

    def get_versioning(self, req: S3Request) -> S3Response:
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET
        status = self.bucket_meta.get(req.bucket).versioning
        root = Element("VersioningConfiguration", S3_XMLNS)
        if status:
            root.child("Status", status)
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def put_versioning(self, req: S3Request) -> S3Response:
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET
        try:
            doc = parse(req.body)
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        status = doc.findtext("Status") or ""
        if status not in ("Enabled", "Suspended"):
            raise s3err.ERR_MALFORMED_XML
        if not getattr(self.layer, "supports_versioning", True):
            # ref FS backend: versioning APIs -> NotImplemented
            raise s3err.ERR_NOT_IMPLEMENTED
        if status == "Suspended" and self._lock_config(req.bucket).enabled:
            # Suspension would turn plain deletes into data-destroying
            # deletes, voiding WORM (AWS: InvalidBucketState).
            raise s3err.ERR_INVALID_BUCKET_STATE
        self.bucket_meta.update(req.bucket, versioning=status)
        return S3Response(200)

    def list_object_versions(self, req: S3Request) -> S3Response:
        """GET /bucket?versions with key-marker/version-id-marker
        pagination (ref ListObjectVersionsHandler,
        cmd/bucket-listobjects-handlers.go)."""
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET
        prefix = req.params.get("prefix", "")
        delimiter = req.params.get("delimiter", "")
        key_marker = req.params.get("key-marker", "")
        vid_marker = req.params.get("version-id-marker", "")
        max_keys = min(int(req.params.get("max-keys", "1000") or "1000"),
                       1000)
        try:
            infos = self.layer.list_object_versions(
                req.bucket, prefix=prefix, max_keys=1_000_000)
        except MethodNotAllowed:
            raise s3err.ERR_NOT_IMPLEMENTED  # FS backend (ref fs-v1.go:1444)
        # Build the flat entry stream first: delimiter collapse, latest
        # flags; then cut one page out of it.
        latest_seen: set[str] = set()
        seen_prefix: set[str] = set()
        entries: list[tuple] = []  # (kind, info-or-prefix, is_latest)
        for info in infos:
            if delimiter:
                rest = info.name[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp not in seen_prefix:
                        seen_prefix.add(cp)
                        entries.append(("prefix", cp, False))
                    continue
            is_latest = info.name not in latest_seen
            latest_seen.add(info.name)
            entries.append(("version", info, is_latest))

        start = 0
        if key_marker:
            for i, (kind, item, _) in enumerate(entries):
                key = item if kind == "prefix" else item.name
                vid = "" if kind == "prefix" else (item.version_id
                                                   or "null")
                if key < key_marker:
                    start = i + 1
                elif key == key_marker:
                    # With a version-id-marker resume AFTER that exact
                    # version; without, skip the whole marker key.
                    start = i + 1
                    if vid_marker and vid == vid_marker:
                        break
                else:
                    break
        page = entries[start:start + max_keys]
        truncated = start + max_keys < len(entries)

        root = Element("ListVersionsResult", S3_XMLNS)
        root.child("Name", req.bucket)
        root.child("Prefix", prefix)
        if key_marker:
            root.child("KeyMarker", key_marker)
        if vid_marker:
            root.child("VersionIdMarker", vid_marker)
        root.child("MaxKeys", max_keys)
        if delimiter:
            root.child("Delimiter", delimiter)
        root.child("IsTruncated", truncated)
        if truncated and page:
            kind, item, _ = page[-1]
            root.child("NextKeyMarker",
                       item if kind == "prefix" else item.name)
            if kind != "prefix":
                root.child("NextVersionIdMarker",
                           item.version_id or "null")
        for kind, item, is_latest in page:
            if kind == "prefix":
                p = root.child("CommonPrefixes")
                p.child("Prefix", item)
                continue
            e = root.child("DeleteMarker" if item.delete_marker
                           else "Version")
            e.child("Key", item.name)
            e.child("VersionId", item.version_id or "null")
            e.child("IsLatest", is_latest)
            e.child("LastModified", _iso8601(item.mod_time))
            if not item.delete_marker:
                e.child("ETag", f'"{item.etag}"')
                e.child("Size", self._actual_size(item))
                e.child("StorageClass", "STANDARD")
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    # ---------------- bucket configs ----------------

    def _check_bucket_exists(self, req: S3Request) -> None:
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET

    def get_bucket_policy(self, req: S3Request) -> S3Response:
        self._check_bucket_exists(req)
        policy = self.bucket_meta.get(req.bucket).policy
        if not policy:
            raise s3err.ERR_NO_SUCH_BUCKET_POLICY
        import json as _json
        return S3Response(200, _json.dumps(policy).encode(),
                          {"Content-Type": "application/json"})

    def put_bucket_policy(self, req: S3Request) -> S3Response:
        self._check_bucket_exists(req)
        import json as _json
        try:
            policy = _json.loads(req.body)
            if not isinstance(policy, dict) or "Statement" not in policy:
                raise ValueError
        except ValueError:
            raise s3err.ERR_MALFORMED_POLICY
        self.bucket_meta.update(req.bucket, policy=policy)
        return S3Response(204)

    def delete_bucket_policy(self, req: S3Request) -> S3Response:
        self._check_bucket_exists(req)
        self.bucket_meta.update(req.bucket, policy=None)
        return S3Response(204)

    def _xml_config(self, req: S3Request, field: str, root_tag: str,
                    missing: s3err.APIError) -> S3Response:
        """Shared GET/PUT/DELETE plumbing for XML bucket configs
        (lifecycle, notification, sse, tagging, object-lock,
        replication — ref cmd/bucket-*-handlers.go)."""
        self._check_bucket_exists(req)
        if req.method == "GET":
            raw = getattr(self.bucket_meta.get(req.bucket), field)
            if not raw:
                raise missing
            return S3Response(200, raw.encode(),
                              {"Content-Type": "application/xml"})
        if req.method == "DELETE":
            self.bucket_meta.update(req.bucket, **{field: ""})
            return S3Response(204)
        # PUT: validate the XML parses and the root tag matches.
        try:
            doc = parse(req.body)
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        if root_tag not in doc.tag:
            raise s3err.ERR_MALFORMED_XML
        self.bucket_meta.update(req.bucket,
                                **{field: req.body.decode("utf-8")})
        return S3Response(200)

    def bucket_lifecycle(self, req: S3Request) -> S3Response:
        return self._xml_config(req, "lifecycle_xml",
                                "LifecycleConfiguration",
                                s3err.ERR_NO_SUCH_LIFECYCLE_CONFIG)

    def bucket_notification(self, req: S3Request) -> S3Response:
        # GET of an unset notification config returns an empty document,
        # not an error (ref GetBucketNotificationHandler).
        self._check_bucket_exists(req)
        if req.method == "GET" and not self.bucket_meta.get(
                req.bucket).notification_xml:
            root = Element("NotificationConfiguration", S3_XMLNS)
            return S3Response(200, root.tobytes(),
                              {"Content-Type": "application/xml"})
        return self._xml_config(req, "notification_xml",
                                "NotificationConfiguration",
                                s3err.ERR_MALFORMED_XML)

    def bucket_encryption(self, req: S3Request) -> S3Response:
        return self._xml_config(req, "sse_xml",
                                "ServerSideEncryptionConfiguration",
                                s3err.ERR_NO_SUCH_SSE_CONFIG)

    def bucket_tagging(self, req: S3Request) -> S3Response:
        return self._xml_config(req, "tagging_xml", "Tagging",
                                s3err.ERR_NO_SUCH_TAG_SET)

    def bucket_object_lock(self, req: S3Request) -> S3Response:
        """Lock config is append-only state: it can never be removed or
        disabled once set, or WORM would be trivially escapable (ref
        PutBucketObjectLockConfigHandler gating,
        cmd/bucket-object-lock.go)."""
        from ..bucket import objectlock as ol
        self._check_bucket_exists(req)
        if req.method == "GET":
            raw = self.bucket_meta.get(req.bucket).object_lock_xml
            if not raw:
                raise s3err.ERR_NO_SUCH_OBJECT_LOCK_CONFIG
            return S3Response(200, raw.encode(),
                              {"Content-Type": "application/xml"})
        if req.method == "DELETE":
            raise s3err.ERR_METHOD_NOT_ALLOWED
        if not self._lock_config(req.bucket).enabled:
            raise s3err.ERR_INVALID_BUCKET_STATE
        try:
            cfg = ol.ObjectLockConfig.from_xml(req.body)
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        if not cfg.enabled:
            raise s3err.ERR_MALFORMED_XML
        self.bucket_meta.update(req.bucket,
                                object_lock_xml=req.body.decode("utf-8"))
        return S3Response(200)

    def bucket_replication(self, req: S3Request) -> S3Response:
        return self._xml_config(req, "replication_xml",
                                "ReplicationConfiguration",
                                s3err.ERR_NO_SUCH_REPLICATION_CONFIG)

    def bucket_cors(self, req: S3Request) -> S3Response:
        return self._xml_config(req, "cors_xml", "CORSConfiguration",
                                s3err.ERR_NO_SUCH_CORS_CONFIG)

    # ---------------- CORS evaluation ----------------

    def cors_rules(self, bucket: str) -> list[dict]:
        raw = self.bucket_meta.get(bucket).cors_xml
        if not raw:
            return []
        try:
            doc = parse(raw.encode())
        except Exception:
            return []
        rules = []
        for r in doc.findall("CORSRule"):
            rules.append({
                "origins": [e.text or "" for e in
                            r.findall("AllowedOrigin")],
                "methods": [(e.text or "").upper() for e in
                            r.findall("AllowedMethod")],
                "headers": [(e.text or "").lower() for e in
                            r.findall("AllowedHeader")],
                "expose": [e.text or "" for e in
                           r.findall("ExposeHeader")],
                "max_age": r.findtext("MaxAgeSeconds") or "",
            })
        return rules

    @staticmethod
    def _origin_matches(pattern: str, origin: str) -> bool:
        if pattern == "*":
            return True
        if "*" in pattern:
            pre, _, post = pattern.partition("*")
            return (origin.startswith(pre) and origin.endswith(post)
                    and len(origin) >= len(pre) + len(post))
        return pattern == origin

    def cors_match(self, bucket: str, origin: str,
                   method: str) -> dict | None:
        """First rule allowing (origin, method), else None (ref the
        CORS filter the reference serves from bucket metadata)."""
        if not origin:
            return None
        for rule in self.cors_rules(bucket):
            if method.upper() not in rule["methods"]:
                continue
            if any(self._origin_matches(p, origin)
                   for p in rule["origins"]):
                return rule
        return None

    # ---------------- object tagging ----------------

    def object_tagging(self, req: S3Request) -> S3Response:
        version_id = self._version_param(req)
        if req.method == "GET":
            try:
                info = self.layer.get_object_info(req.bucket, req.key,
                                                  version_id)
            except MethodNotAllowed:
                raise s3err.ERR_METHOD_NOT_ALLOWED
            except (ObjectNotFound, BucketNotFound):
                raise s3err.ERR_NO_SUCH_KEY
            root = Element("Tagging", S3_XMLNS)
            tagset = root.child("TagSet")
            if hasattr(self.layer, "get_object_tags"):
                # Gateway layers fetch tags from the upstream.
                try:
                    raw = self.layer.get_object_tags(
                        req.bucket, req.key, version_id)
                except (ObjectNotFound, BucketNotFound):
                    raise s3err.ERR_NO_SUCH_KEY
                except MethodNotAllowed:
                    raise s3err.ERR_METHOD_NOT_ALLOWED
            else:
                raw = info.metadata.get("x-amz-tagging", "")
            for pair in raw.split("&") if raw else []:
                k, _, v = pair.partition("=")
                t = tagset.child("Tag")
                t.child("Key", urllib.parse.unquote_plus(k))
                t.child("Value", urllib.parse.unquote_plus(v))
            return S3Response(200, root.tobytes(),
                              {"Content-Type": "application/xml"})
        if req.method == "DELETE":
            self._set_object_tags(req, version_id, "")
            return S3Response(204)
        try:
            doc = parse(req.body)
            pairs = []
            for t in doc.find("TagSet").findall("Tag"):
                pairs.append(
                    f"{urllib.parse.quote_plus(t.findtext('Key') or '')}"
                    f"={urllib.parse.quote_plus(t.findtext('Value') or '')}")
            if len(pairs) > 10:
                raise s3err.ERR_INVALID_ARGUMENT
        except s3err.APIError:
            raise
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        self._set_object_tags(req, version_id, "&".join(pairs))
        return S3Response(200)

    def _set_object_tags(self, req: S3Request, version_id: str,
                         tags: str) -> None:
        try:
            self.layer.put_object_tags(req.bucket, req.key, tags,
                                       version_id)
        except MethodNotAllowed:
            raise s3err.ERR_METHOD_NOT_ALLOWED
        except (ObjectNotFound, BucketNotFound):
            raise s3err.ERR_NO_SUCH_KEY

    # ---------------- object lock ----------------

    def _lock_config(self, bucket: str):
        from ..bucket import objectlock as ol
        try:
            return ol.ObjectLockConfig.from_xml(
                self.bucket_meta.get(bucket).object_lock_xml)
        except ol.ObjectLockError:
            return ol.ObjectLockConfig()

    def _apply_lock_headers(self, req: S3Request, meta: dict) -> None:
        """Stamp retention/legal-hold metadata on a new object/upload
        from its headers or the bucket default."""
        from ..bucket import objectlock as ol
        cfg = self._lock_config(req.bucket)
        has_hdrs = (ol.META_MODE in req.headers
                    or ol.META_RETAIN_UNTIL in req.headers
                    or ol.META_LEGAL_HOLD in req.headers)
        if not cfg.enabled:
            if has_hdrs:
                raise s3err.ERR_INVALID_BUCKET_STATE
            return
        try:
            ol.apply_put_headers(req.headers, cfg, meta)
        except ol.PastRetainDate:
            raise s3err.ERR_PAST_OBJECT_LOCK_RETAIN_DATE
        except ol.BadLockDate:
            raise s3err.ERR_INVALID_ARGUMENT
        except ol.ObjectLockError:
            raise s3err.ERR_INVALID_RETENTION_MODE

    @staticmethod
    def _can_bypass_governance(req: S3Request) -> bool:
        """Header present; the s3:BypassGovernanceRetention grant is
        enforced by S3Server.authorize before dispatch."""
        from ..bucket import objectlock as ol
        return req.headers.get(ol.H_BYPASS_GOVERNANCE,
                               "").lower() == "true"

    def _check_version_delete_allowed(self, bucket: str, key: str,
                                      version_id: str,
                                      bypass: bool) -> None:
        """Versioned deletes destroy data: enforce WORM on the target
        version (plain deletes only write markers and pass)."""
        from ..bucket import objectlock as ol
        if not version_id:
            return
        if not self._lock_config(bucket).enabled:
            return
        try:
            info = self.layer.get_object_info(bucket, key, version_id)
        except (ObjectNotFound, BucketNotFound, MethodNotAllowed):
            return  # missing/marker version: nothing to protect
        if info.delete_marker:
            return
        try:
            ol.check_version_delete(info.metadata, bypass)
        except ol.ObjectLockError:
            raise s3err.ERR_OBJECT_LOCKED

    def object_retention(self, req: S3Request) -> S3Response:
        """GET/PUT /bucket/key?retention (ref
        PutObjectRetentionHandler, cmd/object-handlers.go)."""
        from ..bucket import objectlock as ol
        version_id = self._version_param(req)
        try:
            info = self.layer.get_object_info(req.bucket, req.key,
                                              version_id)
        except (ObjectNotFound, BucketNotFound):
            raise s3err.ERR_NO_SUCH_KEY
        except MethodNotAllowed:
            raise s3err.ERR_METHOD_NOT_ALLOWED
        if req.method == "GET":
            mode = info.metadata.get(ol.META_MODE, "")
            until = info.metadata.get(ol.META_RETAIN_UNTIL, "")
            if not mode:
                raise s3err.ERR_NO_SUCH_RETENTION
            root = Element("Retention", S3_XMLNS)
            root.child("Mode", mode)
            root.child("RetainUntilDate", until)
            return S3Response(200, root.tobytes(),
                              {"Content-Type": "application/xml"})
        if not self._lock_config(req.bucket).enabled:
            raise s3err.ERR_INVALID_BUCKET_STATE
        try:
            mode, ts = ol.parse_retention_xml(req.body)
        except ol.ObjectLockError:
            raise s3err.ERR_INVALID_RETENTION_MODE
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        import time as _time
        if ts <= _time.time():
            raise s3err.ERR_PAST_OBJECT_LOCK_RETAIN_DATE
        try:
            ol.check_retention_update(info.metadata, mode, ts,
                                      self._can_bypass_governance(req))
        except ol.ObjectLockError:
            raise s3err.ERR_OBJECT_LOCKED
        self.layer.update_object_metadata(
            req.bucket, req.key,
            {ol.META_MODE: mode, ol.META_RETAIN_UNTIL: ol.iso8601(ts)},
            version_id)
        return S3Response(200)

    def object_legal_hold(self, req: S3Request) -> S3Response:
        from ..bucket import objectlock as ol
        version_id = self._version_param(req)
        try:
            info = self.layer.get_object_info(req.bucket, req.key,
                                              version_id)
        except (ObjectNotFound, BucketNotFound):
            raise s3err.ERR_NO_SUCH_KEY
        except MethodNotAllowed:
            raise s3err.ERR_METHOD_NOT_ALLOWED
        if req.method == "GET":
            status = info.metadata.get(ol.META_LEGAL_HOLD, "")
            if not status:
                raise s3err.ERR_NO_SUCH_RETENTION
            root = Element("LegalHold", S3_XMLNS)
            root.child("Status", status)
            return S3Response(200, root.tobytes(),
                              {"Content-Type": "application/xml"})
        if not self._lock_config(req.bucket).enabled:
            raise s3err.ERR_INVALID_BUCKET_STATE
        try:
            status = ol.parse_legal_hold_xml(req.body)
        except ol.ObjectLockError:
            raise s3err.ERR_MALFORMED_XML
        except Exception:
            raise s3err.ERR_MALFORMED_XML
        self.layer.update_object_metadata(
            req.bucket, req.key, {ol.META_LEGAL_HOLD: status}, version_id)
        return S3Response(200)

    def post_policy_upload(self, req: S3Request, form,
                           key: str) -> S3Response:
        """Store a browser form upload through the SAME pipeline as a
        PUT — bucket-default SSE, object-lock defaults, compression,
        replication all apply (ref PostPolicyBucketHandler,
        cmd/api-router.go:304; policy checks already done)."""
        if not self.layer.bucket_exists(req.bucket):
            raise s3err.ERR_NO_SUCH_BUCKET
        if len(form.file_data) > MAX_OBJECT_SIZE:
            raise s3err.ERR_ENTITY_TOO_LARGE
        # Synthetic PUT view of the form: fields become headers so the
        # shared lock/SSE/storage-class helpers read them uniformly.
        sub = S3Request("PUT", req.raw_path, "", {
            k.lower(): v for k, v in form.fields.items()},
            form.file_data)
        sub.bucket, sub.key = req.bucket, key
        meta = {"content-type": form.file_content_type
                or form.fields.get("Content-Type",
                                   "application/octet-stream")}
        for k, v in form.fields.items():
            if k.lower().startswith("x-amz-meta-"):
                meta[k.lower()] = v
        self._apply_lock_headers(sub, meta)
        parity = self._parity_for_request(sub)
        self._check_quota(req.bucket, len(form.file_data))
        body = self._maybe_compress(key, form.file_data, meta)
        body = self._sse_encrypt_body(sub, body, meta)
        self._replication_decision(sub, meta)
        try:
            versioned = self._versioned(req.bucket)
            replaced = self._usage_replaced_size(req.bucket, key,
                                                 versioned)
            info = self.layer.put_object(
                req.bucket, key, body, metadata=meta,
                versioned=versioned,
                parity_shards=parity)
            self._usage_add(req.bucket, info.size - replaced)
        except ParentIsObject:
            raise s3err.ERR_PARENT_IS_OBJECT
        from ..event import event as ev
        self._notify(ev.OBJECT_CREATED_POST, req.bucket, key, info)
        self._queue_replication(sub, info, meta)
        h = {"ETag": f'"{info.etag}"',
             "Location": f"/{req.bucket}/{key}"}
        h.update(self._sse_response_headers(info))
        if info.version_id:
            h["x-amz-version-id"] = info.version_id
        redirect = form.fields.get("success_action_redirect", "")
        if redirect:
            sep = "&" if "?" in redirect else "?"
            h["Location"] = (f"{redirect}{sep}" + urllib.parse.urlencode(
                {"bucket": req.bucket, "key": key, "etag": info.etag}))
            return S3Response(303, b"", h)
        status = form.fields.get("success_action_status", "204")
        if status == "201":
            root = Element("PostResponse", S3_XMLNS)
            root.child("Location", h["Location"])
            root.child("Bucket", req.bucket)
            root.child("Key", key)
            root.child("ETag", h["ETag"])
            return S3Response(201, root.tobytes(), h)
        return S3Response(200 if status == "200" else 204, b"", h)

    def restore_object(self, req: S3Request) -> S3Response:
        """POST /bucket/key?restore (ref PostRestoreObjectHandler,
        cmd/bucket-lifecycle.go RestoreTransitionedObject)."""
        from ..bucket import tiering
        days = 1
        if req.body:
            try:
                doc = parse(req.body)
                days = int(doc.findtext("Days") or "1")
            except Exception:
                raise s3err.ERR_MALFORMED_XML
        try:
            tiering.restore_object(self.layer, self.tiers, req.bucket,
                                   req.key, days)
        except (ObjectNotFound, BucketNotFound):
            raise s3err.ERR_NO_SUCH_KEY
        except tiering.TierError as e:
            raise s3err.APIError("InvalidObjectState", str(e), 403)
        return S3Response(202)

    def _tier_meta_if_destroying(self, bucket: str, key: str,
                                 version_id: str,
                                 versioned: bool) -> dict | None:
        """Metadata of a transitioned object about to be DESTROYED
        (unversioned delete or versioned delete of the data version) —
        its remote copy must be GC'd (ref deleteTransitionedObject)."""
        from ..bucket import tiering as tier_mod
        if not self.tiers.list():
            return None
        if versioned and not version_id:
            return None  # marker write: data survives
        try:
            info = self.layer.get_object_info(bucket, key, version_id)
        except Exception:
            return None
        return (info.metadata
                if tier_mod.is_transitioned(info.metadata) else None)

    def delete_object(self, req: S3Request) -> S3Response:
        version_id = self._version_param(req)
        self._check_version_delete_allowed(
            req.bucket, req.key, version_id,
            self._can_bypass_governance(req))
        tier_meta = self._tier_meta_if_destroying(
            req.bucket, req.key, version_id,
            self._versioned(req.bucket))
        h = {}
        try:
            # Size of the version about to be destroyed, for the
            # incremental usage counter (markers destroy nothing).
            versioned = self._versioned(req.bucket)
            freed = 0
            # A versioned delete without a versionId writes a marker —
            # nothing is freed, skip the stat.
            if (self._usage_cache.get(req.bucket) is not None
                    and not (versioned and not version_id)):
                try:
                    freed = self.layer.get_object_info(
                        req.bucket, req.key, version_id).size
                except Exception:
                    freed = 0
            deleted = self.layer.delete_object(
                req.bucket, req.key, version_id,
                versioned=versioned)
            if not deleted.delete_marker and freed:
                self._usage_add(req.bucket, -freed)
            if deleted.delete_marker:
                h["x-amz-delete-marker"] = "true"
            if deleted.version_id:
                h["x-amz-version-id"] = deleted.version_id
            from ..event import event as ev
            self._notify(
                ev.OBJECT_REMOVED_DELETE_MARKER if deleted.delete_marker
                else ev.OBJECT_REMOVED_DELETE,
                req.bucket, req.key, deleted)
            # Only a NEW marker replicates; purging a marker version
            # ("undelete") must not delete the replica.
            if deleted.delete_marker and not version_id and \
                    self.replication.replicates_deletes(req.bucket,
                                                        req.key):
                self.replication.queue_task(req.bucket, req.key, "",
                                            "delete")
            if tier_meta is not None and not deleted.delete_marker:
                self.tiers.delete_remote(tier_meta)
        except (ObjectNotFound, BucketNotFound):
            if version_id:  # S3 DELETE is idempotent-success on missing keys
                h["x-amz-version-id"] = version_id
        except MethodNotAllowed:
            raise s3err.ERR_NOT_IMPLEMENTED  # FS backend versioned delete
        return S3Response(204, headers=h)


class S3Server:
    """HTTP front end with SigV4 auth (the reference's generic-handlers
    auth dispatch, ref cmd/auth-handler.go)."""

    def __init__(self, layer: ErasureObjects | None = None,
                 access_key: str = "minioadmin",
                 secret_key: str = "minioadmin", region: str = "us-east-1",
                 rpc_registry=None, iam=None):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.rpc_registry = rpc_registry
        self.iam = iam  # IAMSys; None = root-credentials-only mode
        self.handlers = None
        self.bucket_meta = None
        self.config = None  # ConfigSys once the layer attaches
        self.audit = None
        self._audit_from_env = False
        # QoS: per-class admission caps + request deadline budget (ref
        # maxClients middleware, cmd/generic-handlers.go). Created
        # before set_layer so _apply_config can configure it, and
        # registered as the dispatch scheduler's foreground-busy probe.
        from ..qos.admission import AdmissionController
        from ..qos.scheduler import GATE
        self.qos = AdmissionController()
        GATE.register(self.qos)
        from .webrpc import WebHandlers
        self.web = WebHandlers(self)
        if layer is not None:
            self.set_layer(layer)
        from .admin import AdminHandlers, Metrics
        self.metrics = Metrics()
        self.admin = AdminHandlers(self)
        from ..logger.audit import AuditWebhook
        from ..utils.bandwidth import BandwidthMonitor
        from ..utils.pubsub import PubSub
        self.bandwidth = BandwidthMonitor()
        # Every request publishes a trace.Info analog here; admin
        # /trace subscribes (ref globalHTTPTrace, cmd/globals.go:184).
        self.trace_hub = PubSub()
        if self.audit is None:
            self.audit = AuditWebhook.from_env()
            self._audit_from_env = self.audit is not None
        self.crawler = None  # attached by serve when scanning is on
        # rpc.peer.NotificationSys in distributed mode: admin trace /
        # profiling / info aggregate across the cluster through it.
        self.notification = None
        # PUT bodies at or above this size stream through the engine's
        # block pipeline instead of buffering (O(batch) server memory).
        self.stream_threshold = 8 * 1024 * 1024
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._front_door = None  # asyncserver.AsyncFrontDoor when async

    @property
    def layer(self):
        return self.handlers.layer if self.handlers else None

    def set_layer(self, layer) -> None:
        """Attach the object layer once boot completes (the reference
        serves 503 until newObjectLayer finishes,
        cmd/server-main.go:463)."""
        from ..bucket.metadata import BucketMetadataSys
        self.bucket_meta = BucketMetadataSys.for_layer(layer)
        self.handlers = S3ApiHandlers(layer, self.region, self.bucket_meta)
        self.handlers.server = self
        from ..config.kv import ConfigSys
        self.config = ConfigSys(self.bucket_meta.store)
        self.config.validators.append(self._validate_config)
        self.config.on_change(self._apply_config)
        self._apply_config(self.config)
        # Boot-time crash recovery: GC orphaned staging residue
        # (age-gated), requeue partially-committed objects, replay the
        # durable MRF journal — synchronously, so the report (and the
        # replayed mrf_queue_depth) exists before the first request is
        # served (storage/recovery.py).
        from ..storage.recovery import sweep_layer
        self.recovery_reports = sweep_layer(layer)

    def _validate_config(self, subsys: str, target: str,
                         kvs: dict) -> None:
        """Reject values that would break the running system BEFORE
        they persist (ref per-subsystem validation in lookupConfigs)."""
        if subsys == "storage_class":
            from ..config.storageclass import _parse_buckets, _parse_ec
            n = getattr(self.layer, "k", 0) + getattr(self.layer, "m", 0)
            for key, v in kvs.items():
                if key == "regen_buckets":
                    # A bucket list, not an EC:m value; any parse
                    # result is safe (unknown buckets simply never
                    # match a PUT).
                    _parse_buckets(v)
                    continue
                try:
                    m = _parse_ec(v)
                except Exception as e:
                    raise ValueError(f"storage_class {key}: {e}")
                if m is not None and n >= 2 and not (0 < m <= n // 2):
                    raise ValueError(
                        f"storage_class {key}={v}: parity out of range "
                        f"for {n}-disk sets")
        if subsys == "audit_webhook":
            ep = kvs.get("endpoint")
            if ep:
                from urllib.parse import urlparse
                if urlparse(ep).scheme not in ("http", "https"):
                    raise ValueError(f"audit endpoint {ep!r} must be "
                                     "http(s)")
        if subsys == "obs":
            from ..qos.deadline import parse_duration
            for key, v in kvs.items():
                if key.startswith("slow_ms"):
                    if v.strip() == "":
                        continue  # empty = inherit the default SLO
                    try:
                        if float(v) < 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"obs {key}={v!r}: must be a millisecond "
                            "number >= 0 (or empty to inherit)")
                elif key == "profile_on_slow":
                    if v not in ("on", "off"):
                        raise ValueError(
                            f"obs profile_on_slow={v!r}: must be "
                            "on/off")
                elif key == "loop_stall_ms":
                    try:
                        # NaN-proof: `not (x > 0)` rejects NaN where
                        # `x <= 0` would wave it through.
                        if not (float(v) > 0):
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"obs loop_stall_ms={v!r}: must be a "
                            "positive millisecond number")
                elif key == "profile_continuous":
                    if v not in ("on", "off"):
                        raise ValueError(
                            f"obs profile_continuous={v!r}: must be "
                            "on/off")
                elif key in ("timeline_sample", "timeline_retention"):
                    try:
                        if parse_duration(v) <= 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"obs {key}={v!r}: must be a positive "
                            "duration like 1s / 500ms / 15m")
        if subsys == "logger":
            if kvs.get("json") not in (None, "on", "off"):
                raise ValueError(
                    f"logger json={kvs.get('json')!r}: must be on/off")
        if subsys == "codec":
            for key, v in kvs.items():
                if key in ("autotune", "probe_on_boot"):
                    if v not in ("on", "off"):
                        raise ValueError(
                            f"codec {key}={v!r}: must be on/off")
                elif key == "hysteresis":
                    try:
                        # NaN-proof: `not (x >= 1.0)` rejects NaN
                        # where `x < 1.0` would wave it through.
                        if not (float(v) >= 1.0):
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"codec hysteresis={v!r}: must be a "
                            "number >= 1.0")
        if subsys == "alerts":
            from ..obs.watchdog import validate_user_rules
            from ..qos.deadline import parse_duration
            for key, v in kvs.items():
                if key == "enable":
                    if v not in ("on", "off"):
                        raise ValueError(
                            f"alerts enable={v!r}: must be on/off")
                elif key in ("fast_window", "slow_window"):
                    try:
                        if parse_duration(v) <= 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"alerts {key}={v!r}: must be a positive "
                            "duration like 30s / 1m / 15m")
                elif key == "burn_threshold":
                    try:
                        if not 0 < float(v) <= 1:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"alerts burn_threshold={v!r}: must be a "
                            "fraction in (0, 1]")
                elif key in ("pending_ticks", "resolve_ticks"):
                    try:
                        if int(v) < 1:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"alerts {key}={v!r}: must be an integer "
                            ">= 1")
                elif key == "rules" and v.strip():
                    validate_user_rules(v)  # AlertRuleError = ValueError
                elif key == "webhook_endpoint" and v.strip():
                    from urllib.parse import urlparse
                    if urlparse(v).scheme not in ("http", "https"):
                        raise ValueError(
                            f"alerts webhook_endpoint={v!r} must be "
                            "http(s)")
            # Cross-key: the two-window semantic (fast reacts, slow
            # confirms) degenerates if fast >= slow — configure()
            # would silently clamp, so reject the write instead. The
            # half not in this write reads its current effective
            # value.
            if "fast_window" in kvs or "slow_window" in kvs:
                try:
                    fast = parse_duration(
                        kvs.get("fast_window")
                        or self.config.get("alerts", "fast_window"))
                    slow = parse_duration(
                        kvs.get("slow_window")
                        or self.config.get("alerts", "slow_window"))
                except ValueError:
                    fast = slow = 0.0  # per-key checks already raised
                if fast and slow and fast > slow:
                    raise ValueError(
                        f"alerts fast_window ({fast:g}s) must be <= "
                        f"slow_window ({slow:g}s) — both windows must "
                        "breach for a burn alert, so a fast window "
                        "wider than the slow one would never confirm")
        if subsys == "usage":
            from ..qos.deadline import parse_duration
            for key, v in kvs.items():
                if key == "enable":
                    if v not in ("on", "off"):
                        raise ValueError(
                            f"usage enable={v!r}: must be on/off")
                elif key in ("top_k", "cardinality_cap",
                             "noisy_min_requests"):
                    caps = {"top_k": 1024, "cardinality_cap": 100_000,
                            "noisy_min_requests": 10_000_000}
                    try:
                        if not 1 <= int(v) <= caps[key]:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"usage {key}={v!r}: must be an integer "
                            f"in [1, {caps[key]}]")
                elif key in ("fast_window", "slow_window"):
                    try:
                        if parse_duration(v) <= 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"usage {key}={v!r}: must be a positive "
                            "duration like 30s / 1m / 15m")
                elif key == "noisy_share":
                    try:
                        if not 0 < float(v) <= 1:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"usage noisy_share={v!r}: must be a "
                            "fraction in (0, 1]")
            # Same two-window cross-check as the alerts subsystem:
            # fast reacts, slow confirms — a fast window wider than
            # the slow one would make noisy_neighbor never confirm.
            if "fast_window" in kvs or "slow_window" in kvs:
                try:
                    fast = parse_duration(
                        kvs.get("fast_window")
                        or self.config.get("usage", "fast_window"))
                    slow = parse_duration(
                        kvs.get("slow_window")
                        or self.config.get("usage", "slow_window"))
                except ValueError:
                    fast = slow = 0.0  # per-key checks already raised
                if fast and slow and fast > slow:
                    raise ValueError(
                        f"usage fast_window ({fast:g}s) must be <= "
                        f"slow_window ({slow:g}s)")
        if subsys == "cache":
            from ..qos.deadline import parse_duration
            for key, v in kvs.items():
                if key == "enable":
                    if v not in ("on", "off"):
                        raise ValueError(
                            f"cache enable={v!r}: must be on/off")
                elif key in ("mem_bytes", "disk_bytes", "min_hits",
                             "max_object_bytes"):
                    try:
                        if int(v) < 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"cache {key}={v!r}: must be an integer "
                            ">= 0")
                elif key == "revalidate":
                    if v == "off":
                        continue
                    try:
                        if parse_duration(v) < 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"cache revalidate={v!r}: must be a "
                            "duration like 1s / 500ms, 0 (always), "
                            "or off (never)")
        if subsys == "rpc":
            from ..qos.deadline import parse_duration
            for key, v in kvs.items():
                if key == "offline_retry":
                    try:
                        if parse_duration(v) <= 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"rpc offline_retry={v!r}: must be a "
                            "positive duration like 2s / 500ms")
        if subsys == "storage":
            for key, v in kvs.items():
                if key == "fsync" and v not in ("on", "off"):
                    raise ValueError(
                        f"storage fsync={v!r}: must be on/off")
        if subsys == "fault_inject":
            for key, v in kvs.items():
                if key == "enable":
                    if v not in ("on", "off"):
                        raise ValueError(
                            f"fault_inject enable={v!r}: must be "
                            "on/off")
                elif key == "plan" and v.strip():
                    import json as _json
                    from ..faultinject import FAULTS, FaultPlanError
                    try:
                        FAULTS.validate(_json.loads(v))
                    except (_json.JSONDecodeError,
                            FaultPlanError) as e:
                        raise ValueError(
                            f"fault_inject plan: {e}")
        if subsys == "api":
            from ..qos.deadline import parse_duration
            for key, v in kvs.items():
                if key.startswith("requests_max"):
                    try:
                        if int(v) < 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"api {key}={v!r}: must be an integer >= 0")
                elif key == "requests_deadline":
                    try:
                        if parse_duration(v) < 0:
                            raise ValueError
                    except ValueError:
                        raise ValueError(
                            f"api requests_deadline={v!r}: must be a "
                            "duration like 10s / 250ms")

    def _apply_config(self, cfg) -> None:
        """Push dynamic config into the running subsystems (the
        reference's dynamic-subsystem reload on SetKVS)."""
        from ..config.storageclass import (StorageClassConfig,
                                           _parse_buckets, _parse_ec)
        from ..logger.audit import AuditWebhook
        h = self.handlers
        if h is None:
            return
        # compression.enable flips the PUT-path wrap live; env keeps
        # its historical override.
        import os as _os
        h.compress_enabled = (
            _os.environ.get("MINIO_COMPRESS", "") == "on"
            or cfg.get("compression", "enable") == "on")
        try:
            h.storage_class = StorageClassConfig(
                standard_parity=_parse_ec(
                    cfg.get("storage_class", "standard")),
                rrs_parity=_parse_ec(cfg.get("storage_class", "rrs")),
                regen_buckets=_parse_buckets(
                    cfg.get("storage_class", "regen_buckets")))
        except Exception as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"storage_class config invalid, keeping previous: {e}",
                "config")
        # Admission caps + deadline reload live (per-class overrides on
        # top of the reference's single requests_max knob).
        from ..qos.deadline import parse_duration
        try:
            self.qos.configure(
                int(cfg.get("api", "requests_max") or "0"),
                {c: int(cfg.get("api", f"requests_max_{c}") or "0")
                 for c in ("read", "write", "list", "admin",
                           "select")},
                parse_duration(cfg.get("api", "requests_deadline")))
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"api qos config invalid, keeping previous: {e}", "config")
        # Peer health-gate window reloads live on the CLASS, so every
        # RPC client in the process follows (rpc/transport.py).
        from ..qos.deadline import parse_duration as _pd
        from ..rpc.transport import RPCClient
        try:
            _retry = _pd(cfg.get("rpc", "offline_retry"))
            # Env overrides bypass _validate: a zero here would
            # disable the peer health gate entirely (every RPC to a
            # dead peer pays the full socket timeout).
            if _retry <= 0:
                raise ValueError(f"offline_retry={_retry!r}: must be "
                                 "positive")
            RPCClient.OFFLINE_RETRY = _retry
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"rpc config invalid, keeping previous: {e}", "config")
        # Commit-path fsync policy flips live (storage/xl.py
        # commit_replace); env MINIO_STORAGE_FSYNC wins via the
        # config's env-first rule. Anything but an explicit "on" is
        # off — durability must be asked for, never inferred.
        from ..storage.xl import set_fsync
        set_fsync(cfg.get("storage", "fsync") == "on")
        # Fault-injection plan: applied only when the EFFECTIVE
        # fault_inject config changed — the apply hook runs on every
        # config write, and an unrelated change must not clobber a
        # plan loaded through the admin /fault-inject API.
        fcfg = (cfg.get("fault_inject", "enable"),
                cfg.get("fault_inject", "plan"))
        if fcfg != getattr(self, "_last_fault_cfg", ("off", "")):
            self._last_fault_cfg = fcfg
            from ..faultinject import FAULTS
            try:
                if fcfg[0] == "on" and fcfg[1].strip():
                    import json as _json
                    FAULTS.load_plan(_json.loads(fcfg[1]))
                else:
                    FAULTS.clear()
            except Exception as e:  # env override may carry garbage
                from ..logger import Logger
                Logger.get().log_once(
                    f"fault_inject config invalid, ignored: {e}",
                    "config")
        # Hot-object serving tier reloads live (cache/hotcache.py):
        # budgets shrink in place, disabling clears both tiers, a dir
        # change re-creates the disk tier.
        from ..cache.hotcache import HOTCACHE
        from ..qos.deadline import parse_duration as _pdur
        try:
            _reval_raw = cfg.get("cache", "revalidate").strip()
            _reval = (None if _reval_raw == "off"
                      else _pdur(_reval_raw))
            if _reval is not None and _reval < 0:
                raise ValueError("revalidate must be >= 0")
            HOTCACHE.configure(
                enable=cfg.get("cache", "enable") == "on",
                mem_bytes=int(cfg.get("cache", "mem_bytes")),
                disk_bytes=int(cfg.get("cache", "disk_bytes")),
                dirs=[d for d in
                      cfg.get("cache", "dirs").split(",") if d],
                min_hits=int(cfg.get("cache", "min_hits")),
                max_object_bytes=int(
                    cfg.get("cache", "max_object_bytes")),
                revalidate_s=_reval)
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"cache config invalid, keeping previous: {e}",
                "config")
        # Slowlog SLO thresholds reload live (the always-on tail
        # capture must be tunable under fire, like the QoS caps).
        from ..obs.slowlog import SLOWLOG

        def _ms(key: str) -> float | None:
            raw = cfg.get("obs", key).strip()
            return float(raw) if raw else None

        try:
            # Empty default = inherit the shipped SLO, matching the
            # validator's contract (an operator CLEARING the key must
            # not silently disable capture; "0" does that explicitly).
            default_ms = _ms("slow_ms")
            if default_ms is None:
                from ..config.kv import DEFAULT_KVS
                default_ms = float(DEFAULT_KVS["obs"]["slow_ms"])
            SLOWLOG.configure(
                default_ms,
                {c: _ms(f"slow_ms_{c}")
                 for c in ("read", "write", "list", "admin",
                           "select")},
                cfg.get("obs", "profile_on_slow") == "on")
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"obs slowlog config invalid, keeping previous: {e}",
                "config")
        # Timeline ring shape reloads live (obs/timeline.py keeps the
        # history it already has, up to the new capacity).
        from ..obs.timeline import TIMELINE
        try:
            _period = parse_duration(cfg.get("obs", "timeline_sample"))
            _keep = parse_duration(cfg.get("obs", "timeline_retention"))
            if _period <= 0 or _keep <= 0:
                raise ValueError("timeline durations must be positive")
            TIMELINE.configure(_period, _keep)
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"obs timeline config invalid, keeping previous: {e}",
                "config")
        # Event-loop health plane (obs/loopmon.py): the stall
        # threshold and the continuous profiler reload live — an
        # operator chasing a stall must be able to tighten the trip
        # wire (or switch the profiler on) without a restart.
        from ..obs.loopmon import LOOPMON
        try:
            _stall = float(cfg.get("obs", "loop_stall_ms"))
            if not (_stall > 0):  # env bypasses _validate; NaN-proof
                raise ValueError("loop_stall_ms must be positive")
            LOOPMON.configure(
                stall_ms=_stall,
                profile_continuous=cfg.get(
                    "obs", "profile_continuous") == "on")
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"obs loopmon config invalid, keeping previous: {e}",
                "config")
        # Watchdog alert engine: windows/threshold/hysteresis/user
        # rules/webhook all reload live (an operator tuning an alert
        # storm must not need a restart). Applied only when the
        # EFFECTIVE alerts config changed — the apply hook runs on
        # every config write, and rebuilding the rule set resets a
        # rate-mode user rule's delta window (a firing alert would
        # falsely resolve whenever an operator tunes an UNRELATED
        # key mid-incident; same convention as fault_inject below).
        from ..obs.watchdog import WATCHDOG, validate_user_rules
        acfg = tuple(cfg.get("alerts", k) for k in
                     ("enable", "fast_window", "slow_window",
                      "burn_threshold", "pending_ticks",
                      "resolve_ticks", "rules", "webhook_endpoint",
                      "webhook_auth_token"))
        if acfg != getattr(self, "_last_alerts_cfg", None):
            try:
                _rules_raw = acfg[6].strip()
                WATCHDOG.configure(
                    enable=acfg[0] == "on",
                    fast_s=parse_duration(acfg[1]),
                    slow_s=parse_duration(acfg[2]),
                    burn_threshold=float(acfg[3]),
                    pending_ticks=int(acfg[4]),
                    resolve_ticks=int(acfg[5]),
                    user_rules=(validate_user_rules(_rules_raw)
                                if _rules_raw else ()),
                    webhook_endpoint=acfg[7].strip(),
                    webhook_auth_token=acfg[8])
                self._last_alerts_cfg = acfg
            except ValueError as e:  # env override may carry garbage
                from ..logger import Logger
                Logger.get().log_once(
                    f"alerts config invalid, keeping previous: {e}",
                    "config")
        # Tenant/workload attribution reloads live (obs/usage.py):
        # enable toggles the _finish_request hook, top_k reshapes the
        # sketches, cardinality_cap retunes both the account fold and
        # the metrics2 usage_* label guard, the windows and noisy_*
        # knobs retune the noisy_neighbor rule.
        from ..obs.usage import USAGE
        try:
            USAGE.configure(
                enable=cfg.get("usage", "enable") == "on",
                top_k=int(cfg.get("usage", "top_k")),
                cardinality_cap=int(cfg.get("usage",
                                            "cardinality_cap")),
                fast_s=parse_duration(cfg.get("usage", "fast_window")),
                slow_s=parse_duration(cfg.get("usage", "slow_window")),
                noisy_share=float(cfg.get("usage", "noisy_share")),
                noisy_min_requests=int(
                    cfg.get("usage", "noisy_min_requests")))
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"usage config invalid, keeping previous: {e}",
                "config")
        # Codec autotuner knobs reload live (ops/autotune.py):
        # autotune=off pins the static policy, hysteresis retunes the
        # plan-flip margin.
        from ..ops.autotune import AUTOTUNE
        try:
            _hyst = float(cfg.get("codec", "hysteresis"))
            if not (_hyst >= 1.0):  # env bypasses _validate; NaN-proof
                raise ValueError("hysteresis must be >= 1.0")
            AUTOTUNE.configure(
                enabled=cfg.get("codec", "autotune") == "on",
                hysteresis=_hyst)
        except ValueError as e:  # env override may carry garbage
            from ..logger import Logger
            Logger.get().log_once(
                f"codec config invalid, keeping previous: {e}",
                "config")
        # Structured JSON log mode; the legacy MINIO_LOG_JSON env
        # spelling wins over config (env-first, like every subsystem).
        import os as _os_log
        if not _os_log.environ.get("MINIO_LOG_JSON", ""):
            from ..logger import Logger
            Logger.get().json_output = \
                cfg.get("logger", "json") == "on"
        ep = cfg.get("audit_webhook", "endpoint")
        tok = cfg.get("audit_webhook", "auth_token")
        if cfg.get("audit_webhook", "enable") == "on" and ep:
            if (self.audit is None or self.audit.endpoint != ep
                    or self.audit.auth_token != tok):
                if self.audit is not None:
                    self.audit.close()
                self.audit = AuditWebhook(ep, tok)
                self._audit_from_env = False
        elif self.audit is not None and not self._audit_from_env:
            # Config turned it off: stop posting. An env-configured
            # sink survives config (env always wins).
            self.audit.close()
            self.audit = None

    def _lookup_secret(self, access_key: str) -> str | None:
        if self.iam is not None:
            return self.iam.lookup_secret(access_key)
        return self.secret_key if access_key == self.access_key else None

    def authenticate(self, req: S3Request) -> str:
        if req.headers.get("authorization", "").startswith("AWS "):
            # Legacy V2 signature (ref cmd/signature-v2.go).
            return sigv4.verify_header_auth_v2(
                req.method, req.raw_path, req.query, req.headers,
                self._lookup_secret)
        if "authorization" in req.headers:
            if (req.body_stream is not None
                    and "x-amz-content-sha256" not in req.headers):
                # The canonical request then needs the actual body hash:
                # buffer (clients virtually always send the header).
                req.body = _drain_stream(req.body_stream)
                req.body_stream = None
            ak = sigv4.verify_header_auth(
                req.method, req.raw_path, req.query, req.headers,
                "" if req.body_stream is not None
                else hashlib.sha256(req.body).hexdigest(),
                self._lookup_secret)
            # aws-chunked streaming upload: the seed signature just
            # verified chains the per-chunk signatures; decode + verify
            # the payload — incrementally when the body streams (ref
            # newSignV4ChunkedReader, cmd/streaming-signature-v4.go:156).
            if req.headers.get("x-amz-content-sha256",
                               "") == sigv4.STREAMING_PAYLOAD:
                cred, _, seed = sigv4.parse_auth_fields(req.headers)
                want = req.headers.get("x-amz-decoded-content-length")
                if req.body_stream is not None:
                    # AWS requires the decoded length for aws-chunked;
                    # without it the size/quota caps would be blind.
                    if not want:
                        raise s3err.ERR_MISSING_CONTENT_LENGTH
                    req.body_stream = sigv4.ChunkedDecoder(
                        req.body_stream, self._lookup_secret(ak), cred,
                        req.headers.get("x-amz-date", ""), seed)
                    try:
                        req.content_length = int(want)
                    except ValueError:
                        raise s3err.ERR_INVALID_ARGUMENT
                else:
                    req.body = sigv4.decode_streaming(
                        req.body, self._lookup_secret(ak), cred,
                        req.headers.get("x-amz-date", ""), seed)
                    req.content_length = len(req.body)
                    try:
                        if want and int(want) != len(req.body):
                            raise s3err.ERR_SIGNATURE_DOES_NOT_MATCH
                    except ValueError:
                        raise s3err.ERR_INVALID_ARGUMENT
        elif "X-Amz-Signature" in req.params:
            ak = sigv4.verify_presigned(
                req.method, req.raw_path, req.query, req.headers,
                self._lookup_secret)
        else:
            raise s3err.ERR_MISSING_AUTH
        # Temporary (STS) credentials must present their session token
        # (ref cmd/auth-handler.go session-token validation).
        if self.iam is not None:
            u = self.iam.get_user(ak)
            if u is not None and u.session_token:
                sent = (req.headers.get("x-amz-security-token")
                        or req.params.get("X-Amz-Security-Token", ""))
                if sent != u.session_token:
                    raise s3err.ERR_ACCESS_DENIED
        return ak

    @staticmethod
    def _action_for(req: S3Request) -> tuple[str, str]:
        """Map a request to (s3 action, resource) for policy checks
        (ref cmd/auth-handler.go action dispatch)."""
        m, p = req.method, req.params
        if not req.bucket:
            return "s3:ListAllMyBuckets", "*"
        resource = (f"{req.bucket}/{req.key}" if req.key
                    else req.bucket)
        if not req.key:
            if "policy" in p:
                return ({"GET": "s3:GetBucketPolicy",
                         "PUT": "s3:PutBucketPolicy",
                         "DELETE": "s3:DeleteBucketPolicy"}.get(
                             m, "s3:GetBucketPolicy"), resource)
            if "versioning" in p:
                return ("s3:GetBucketVersioning" if m == "GET"
                        else "s3:PutBucketVersioning", resource)
            if "lifecycle" in p:
                return ("s3:GetLifecycleConfiguration" if m == "GET"
                        else "s3:PutLifecycleConfiguration", resource)
            if "notification" in p:
                return ("s3:GetBucketNotification" if m == "GET"
                        else "s3:PutBucketNotification", resource)
            if "encryption" in p:
                return ("s3:GetEncryptionConfiguration" if m == "GET"
                        else "s3:PutEncryptionConfiguration", resource)
            if "tagging" in p:
                return ("s3:GetBucketTagging" if m == "GET"
                        else "s3:PutBucketTagging", resource)
            if "object-lock" in p:
                return ("s3:GetBucketObjectLockConfiguration" if m == "GET"
                        else "s3:PutBucketObjectLockConfiguration",
                        resource)
            if "replication" in p:
                return ("s3:GetReplicationConfiguration" if m == "GET"
                        else "s3:PutReplicationConfiguration", resource)
            if "cors" in p:
                return ("s3:GetBucketCORS" if m == "GET"
                        else "s3:PutBucketCORS", resource)
            if "versions" in p:
                return "s3:ListBucketVersions", resource
            if m == "PUT":
                return "s3:CreateBucket", resource
            if m == "DELETE":
                return "s3:DeleteBucket", resource
            if m == "POST" and "delete" in p:
                return "s3:DeleteObject", f"{req.bucket}/*"
            if "location" in p:
                return "s3:GetBucketLocation", resource
            if "uploads" in p:
                return "s3:ListBucketMultipartUploads", resource
            return "s3:ListBucket", resource
        if "tagging" in p:
            if m == "GET":
                return ("s3:GetObjectVersionTagging" if "versionId" in p
                        else "s3:GetObjectTagging"), resource
            return ("s3:PutObjectVersionTagging" if "versionId" in p
                    else "s3:PutObjectTagging"), resource
        if "retention" in p:
            return ("s3:GetObjectRetention" if m == "GET"
                    else "s3:PutObjectRetention"), resource
        if "legal-hold" in p:
            return ("s3:GetObjectLegalHold" if m == "GET"
                    else "s3:PutObjectLegalHold"), resource
        if "uploadId" in p or "uploads" in p:
            if m == "DELETE":
                return "s3:AbortMultipartUpload", resource
            if m == "GET":
                return "s3:ListMultipartUploadParts", resource
            return "s3:PutObject", resource
        if m == "POST" and "restore" in p:
            return "s3:RestoreObject", resource
        if m == "POST" and "select" in p:
            # SELECT scans object content: same grant as GetObject
            # (ref SelectObjectContentHandler auth).
            return "s3:GetObject", resource
        if m in ("GET", "HEAD"):
            if "versionId" in p:
                return "s3:GetObjectVersion", resource
            return "s3:GetObject", resource
        if m == "PUT":
            return "s3:PutObject", resource
        if m == "DELETE":
            return "s3:DeleteObject", resource
        return "s3:*", resource

    def authorize(self, req: S3Request, access_key: str) -> None:
        if self.iam is None:
            return  # root-only mode: authentication implies full access
        action, resource = self._action_for(req)
        ctx = {"s3:prefix": req.params.get("prefix", "")}
        if not self.iam.is_allowed(access_key, action, resource, ctx):
            raise s3err.ERR_ACCESS_DENIED
        # Governance bypass is itself a grant (ref
        # enforceRetentionBypassForDelete permission check).
        from ..bucket.objectlock import H_BYPASS_GOVERNANCE
        if req.headers.get(H_BYPASS_GOVERNANCE, "").lower() == "true":
            if not self.iam.is_allowed(
                    access_key, "s3:BypassGovernanceRetention", resource,
                    ctx):
                raise s3err.ERR_ACCESS_DENIED
        # CopyObject additionally reads the source: require GetObject
        # on it (ref CopyObjectHandler source auth).
        if req.method == "PUT" and req.key and \
                "x-amz-copy-source" in req.headers:
            src = urllib.parse.unquote(
                req.headers["x-amz-copy-source"]).lstrip("/")
            if not self.iam.is_allowed(access_key, "s3:GetObject", src,
                                       ctx):
                raise s3err.ERR_ACCESS_DENIED

    def _post_policy(self, req: S3Request) -> S3Response:
        """Auth + policy checks for a browser form POST, then store
        (ref PostPolicyBucketHandler: the signature lives in the FORM,
        not the headers)."""
        from . import formupload as fu
        try:
            form = fu.parse_multipart(
                req.headers.get("content-type", ""), req.body)
        except fu.FormError:
            raise s3err.ERR_MALFORMED_XML
        if not form.has_file:
            raise s3err.ERR_INVALID_ARGUMENT
        policy_b64 = form.fields.get("policy", "")
        if not policy_b64:
            raise s3err.ERR_MISSING_AUTH
        access_key = fu.verify_post_signature(policy_b64, form.fields,
                                              self._lookup_secret)
        try:
            policy = fu.PostPolicy.from_json(
                base64.b64decode(policy_b64))
        except (fu.FormError, ValueError):
            raise s3err.ERR_MALFORMED_POLICY
        key = form.fields.get("key", "")
        if not key:
            raise s3err.ERR_INVALID_ARGUMENT
        key = key.replace("${filename}", form.file_name)
        fields = dict(form.fields)
        fields["bucket"] = req.bucket
        fields["key"] = key
        try:
            policy.check(fields, len(form.file_data))
        except fu.PolicyViolation:
            raise s3err.ERR_ACCESS_DENIED
        if self.iam is not None and not self.iam.is_allowed(
                access_key, "s3:PutObject", f"{req.bucket}/{key}", {}):
            raise s3err.ERR_ACCESS_DENIED
        return self.handlers.post_policy_upload(req, form, key)

    def _federation_redirect(self, req: S3Request) -> "S3Response | None":
        """307 to the owning cluster when the bucket lives elsewhere in
        the federation (ref bucket DNS resolution; the reference fronts
        this with CoreDNS — the redirect covers clients that address
        any federated node directly)."""
        h = self.handlers
        if h is None or h.bucket_dns is None or not req.bucket:
            return None
        try:
            records = h.bucket_dns.lookup(req.bucket)
        except Exception:
            return None
        me = h.public_addr
        others = [r for r in records if r != me]
        if not others:
            return None
        host, port = others[0]
        scheme = "https" if getattr(self, "cert_manager", None) else \
            "http"
        loc = f"{scheme}://{host}:{port}{req.raw_path}"
        if req.query:
            loc += f"?{req.query}"
        return S3Response(307, headers={"Location": loc})

    def route_qos(self, req: S3Request) -> S3Response:
        """Admission + deadline wrapper around route (ref the
        maxClients middleware fronting the router,
        cmd/generic-handlers.go): classify the request, open its time
        budget, wait FIFO for a slot within that budget, shed with 503
        SlowDown + Retry-After past it. The deadline stays current for
        the whole handler, so storage/peer RPC below sees the remaining
        budget."""
        from ..qos import admission as adm
        from ..qos import deadline as dl
        api_class = adm.classify(req.method, req.bucket, req.key,
                                 req.params)
        req.qos_class = api_class
        budget_s = self.qos.deadline_s if self.qos.engaged else 0.0
        req.qos_deadline_s = budget_s
        with dl.open_deadline(budget_s) as budget:
            _t_adm = time.perf_counter()
            try:
                admitted = self.qos.acquire(api_class, budget)
            except adm.AdmissionShed as shed:
                # Deliberate backpressure: the QoS layer WORKING must
                # not flood the slow-request log's blame histogram.
                req.slowlog_exempt = True
                raise s3err.ERR_SLOW_DOWN.with_retry_after(
                    shed.retry_after)
            req.qos_wait_ms = (time.perf_counter() - _t_adm) * 1e3
            try:
                resp = self.route(req)
            except BaseException:
                admitted.release()
                raise
            if isinstance(resp.body, (bytes, bytearray)):
                admitted.release()
            else:
                # Streaming body: the per-group shard reads run LAZILY
                # while the body writes to the socket — the request is
                # still consuming its class's capacity. Hold the slot
                # until _finish_request (which also covers vanished
                # clients); release() is idempotent.
                resp.qos_release = admitted.release
            return resp

    def route(self, req: S3Request) -> S3Response:
        h = self.handlers
        if h is None:
            raise s3err.ERR_SLOW_DOWN  # 503 until the layer is ready
        if (req.method == "POST" and req.bucket and not req.key
                and req.headers.get("content-type", "").startswith(
                    "multipart/form-data")):
            return self._post_policy(req)
        if (req.method == "POST" and not req.bucket
                and (b"AssumeRoleWithWebIdentity" in req.body
                     or b"AssumeRoleWithClientGrants" in req.body)):
            # JWT-based STS is unauthenticated: the TOKEN is the
            # credential (ref one shared JWT handler for WebIdentity
            # and ClientGrants, cmd/sts-handlers.go:86,270-305).
            return self.sts_web_identity(req)
        if (req.method == "POST" and not req.bucket
                and b"AssumeRoleWithLDAPIdentity" in req.body):
            # LDAP STS is unauthenticated: the directory password is
            # the credential (ref AssumeRoleWithLDAPIdentity,
            # cmd/sts-handlers.go:78-93).
            return self.sts_ldap_identity(req)
        _t_auth = time.perf_counter()
        from ..obs.span import TRACER
        with TRACER.span("auth.sigv4"):
            access_key = self.authenticate(req)
        if req.method == "PUT" and req.key:
            from ..utils.phasetimer import PUT as _PUT
            _PUT.record("auth_sigv4",
                        (time.perf_counter() - _t_auth) * 1e3)
        req.access_key = access_key  # audit/trace attribution
        m, bucket, key, p = req.method, req.bucket, req.key, req.params
        # STS API: POST / (ref cmd/sts-handlers.go).
        if not bucket and m == "POST":
            return self.sts_handler(req, access_key)
        
        self.authorize(req, access_key)
        # Only plain object PUTs and part uploads consume body streams;
        # sub-resource PUTs (?tagging, ?retention, ...) read req.body.
        if req.body_stream is not None and (
                not key or m != "PUT"
                or any(q in p for q in ("tagging", "retention",
                                        "legal-hold"))):
            req.body = _drain_stream(req.body_stream)
            req.body_stream = None
            req.content_length = len(req.body)
        if not bucket:
            if m == "GET":
                return h.list_buckets(req)
            raise s3err.ERR_METHOD_NOT_ALLOWED
        if not key:
            # Bucket sub-resources (?policy, ?versioning, ?lifecycle...)
            # dispatch on the query param (ref cmd/api-router.go queries()).
            if "policy" in p:
                if m == "GET":
                    return h.get_bucket_policy(req)
                if m == "PUT":
                    return h.put_bucket_policy(req)
                if m == "DELETE":
                    return h.delete_bucket_policy(req)
            if "versioning" in p:
                if m == "GET":
                    return h.get_versioning(req)
                if m == "PUT":
                    return h.put_versioning(req)
            for param, fn in (("lifecycle", h.bucket_lifecycle),
                              ("notification", h.bucket_notification),
                              ("encryption", h.bucket_encryption),
                              ("tagging", h.bucket_tagging),
                              ("object-lock", h.bucket_object_lock),
                              ("replication", h.bucket_replication),
                              ("cors", h.bucket_cors)):
                if param in p:
                    return fn(req)
            if m == "PUT":
                return h.make_bucket(req)
            if m == "HEAD":
                return h.head_bucket(req)
            if m == "DELETE":
                return h.delete_bucket(req)
            if m == "POST" and "delete" in p:
                return h.delete_multiple(req)
            if m == "GET":
                if "location" in p:
                    return h.get_location(req)
                if "uploads" in p:
                    return h.list_multipart_uploads(req)
                if "versions" in p:
                    return h.list_object_versions(req)
                return h.list_objects(req)
            raise s3err.ERR_METHOD_NOT_ALLOWED
        if "tagging" in p:
            return h.object_tagging(req)
        if "retention" in p:
            return h.object_retention(req)
        if "legal-hold" in p:
            return h.object_legal_hold(req)
        if m == "POST" and "restore" in p:
            return h.restore_object(req)
        if m == "POST" and "select" in p:
            return h.select_object_content(req)
        if m == "POST" and "uploads" in p:
            return h.initiate_multipart(req)
        if m == "POST" and "uploadId" in p:
            return h.complete_multipart(req)
        if m == "PUT" and "partNumber" in p and "uploadId" in p:
            if "x-amz-copy-source" in req.headers:
                return h.upload_part_copy(req)
            return h.put_part(req)
        if m == "DELETE" and "uploadId" in p:
            return h.abort_multipart(req)
        if m == "GET" and "uploadId" in p:
            return h.list_parts(req)
        if m == "PUT":
            return h.put_object(req)
        if m == "GET":
            return h.get_object(req)
        if m == "HEAD":
            return h.get_object(req, head=True)
        if m == "DELETE":
            return h.delete_object(req)
        raise s3err.ERR_METHOD_NOT_ALLOWED

    def handle_ops(self, method: str, raw_path: str, query: str,
                   headers: dict[str, str], body: bytes,
                   ) -> tuple:
        """Health / metrics / admin routes (non-S3 prefixes).
        Returns (status, content_type, body[, extra_headers]) — the
        4th element is optional and carries response headers (the
        admin shed path's Retry-After)."""
        import json as _json
        params = dict(urllib.parse.parse_qsl(query,
                                             keep_blank_values=True))
        if raw_path == "/minio-tpu/health/live":
            return 200, "text/plain", b"OK"
        if raw_path == "/minio-tpu/health/ready":
            ok = self.handlers is not None
            return (200 if ok else 503), "text/plain", \
                (b"OK" if ok else b"initializing")
        if raw_path == "/minio-tpu/health/cluster":
            ok = self._cluster_healthy()
            return (200 if ok else 503), "text/plain", \
                (b"OK" if ok else b"degraded")
        if raw_path == "/minio-tpu/metrics":
            text = self.metrics.prometheus(self.layer)
            return 200, "text/plain; version=0.0.4", text.encode()
        if raw_path == "/minio-tpu/v2/metrics/node":
            # Metrics v2, node scope: the typed registry (per-API
            # histograms, PUT phase split, kernel counters, disk-op
            # latency) — ref cmd/metrics-v2.go node collectors.
            from ..obs import metrics2 as m2
            text = m2.render(m2.METRICS2.snapshot())
            return 200, "text/plain; version=0.0.4", text.encode()
        if raw_path == "/minio-tpu/v2/metrics/cluster":
            return self._metrics_cluster()
        if raw_path == "/minio-tpu/v2/health/drives":
            # Node drive health: the drivemon's per-drive EWMAs +
            # suspect/faulty states (ref the drive sections of
            # `mc admin obd`; here continuously tracked, not probed).
            # UNAUTHENTICATED like the metrics pages, so endpoints are
            # redacted — full paths are on the admin /drive-health.
            # The MRF heal-queue census rides along: queue depth +
            # drops are the "how far behind is healing" signal that
            # belongs next to the drive states.
            from ..obs.drivemon import DRIVEMON, redact_drives
            doc = redact_drives(DRIVEMON.snapshot())
            doc["mrf"] = self._mrf_stats()
            return 200, "application/json", _json.dumps(doc).encode()
        if raw_path == "/minio-tpu/v2/health/cluster/drives":
            return self._health_cluster_drives()
        if raw_path == "/minio-tpu/v2/timeline":
            # Node timeline: the in-process ring of 1-second samples
            # (obs/timeline.py) — per-class rates, kernel GiB/s per
            # backend, drive census, worst-sample trace exemplars.
            # `?n=` tails, `?since=` returns samples after a stamp
            # (what mtpu_top uses for incremental refresh).
            from ..obs.timeline import TIMELINE
            try:
                n, since = self._parse_n_since(params)
            except ValueError:
                return 400, "text/plain", b"bad n/since"
            doc = TIMELINE.snapshot(n=n, since=since)
            return 200, "application/json", _json.dumps(doc).encode()
        if raw_path == "/minio-tpu/v2/timeline/cluster":
            try:
                n, since = self._parse_n_since(params)
            except ValueError:
                return 400, "text/plain", b"bad n/since"
            return self._timeline_cluster(n=n, since=since)
        if raw_path == "/minio-tpu/v2/alerts":
            # Node alert census (obs/watchdog.py): active + recently
            # resolved alerts with causes. Unauthenticated like the
            # metrics pages — drive identities in causes are redacted.
            from ..obs.watchdog import WATCHDOG
            return (200, "application/json",
                    _json.dumps(WATCHDOG.snapshot()).encode())
        if raw_path == "/minio-tpu/v2/alerts/cluster":
            return self._alerts_cluster()
        if raw_path == "/minio-tpu/v2/usage":
            # Node workload attribution (obs/usage.py): per-bucket/
            # per-tenant window accounts + per-class heavy-hitter
            # sketches. Unauthenticated like the metrics pages, so
            # access keys, client addresses and object-key tails are
            # redacted — admin /top serves them whole.
            from ..obs.usage import USAGE, redact_usage
            return (200, "application/json", _json.dumps(
                redact_usage(USAGE.snapshot())).encode())
        if raw_path == "/minio-tpu/v2/usage/cluster":
            return self._usage_cluster()
        if raw_path in ("/minio-tpu/console", "/minio-tpu/console/") \
                and method == "GET":
            from .console import console_response
            return console_response()
        if raw_path == "/minio-tpu/webrpc" and method == "POST":
            out = self.web.handle_rpc(headers, body)
            return 200, "application/json", out
        if raw_path.startswith("/minio-tpu/web/upload/") and \
                method == "PUT":
            return self.web.handle_upload(raw_path, headers, body)
        if raw_path.startswith("/minio-tpu/web/download/") and \
                method == "GET":
            return self.web.handle_download(raw_path, query)
        if raw_path.startswith("/minio-tpu/admin/"):
            try:
                req = S3Request(method, raw_path, query, headers, body)
                access_key = self.authenticate(req)
            except APIError:
                return 403, "application/json", _json.dumps(
                    {"error": "authentication failed"}).encode()
            # Admin rides its own admission class so a control-plane
            # storm cannot crowd out data-plane caps (and vice versa).
            from ..qos import admission as adm
            from ..qos import deadline as dl
            _budget_s = self.qos.deadline_s if self.qos.engaged else 0.0
            with dl.open_deadline(_budget_s) as budget:
                try:
                    admitted = self.qos.acquire("admin", budget)
                except adm.AdmissionShed as shed:
                    return (503, "application/json", _json.dumps(
                        {"error": "SlowDown",
                         "retryAfterSeconds": shed.retry_after}).encode(),
                        {"Retry-After": str(shed.retry_after)})
                with admitted:
                    status, out = self.admin.handle(
                        method, raw_path, params, body, access_key)
            return status, "application/json", out
        return 404, "text/plain", b"not found"

    def publish_trace(self, api: str, method: str, path: str,
                      status: int, duration_ms: float, rx: int, tx: int,
                      request_id: str = "", remote: str = "",
                      access_key: str = "", spans: dict | None = None,
                      qos_class: str = "", blamed_layer: str = "",
                      ) -> None:
        """Fan a per-request trace entry to subscribers + the audit
        sink (ref httpTraceAll wrapper, cmd/handler-utils.go:349, and
        the AuditLog call in the same wrapper). `spans` carries the
        request's completed span tree, so `mc admin trace` consumers
        get the per-layer breakdown alongside the flat entry;
        qos_class/blamed_layer ride into the audit entry so the
        webhook stream joins against the slow-request log."""
        if self.trace_hub.subscriber_count:
            entry = {
                "time": time.time(), "api": api, "method": method,
                "path": path, "statusCode": status,
                "durationMs": round(duration_ms, 3),
                "rx": rx, "tx": tx, "requestID": request_id,
                "remote": remote, "accessKey": access_key,
            }
            if spans is not None:
                entry["spans"] = spans
            self.trace_hub.publish(entry)
        if self.audit is not None:
            from ..logger.audit import audit_entry
            self.audit.send(audit_entry(
                api, method, path, status, duration_ms, rx, tx,
                access_key=access_key, request_id=request_id,
                remote=remote, qos_class=qos_class,
                blamed_layer=blamed_layer))

    # One cluster scrape may fan out to every peer; cache it so an
    # unauthenticated GET loop cannot amplify into N internal RPCs per
    # hit (Prometheus scrapes at interval >> this TTL anyway).
    CLUSTER_METRICS_TTL = 10.0
    _cluster_metrics_cache: tuple[float, bytes] | None = None

    def _cached_cluster_scrape(self, cache_attr: str, build) -> bytes:
        """Shared anti-amplification TTL cache for cluster fan-in
        endpoints (metrics2, drive health): build() runs the peer
        fan-out at most once per CLUSTER_METRICS_TTL."""
        cached = getattr(self, cache_attr)
        if cached is not None and \
                time.monotonic() - cached[0] < self.CLUSTER_METRICS_TTL:
            return cached[1]
        # The fill serves EVERY request for the next TTL window, so it
        # must not inherit the triggering request's remaining deadline:
        # now that peer fan-out threads carry QoS context (qos/ctx.py),
        # a nearly-burnt request would otherwise fast-fail the peer
        # RPCs and poison the cache with a degraded scrape for 10s.
        from ..qos.deadline import deadline_scope
        with deadline_scope(None):
            body = build()
        setattr(self, cache_attr, (time.monotonic(), body))
        return body

    def _metrics_cluster(self) -> tuple[int, str, bytes]:
        """Metrics v2, cluster scope: this node's snapshot merged with
        every peer's (scraped over the `metrics2` peer RPC) — the
        node/cluster split of cmd/metrics-v2.go. Unreachable peers
        degrade the node count, never the scrape."""
        from ..obs import metrics2 as m2

        def build() -> bytes:
            snaps = [m2.METRICS2.snapshot()]
            nodes = 1
            if self.notification is not None:
                for res in self.notification.metrics2_all().values():
                    snap = res.get("metrics2") if isinstance(res, dict) \
                        else None
                    if snap is not None:
                        snaps.append(snap)
                        nodes += 1
            merged = m2.merge(*snaps)
            merged["minio_tpu_v2_cluster_nodes"] = {
                "type": "gauge",
                "help": "Nodes contributing to a cluster metrics scrape.",
                "buckets": None,
                "series": [{"labels": {}, "value": nodes}]}
            return m2.render(merged).encode()

        body = self._cached_cluster_scrape("_cluster_metrics_cache",
                                           build)
        return 200, "text/plain; version=0.0.4", body

    _cluster_drives_cache: tuple[float, bytes] | None = None

    def _health_cluster_drives(self) -> tuple[int, str, bytes]:
        """Cluster drive health: this node's drivemon snapshot merged
        with every peer's (scraped over the `drivemon` peer RPC),
        exactly like the metrics2 fan-in — each drive annotated with
        the node it was observed from. Unreachable peers degrade the
        node count, never the scrape."""
        import json as _json
        from ..obs.drivemon import DRIVEMON, redact_drives

        def build() -> bytes:
            local = DRIVEMON.snapshot()
            drives = [dict(d, node="local") for d in local["drives"]]
            nodes = 1
            if self.notification is not None:
                for i, (key, res) in enumerate(
                        sorted(self.notification.drivemon_all()
                               .items())):
                    snap = res.get("drivemon") if isinstance(res, dict) \
                        else None
                    if snap is None:
                        continue
                    nodes += 1
                    for d in snap.get("drives", []):
                        if isinstance(d, dict):
                            # Anonymous surface: a stable ordinal, not
                            # the peer's internal host:port.
                            drives.append(dict(d, node=f"peer{i}"))
            return _json.dumps(redact_drives({
                "nodes": nodes,
                "drives": drives,
                "suspect": sum(1 for d in drives
                               if d.get("state") == "suspect"),
                "faulty": sum(1 for d in drives
                              if d.get("state") == "faulty"),
            })).encode()

        body = self._cached_cluster_scrape("_cluster_drives_cache",
                                           build)
        return 200, "application/json", body

    _cluster_alerts_cache: tuple[float, bytes] | None = None

    def _alerts_cluster(self) -> tuple[int, str, bytes]:
        """Cluster alert census: this node's watchdog snapshot merged
        with every peer's (scraped over the `alerts` peer RPC) —
        worst state per rule, count of nodes firing it, and an HONEST
        node count: unreachable peers are reported as such instead of
        silently reading as alert-free (same TTL-cached fan-in shape
        as metrics2/drives/timeline)."""
        import json as _json
        from ..obs.watchdog import WATCHDOG, merge_alerts

        def build() -> bytes:
            named = [("local", WATCHDOG.snapshot())]
            unreachable = 0
            if self.notification is not None:
                for i, (key, res) in enumerate(
                        sorted(self.notification.alerts_all()
                               .items())):
                    snap = res.get("alerts") if isinstance(res, dict) \
                        else None
                    if isinstance(snap, dict):
                        # Anonymous surface: a stable ordinal, not the
                        # peer's internal host:port.
                        named.append((f"peer{i}", snap))
                    else:
                        unreachable += 1
            doc = merge_alerts(named)
            doc["unreachable"] = unreachable
            return _json.dumps(doc).encode()

        body = self._cached_cluster_scrape("_cluster_alerts_cache",
                                           build)
        return 200, "application/json", body

    _cluster_usage_cache: tuple[float, bytes] | None = None

    def _usage_cluster(self) -> tuple[int, str, bytes]:
        """Cluster workload attribution: this node's usage snapshot
        merged with every peer's (scraped over the `usage` peer RPC)
        — accounts sum per name, heavy-hitter sketches merge with the
        count-min backing, and the node count is HONEST: unreachable
        peers are reported as such instead of silently reading as
        idle (same TTL-cached fan-in shape as metrics2/alerts)."""
        import json as _json
        from ..obs.usage import USAGE, merge_usage, redact_usage

        def build() -> bytes:
            named = [("local", USAGE.snapshot())]
            unreachable = 0
            if self.notification is not None:
                for i, (key, res) in enumerate(
                        sorted(self.notification.usage_all()
                               .items())):
                    snap = res.get("usage") if isinstance(res, dict) \
                        else None
                    if isinstance(snap, dict):
                        named.append((f"peer{i}", snap))
                    else:
                        unreachable += 1
            doc = merge_usage(named)
            doc["unreachable"] = unreachable
            return _json.dumps(redact_usage(doc)).encode()

        body = self._cached_cluster_scrape("_cluster_usage_cache",
                                           build)
        return 200, "application/json", body

    @staticmethod
    def _parse_n_since(params: dict) -> tuple[int | None, float | None]:
        """The timeline endpoints' shared ?n=/?since= parse (raises
        ValueError on garbage; both routes answer 400)."""
        n = int(params["n"]) if "n" in params else None
        since = float(params["since"]) if "since" in params else None
        return n, since

    _cluster_timeline_cache: tuple[float, bytes] | None = None

    def _timeline_cluster(self, n: int | None = None,
                          since: float | None = None,
                          ) -> tuple[int, str, bytes]:
        """Cluster timeline: this node's sample ring merged with every
        peer's (scraped over the `timeline` peer RPC) on aligned
        1-second buckets — exactly the metrics2/drivemon fan-in shape,
        TTL-cached against scrape amplification. A lagging peer's
        samples still land in their own time buckets (merge_timelines
        keeps per-bucket node counts honest).  The cache holds the
        FULL merge (one shape for every caller); ?n=/?since= slice it
        per request so a 1 Hz mtpu_top poll doesn't re-download the
        whole 15-minute history each refresh."""
        import json as _json
        from ..obs import timeline as tl

        def build() -> bytes:
            snaps = [tl.TIMELINE.snapshot()]
            if self.notification is not None:
                for res in self.notification.timeline_all().values():
                    snap = res.get("timeline") if isinstance(res, dict) \
                        else None
                    if isinstance(snap, dict):
                        snaps.append(snap)
            return _json.dumps(tl.merge_timelines(snaps)).encode()

        body = self._cached_cluster_scrape("_cluster_timeline_cache",
                                           build)
        if n is not None or since is not None:
            doc = _json.loads(body)
            doc["samples"] = tl.slice_samples(doc.get("samples", []),
                                              n=n, since=since)
            body = _json.dumps(doc).encode()
        return 200, "application/json", body

    def _incident_config(self) -> dict:
        """Effective config for incident bundles, credentials masked
        (obs/incidents.py applies the same policy; doubly-redacted is
        fine, un-redacted is not)."""
        if self.config is None:
            return {}
        from ..obs.incidents import _redact_config
        return _redact_config(self.config.dump())

    def _mrf_stats(self) -> dict:
        """MRF heal-queue census across this node's erasure sets
        (depth + drop count; see erasure/heal.py MRFQueue)."""
        from .admin import _pools
        depth = drops = 0
        if self.layer is not None:
            for pool in _pools(self.layer):
                for es in pool.sets:
                    mrf = getattr(es, "mrf", None)
                    if mrf is not None:
                        depth += mrf.depth()
                        drops += mrf.drops
        return {"depth": depth, "drops": drops}

    def _cluster_healthy(self) -> bool:
        """Quorum-aware cluster check (ref ClusterCheckHandler,
        cmd/healthcheck-handler.go:30): every set must have >= read
        quorum of its disks reachable."""
        layer = self.layer
        if layer is None:
            return False
        from .admin import _pools
        for pool in _pools(layer):
            for es in pool.sets:
                online = 0
                for d in es.disks:
                    try:
                        d.disk_info()
                        online += 1
                    except Exception:
                        pass
                if online < es.k:
                    return False
        return True

    def sts_handler(self, req: S3Request, access_key: str) -> S3Response:
        """AssumeRole: mint temp credentials for the authenticated
        identity (ref cmd/sts-handlers.go AssumeRole)."""
        form = dict(urllib.parse.parse_qsl(
            req.body.decode("utf-8", "replace")))
        if form.get("Action") != "AssumeRole":
            raise s3err.ERR_NOT_IMPLEMENTED
        if self.iam is None:
            raise s3err.ERR_NOT_IMPLEMENTED
        try:
            duration = int(form.get("DurationSeconds", "3600"))
        except ValueError:
            raise s3err.ERR_INVALID_ARGUMENT
        session_policy = None
        if form.get("Policy"):
            import json as _json
            try:
                session_policy = _json.loads(form["Policy"])
            except ValueError:
                raise s3err.ERR_MALFORMED_XML
        cred = self.iam.assume_role(access_key, duration, session_policy)
        ns = "https://sts.amazonaws.com/doc/2011-06-15/"
        root = Element("AssumeRoleResponse", ns)
        result = root.child("AssumeRoleResult")
        c = result.child("Credentials")
        c.child("AccessKeyId", cred.access_key)
        c.child("SecretAccessKey", cred.secret_key)
        c.child("SessionToken", cred.session_token)
        c.child("Expiration", _iso8601(cred.expiration))
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def _openid_validator(self):
        """Per-server cached OpenID validator, rebuilt when the
        identity env config changes (tests reconfigure between
        servers; the JWKS cache must survive across requests)."""
        import os as _os
        sig = tuple(_os.environ.get(k, "") for k in (
            "MINIO_IDENTITY_OPENID_JWKS_URL",
            "MINIO_IDENTITY_OPENID_SECRET",
            "MINIO_IDENTITY_OPENID_CLIENT_ID",
            "MINIO_IDENTITY_OPENID_CLAIM_NAME"))
        cached = getattr(self, "_oidc_cache", None)
        if cached is None or cached[0] != sig:
            from ..iam.oidc import OpenIDValidator
            self._oidc_cache = (sig, OpenIDValidator.from_env())
        return self._oidc_cache[1]

    def sts_web_identity(self, req: S3Request) -> S3Response:
        """AssumeRoleWithWebIdentity: validate the bearer JWT — RS256
        against the provider's JWKS (MINIO_IDENTITY_OPENID_JWKS_URL;
        ref cmd/config/identity/openid/jwks.go:30), or HS256 against
        MINIO_IDENTITY_OPENID_SECRET as an explicit dev mode — and mint
        temp creds carrying the token's policy claim (ref
        cmd/sts-handlers.go AssumeRoleWithWebIdentity)."""
        from ..iam.oidc import OIDCError
        form = dict(urllib.parse.parse_qsl(
            req.body.decode("utf-8", "replace")))
        action = form.get("Action")
        if action not in ("AssumeRoleWithWebIdentity",
                          "AssumeRoleWithClientGrants"):
            raise s3err.ERR_NOT_IMPLEMENTED
        validator = self._openid_validator()
        if validator is None or self.iam is None:
            raise s3err.ERR_NOT_IMPLEMENTED
        # ClientGrants sends the provider token as `Token`; WebIdentity
        # as `WebIdentityToken` (ref stsToken/stsWebIdentityToken,
        # cmd/sts-handlers.go:300-303). Validation is identical.
        token = (form.get("Token") or form.get("WebIdentityToken", ""))
        try:
            claims = validator.validate(token)
        except OIDCError:
            raise s3err.ERR_ACCESS_DENIED
        except Exception:
            # JWKS endpoint unreachable: auth cannot be decided.
            raise s3err.ERR_SLOW_DOWN
        subject = claims.get("sub", "")
        policy_name = claims.get(validator.claim_name, "")
        if not subject or not policy_name:
            raise s3err.ERR_ACCESS_DENIED
        try:
            duration = int(form.get("DurationSeconds", "3600"))
        except ValueError:
            raise s3err.ERR_INVALID_ARGUMENT
        try:
            cred = self.iam.assume_role_web_identity(
                subject, policy_name, duration)
        except KeyError:
            raise s3err.ERR_ACCESS_DENIED
        ns = "https://sts.amazonaws.com/doc/2011-06-15/"
        grants = action == "AssumeRoleWithClientGrants"
        root = Element(f"{action}Response", ns)
        result = root.child("ClientGrantsResult" if grants
                            else "AssumeRoleWithWebIdentityResult")
        c = result.child("Credentials")
        c.child("AccessKeyId", cred.access_key)
        c.child("SecretAccessKey", cred.secret_key)
        c.child("SessionToken", cred.session_token)
        c.child("Expiration", _iso8601(cred.expiration))
        result.child("SubjectFromToken" if grants
                     else "SubjectFromWebIdentityToken", subject)
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    def sts_ldap_identity(self, req: S3Request) -> S3Response:
        """AssumeRoleWithLDAPIdentity: authenticate the username and
        password against the configured directory (lookup-bind mode)
        and mint temp creds carrying the policies mapped to the user's
        DN / group DNs (ref cmd/sts-handlers.go:78-93,
        cmd/config/identity/ldap/)."""
        import os as _os

        from ..iam.ldap import LDAPError, LDAPIdentity
        form = dict(urllib.parse.parse_qsl(
            req.body.decode("utf-8", "replace")))
        if form.get("Action") != "AssumeRoleWithLDAPIdentity":
            raise s3err.ERR_NOT_IMPLEMENTED
        ldap = getattr(self, "ldap_identity", None) \
            or LDAPIdentity.from_env(_os.environ)
        if ldap is None or self.iam is None:
            raise s3err.ERR_NOT_IMPLEMENTED
        try:
            duration = int(form.get("DurationSeconds", "3600"))
        except ValueError:
            raise s3err.ERR_INVALID_ARGUMENT
        try:
            user_dn, groups = ldap.authenticate(
                form.get("LDAPUsername", ""),
                form.get("LDAPPassword", ""))
            cred = self.iam.assume_role_ldap_identity(
                user_dn, groups, duration)
        except LDAPError:
            raise s3err.ERR_ACCESS_DENIED
        except KeyError:
            raise s3err.ERR_ACCESS_DENIED
        except OSError:
            raise s3err.ERR_SLOW_DOWN  # directory unreachable
        ns = "https://sts.amazonaws.com/doc/2011-06-15/"
        root = Element("AssumeRoleWithLDAPIdentityResponse", ns)
        result = root.child("AssumeRoleWithLDAPIdentityResult")
        c = result.child("Credentials")
        c.child("AccessKeyId", cred.access_key)
        c.child("SecretAccessKey", cred.secret_key)
        c.child("SessionToken", cred.session_token)
        c.child("Expiration", _iso8601(cred.expiration))
        result.child("LDAPUserDN", user_dn)
        return S3Response(200, root.tobytes(),
                          {"Content-Type": "application/xml"})

    # ---------------- request core (transport-agnostic) ----------------

    def preflight(self, raw_path: str, headers: dict,
                  ) -> tuple[int, list]:
        """CORS preflight decision, shared by the threaded handler's
        do_OPTIONS and the async front door (unauthenticated by
        design; ref the preflight path of the CORS middleware).
        Returns (status, response headers)."""
        origin = headers.get("origin", "")
        want = headers.get("access-control-request-method", "")
        want_headers = [
            x.strip().lower() for x in headers.get(
                "access-control-request-headers", ""
            ).split(",") if x.strip()]
        bucket = raw_path.lstrip("/").split("/", 1)[0]
        rule = None
        if bucket and self.handlers is not None:
            rule = self.handlers.cors_match(bucket, origin, want)
        if rule is not None and want_headers:
            allowed = rule["headers"]
            if "*" not in allowed and any(
                    hh not in allowed for hh in want_headers):
                rule = None  # requested header not allowed
        if rule is None:
            return 403, [("Content-Length", "0")]
        out = [("Access-Control-Allow-Origin", origin),
               ("Access-Control-Allow-Methods",
                ", ".join(rule["methods"]))]
        if rule["headers"]:
            out.append(("Access-Control-Allow-Headers",
                        ", ".join(rule["headers"])))
        if rule["max_age"]:
            out.append(("Access-Control-Max-Age", rule["max_age"]))
        out.append(("Content-Length", "0"))
        return 200, out

    def _serve_one(self, txn) -> None:
        """One request's full lifecycle over an abstract transport
        (`txn`): routing, QoS boundary, trace root, accounting,
        response framing.  Both front ends — the threaded handler
        (`_ThreadedTxn`) and the async event loop (`asyncserver`'s
        `_AsyncTxn`) — drive requests through THIS method, so the
        semantics at the QoS/trace/metrics boundary cannot drift
        between them.  Runs on a handler thread (threaded) or a
        worker-pool thread (async)."""
        server = self
        t0 = time.monotonic()
        root_span = None
        finish_fn = None
        detached = False
        command, raw_path, query = txn.command, txn.raw_path, txn.query
        headers, body, length = txn.headers, txn.body, txn.rx_length
        try:
            if command == "OPTIONS":
                status, hdrs = self.preflight(raw_path, headers)
                txn.send_head(status, hdrs)
                return
            # Internal cluster RPC rides the same port
            # (ref registerDistErasureRouters, cmd/routers.go:26).
            if server.rpc_registry is not None and \
                    raw_path.startswith("/minio-tpu/rpc/"):
                status, rhdrs, rbody = server.rpc_registry.handle(
                    raw_path, headers, body)
                out = list(rhdrs.items())
                out.append(("Content-Length", str(len(rbody))))
                txn.send_head(status, out)
                txn.write(rbody)
                return
            # Health, metrics, admin (ref healthcheck-router.go,
            # metrics-router.go, admin-router.go).
            if raw_path.startswith("/minio-tpu/"):
                res = server.handle_ops(command, raw_path, query,
                                        headers, body)
                status, ctype, rbody = res[:3]
                out = [("Content-Type", ctype)]
                out.extend((res[3] if len(res) > 3 else {}).items())
                out.append(("Content-Length", str(len(rbody))))
                txn.send_head(status, out)
                txn.write(rbody)
                return
            req = S3Request(command, raw_path, query, headers, body)
            if txn.body_stream is not None:
                req.body_stream = txn.body_stream
                req.content_length = txn.content_length
            # Root span of this request's trace, keyed by the
            # x-amz-request-id the response already carries —
            # every layer below (engine, kernels, disks, peer
            # RPC) hangs child spans off it via the contextvar.
            from ..obs.span import TRACER
            root_span = TRACER.begin(
                "s3.request", req.request_id,
                method=command, path=raw_path)
            if root_span is not None:
                root_span.__enter__()
            try:
                resp = server.route_qos(req)
            except APIError as e:
                resp = None
                if getattr(e, "code", "") == "NoSuchBucket":
                    resp = server._federation_redirect(req)
                if resp is None:
                    hdrs = {"Content-Type": "application/xml"}
                    hdrs.update(e.headers())
                    resp = S3Response(
                        e.http_status,
                        e.xml(raw_path, req.request_id),
                        hdrs)
            except (QuorumError, TimeoutError) as e:
                # Quorum races/outages and lock-acquire
                # timeouts are RETRYABLE: 503 SlowDown,
                # matching the reference's
                # InsufficientWriteQuorum/OperationTimedOut ->
                # ErrSlowDown (cmd/api-errors.go:1898). Clients
                # with standard retry policies recover
                # transparently. A burnt request DEADLINE is
                # the same family but its own code: 503
                # RequestTimeout (ref ErrOperationTimedOut).
                from ..logger import Logger
                from ..qos.deadline import DeadlineExceeded
                Logger.get().log_once(
                    f"{command} {raw_path}: quorum: {e}",
                    "s3-handler")
                if isinstance(e, DeadlineExceeded):
                    # Burnt budget = deliberate backpressure,
                    # exempt from slowlog like admission sheds.
                    req.slowlog_exempt = True
                err = (s3err.ERR_REQUEST_TIMEOUT
                       if isinstance(e, DeadlineExceeded)
                       else s3err.ERR_SLOW_DOWN
                       ).with_retry_after(1)
                resp = S3Response(
                    err.http_status,
                    err.xml(raw_path, req.request_id),
                    {"Content-Type": "application/xml",
                     **err.headers()})
            except Exception as e:  # noqa: BLE001
                if isinstance(e, APIError):
                    raise
                from ..logger import Logger
                Logger.get().log_once(
                    f"{command} {raw_path}: "
                    f"{type(e).__name__}: {e}", "s3-handler")
                # A raw per-disk storage error that escaped the
                # engine's quorum reduction still answers its
                # TYPED S3 code (404/409/503/507) instead of an
                # opaque 500 — STORAGE_ERROR_MAP is kept total
                # by lint rule R5.
                err = (s3err.storage_api_error(e)
                       or s3err.ERR_INTERNAL_ERROR)
                resp = S3Response(
                    err.http_status,
                    err.xml(raw_path, req.request_id),
                    {"Content-Type": "application/xml",
                     **err.headers()})
            api = (f"{command}-"
                   f"{'object' if req.key else 'bucket' if req.bucket else 'service'}")
            body_is_stream = not isinstance(
                resp.body, (bytes, bytearray))
            trace_tree = None
            if root_span is not None:
                root_span.name = api
                root_span.tags["statusCode"] = resp.status
                if not body_is_stream or command == "HEAD":
                    # Buffered response: close BEFORE further
                    # socket work so the thread's span context
                    # never leaks into the next keep-alive
                    # request. STREAMING responses keep the
                    # root open — the engine's per-group shard
                    # reads run lazily while the body writes
                    # below, and must still attach; the
                    # _finish_request finally closes it.
                    trace_tree = root_span.finish()
            # Keep-alive hygiene: whatever the handler left unread
            # (auth failures, sheds, burnt deadlines, early errors)
            # must not desync the next pipelined request. Policy is
            # the transport's: threaded drains the remainder inline;
            # async discards small tails loop-side and CLOSES past its
            # cap (or when an Expect body was never solicited), per
            # Content-Length. close_hdr = the response must carry
            # `Connection: close` so the client knows.
            close_hdr = txn.prepare_body_cleanup()
            resp_len = (int(resp.headers.get("Content-Length", 0))
                        if body_is_stream else len(resp.body))

            # Atomic once-guard: on the async path the teardown safety
            # net and the drain task's cleanup can (in pathological
            # interleavings) both reach this from different pool
            # threads — a bare flag's check-then-set window would
            # account the request twice and double-release its slot.
            _fin_mu = threading.Lock()
            _finished = [False]

            def _finish_request():
                nonlocal trace_tree
                with _fin_mu:
                    if _finished[0]:
                        return
                    _finished[0] = True
                qos_release = getattr(resp, "qos_release", None)
                if qos_release is not None:
                    qos_release()  # streaming body done: free
                if root_span is not None and trace_tree is None:
                    trace_tree = root_span.finish()
                dur_ms = (time.monotonic() - t0) * 1000.0
                server.metrics.record(api, resp.status, length,
                                      resp_len)
                from ..obs.metrics2 import METRICS2
                METRICS2.inc("minio_tpu_v2_api_requests_total",
                             {"api": api,
                              "status": resp.status})
                if resp.status >= 500 \
                        and not req.slowlog_exempt:
                    # Per-CLASS 5xx counter: the watchdog's
                    # error-burn numerator (api_requests_total
                    # has per-API status detail but no class).
                    # Sheds/burnt deadlines are EXEMPT like in
                    # the slowlog: deliberate backpressure is
                    # the shed-burn rule's signal, and letting
                    # it bleed into error-burn would page twice
                    # for one brownout.
                    METRICS2.inc(
                        "minio_tpu_v2_api_class_errors_total",
                        {"class": req.qos_class or "read"})
                METRICS2.observe(
                    "minio_tpu_v2_api_request_duration_ms",
                    {"api": api}, dur_ms)
                if length:
                    METRICS2.inc(
                        "minio_tpu_v2_api_rx_bytes_total",
                        None, length)
                if resp_len:
                    METRICS2.inc(
                        "minio_tpu_v2_api_tx_bytes_total",
                        None, resp_len)
                server.bandwidth.record(req.bucket, length,
                                        resp_len)
                # Workload attribution (obs/usage.py): who was
                # this request — bucket/tenant accounts, per-class
                # key/client heavy-hitter sketches, usage_* series.
                # Sheds/burnt deadlines count as shed, not error,
                # mirroring the slowlog exemption split.
                from ..obs.usage import (USAGE,
                                         claimed_access_key)
                USAGE.record(
                    bucket=req.bucket,
                    access_key=(getattr(req, "access_key", "")
                                or claimed_access_key(
                                    headers.get("authorization",
                                                ""),
                                    req.params)),
                    qos_class=req.qos_class or "read",
                    rx=length, tx=resp_len,
                    status=resp.status,
                    shed=(resp.status >= 500
                          and req.slowlog_exempt),
                    key=req.key, client=txn.client_ip,
                    duration_ms=dur_ms,
                    trace_id=req.request_id)
                # Slow-request capture: over-SLO or 5xx lands
                # the full span tree + QoS data in the slowlog
                # ring, annotated with the blamed layer
                # (obs/slowlog.py). Sheds/burnt deadlines are
                # exempt (deliberate backpressure).
                # Worst-request exemplar for the current
                # timeline window: a spike in the 1s series
                # links straight to this request's trace tree
                # (and its slowlog entry when captured).
                from ..obs.timeline import TIMELINE
                TIMELINE.note_request(req.qos_class, dur_ms,
                                      req.request_id)
                from ..obs.slowlog import SLOWLOG
                slow_entry = SLOWLOG.record(
                    api=api, api_class=req.qos_class,
                    method=command, path=raw_path,
                    status=resp.status, duration_ms=dur_ms,
                    request_id=req.request_id,
                    trace=trace_tree,
                    qos={"class": req.qos_class,
                         "waitMs": round(req.qos_wait_ms, 3),
                         "deadlineS": req.qos_deadline_s},
                    exempt=req.slowlog_exempt)
                server.publish_trace(
                    api, command, raw_path, resp.status,
                    dur_ms, length,
                    resp_len, req.request_id,
                    txn.client_ip,
                    getattr(req, "access_key", ""),
                    spans=trace_tree,
                    qos_class=req.qos_class,
                    blamed_layer=(slow_entry["blamedLayer"]
                                  if slow_entry else ""))

            finish_fn = _finish_request
            if not body_is_stream:
                # Buffered: account/publish before the write,
                # as before (the body cannot fail mid-flight).
                _finish_request()
            hdrs_out = [("x-amz-request-id", req.request_id),
                        ("Server", "MinIO-TPU")]
            origin = headers.get("origin", "")
            if origin and req.bucket and \
                    server.handlers is not None:
                rule = server.handlers.cors_match(
                    req.bucket, origin, command)
                if rule is not None:
                    hdrs_out.append(
                        ("Access-Control-Allow-Origin", origin))
                    if rule["expose"]:
                        hdrs_out.append(
                            ("Access-Control-Expose-Headers",
                             ", ".join(rule["expose"])))
            for k, v in resp.headers.items():
                hdrs_out.append((k, v))
            if "Content-Length" not in resp.headers:
                hdrs_out.append(("Content-Length", str(resp_len)))
            if close_hdr:
                hdrs_out.append(("Connection", "close"))
            txn.send_head(resp.status, hdrs_out)
            if command == "HEAD":
                pass
            elif body_is_stream:
                # Streaming GET: blocks flow decoded-chunk by
                # decoded-chunk from the engine to the socket.
                # Mid-stream decode/auth failures (bitrot,
                # compression damage, GCM auth) arrive AFTER the
                # 200 headers went out — the transport aborts the
                # connection so the client sees a short body, never
                # a clean success. The threaded transport drives
                # the body inline; the async one DETACHES (returns
                # True) and its loop pulls chunks, owning finish_fn
                # from here.
                detached = txn.stream_response(resp, raw_path,
                                               _finish_request,
                                               root_span)
            elif resp.body:
                txn.write(resp.body)
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            # Safety nets (both idempotent): a streaming
            # response whose client vanished before/while the
            # body wrote still gets its metrics/trace
            # accounted, and an open span context never leaks
            # into the next keep-alive request on this thread.
            # A DETACHED response hands both duties to the async
            # drain task (backstopped by connection teardown).
            if not detached:
                if finish_fn is not None:
                    finish_fn()
                if root_span is not None:
                    root_span.finish()

    # ---------------- HTTP plumbing ----------------

    def start(self, host: str = "127.0.0.1", port: int = 0,
              cert_manager=None) -> int:
        """Boot the front door. Default is the asyncio event-loop
        listener (`s3/asyncserver.py`): accept/parse/keep-alive for
        10k+ sockets on a handful of loop threads, request execution
        on a bounded worker pool through the same `_serve_one` core.
        `MINIO_FRONT_DOOR=threaded` keeps the legacy thread-per-
        connection front end. cert_manager: utils.certs.CertManager
        for HTTPS with hot-reloaded certificates (None = plaintext)."""
        import os as _os
        self.cert_manager = cert_manager
        mode = _os.environ.get("MINIO_FRONT_DOOR",
                               "async").strip().lower()
        if mode == "threaded":
            bound = self._start_threaded(host, port, cert_manager)
        else:
            from .asyncserver import AsyncFrontDoor
            front = AsyncFrontDoor(self, cert_manager=cert_manager)
            try:
                bound = front.start(host, port)
            except BaseException:
                front.pool.shutdown(wait=False)
                front.rpc_pool.shutdown(wait=False)
                front.stream_pool.shutdown(wait=False)
                raise
            self._front_door = front
            # Address shim: callers (webrpc port probe, tests) read
            # `server._httpd.server_address` regardless of front end.
            self._httpd = _BoundAddress(host, bound)
        # Timeline sampler: one process-wide daemon deltaing the
        # registry per sample period (refcounted — the last server to
        # stop stops it; its tick also drives kernprof's rate-limited
        # backend recovery probes).
        from ..obs.timeline import TIMELINE
        TIMELINE.start()
        self._timeline_started = True
        # Codec autotuner boot probe ladder (ops/autotune.py): one
        # background run per process — tiny known-answer dispatches
        # seeding the measured per-lane crossover; serving starts on
        # the static policy and flips to the plan when the ladder
        # lands (codec probe_on_boot=off skips it; the plan then
        # builds from live dispatch samples only).
        try:
            probe_on_boot = (self.config is None
                             or self.config.get(
                                 "codec", "probe_on_boot") == "on")
        except Exception:
            probe_on_boot = True
        if probe_on_boot:
            from ..ops.autotune import AUTOTUNE
            AUTOTUNE.ensure_probed(background=True)
        # Incident bundles capture server-scoped context (effective
        # config, MRF census) through providers — the recorder itself
        # stays server-agnostic.
        from ..obs.incidents import INCIDENTS
        INCIDENTS.providers["config"] = self._incident_config
        INCIDENTS.providers["mrf"] = self._mrf_stats
        if cert_manager is not None:
            cert_manager.start()
        return bound

    def _start_threaded(self, host: str, port: int,
                        cert_manager) -> int:
        """The legacy thread-per-connection front end
        (MINIO_FRONT_DOOR=threaded): one OS thread per socket,
        BaseHTTPRequestHandler framing, same `_serve_one` core."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Socket timeout: a client that stops reading (streamed GET)
            # or writing (streamed PUT) errors out and releases any held
            # namespace lock instead of pinning it indefinitely (ref the
            # reference's conn read/write deadlines, cmd/http/listener.go).
            timeout = 120

            def log_message(self, *args):  # silence
                pass

            def _reject(self, status: int, msg: str):
                """Pre-dispatch framing error: terse close-delimited
                response (the request body's extent is unknowable, so
                keep-alive is off the table)."""
                self.send_response(status, msg)
                self.send_header("Content-Length", "0")
                self.send_header("Connection", "close")
                self.end_headers()
                self.close_connection = True

            def _handle(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw_path, _, query = self.path.partition("?")
                    headers = {k.lower(): v
                               for k, v in self.headers.items()}
                    te = headers.get("transfer-encoding", "").strip()
                    if te:
                        if te.lower() != "chunked":
                            return self._reject(501, "Not Implemented")
                        if "content-length" in headers:
                            # CL + TE together is the classic request
                            # smuggling vector: refuse outright.
                            return self._reject(400, "Bad Request")
                        if self.request_version == "HTTP/1.0":
                            return self._reject(400, "Bad Request")
                        return self._handle_chunked(
                            raw_path, query, headers)
                    # Large object PUTs stream: the socket body is never
                    # buffered whole (ref the reference's streaming PUT
                    # pipeline, cmd/erasure-encode.go:73).
                    stream_body = (
                        self.command == "PUT"
                        and length >= server.stream_threshold
                        and not raw_path.startswith("/minio-tpu/")
                        and "/" in raw_path.lstrip("/"))
                    if stream_body:
                        from ..utils.streams import LimitReader
                        body = b""
                        body_stream = LimitReader(self.rfile, length)
                    else:
                        body = self.rfile.read(length) if length else b""
                        body_stream = None
                    txn = _ThreadedTxn(self, raw_path, query, headers,
                                       body, body_stream, length)
                    server._serve_one(txn)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _handle_chunked(self, raw_path, query, headers):
                """Chunked Transfer-Encoding request body: object PUTs
                stream the decoder straight into the erasure pipeline
                (length -1 = unknown); everything else decodes to a
                buffer first — same split as the async front door
                (`asyncserver._HttpConn._begin_chunked`)."""
                from .asyncserver import CHUNKED_BUF_MAX
                from ..utils.streams import (ChunkedTEReader,
                                             ChunkedTooLarge)
                stream_body = (
                    self.command == "PUT"
                    and not raw_path.startswith("/minio-tpu/")
                    and "/" in raw_path.lstrip("/"))
                if stream_body:
                    body = b""
                    body_stream = ChunkedTEReader(
                        self.rfile, MAX_OBJECT_SIZE + 1)
                    length = -1
                else:
                    reader = ChunkedTEReader(self.rfile, CHUNKED_BUF_MAX)
                    acc = bytearray()
                    try:
                        while True:
                            piece = reader.read(64 * 1024)
                            if not piece:
                                break
                            acc += piece
                    except ChunkedTooLarge:
                        return self._reject(413, "Payload Too Large")
                    except ValueError:
                        return self._reject(400, "Bad Request")
                    body = bytes(acc)
                    body_stream = None
                    length = len(body)
                txn = _ThreadedTxn(self, raw_path, query, headers,
                                   body, body_stream, length)
                server._serve_one(txn)

            def do_OPTIONS(self):
                """CORS preflight: unauthenticated by design (ref the
                preflight path of the CORS middleware)."""
                raw_path, _, _q = self.path.partition("?")
                headers = {k.lower(): v for k, v in self.headers.items()}
                status, hdrs = server.preflight(raw_path, headers)
                self.send_response(status)
                for k, v in hdrs:
                    self.send_header(k, v)
                self.end_headers()

            do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _handle

        class _Server(ThreadingHTTPServer):
            # Keep-alive handler threads must never block shutdown
            # (the reference's xhttp.Server drains with a deadline,
            # cmd/http/server.go:117).
            daemon_threads = True
            block_on_close = False

            def finish_request(self, request, client_address):
                # TLS wraps PER CONNECTION in the handler thread — a
                # wrapped LISTENING socket would run the blocking
                # handshake inside the single accept loop, letting one
                # silent client stall every new connection (trivial
                # DoS). The handshake also gets the handler timeout.
                if cert_manager is not None:
                    import ssl as _ssl
                    request.settimeout(Handler.timeout)
                    try:
                        request = cert_manager.context.wrap_socket(
                            request, server_side=True)
                    except (_ssl.SSLError, OSError, TimeoutError):
                        try:
                            request.close()
                        except OSError:
                            pass
                        return
                super().finish_request(request, client_address)

        Handler.timeout = 120  # idle keep-alive reaper
        self._httpd = _Server((host, port), Handler)
        # mtpu-lint: disable=R1 -- the accept loop itself; request context is OPENED per request below it
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def notifier(self):
        return self.handlers.notifier if self.handlers else None

    @property
    def kms(self):
        return self.handlers.kms if self.handlers else None

    def stop(self) -> None:
        if getattr(self, "_timeline_started", False):
            self._timeline_started = False
            from ..obs.timeline import TIMELINE
            TIMELINE.stop()
            # Unregister OUR incident providers (another server may
            # have installed its own since): bound methods would
            # otherwise pin this server's whole object graph for the
            # process lifetime and report a dead server's config in
            # bundles captured after the stop.
            from ..obs.incidents import INCIDENTS
            for key, fn in (("config", self._incident_config),
                            ("mrf", self._mrf_stats)):
                if INCIDENTS.providers.get(key) == fn:
                    del INCIDENTS.providers[key]
        if getattr(self, "cert_manager", None) is not None:
            self.cert_manager.stop()
        if getattr(self, "_front_door", None) is not None:
            # Graceful drain: stop accepting, let in-flight requests
            # finish within the deadline, then abort stragglers —
            # the SIGTERM semantics the threaded front end only
            # approximated with abandoned daemon threads.
            import os as _os
            try:
                drain = float(_os.environ.get(
                    "MINIO_SHUTDOWN_DRAIN", "10") or 10)
            except ValueError:
                drain = 10.0
            self._front_door.stop(drain_s=drain)
            self._front_door = None
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # Stop the layer's background daemons (MRF heal worker, disk
        # monitors, quarantine prober) — a stopped server's daemons
        # must not keep churning its disks (tests run many servers per
        # process; leaked healers steal CPU from everything after).
        layer_shutdown = getattr(self.layer, "shutdown", None)
        if callable(layer_shutdown):
            layer_shutdown()
        if self.notifier is not None:
            self.notifier.close()
        if self.handlers is not None:
            self.handlers.replication.close()
        if self.audit is not None:
            self.audit.close()


class _BoundAddress:
    """Duck-typed stand-in for the ThreadingHTTPServer attribute
    surface the rest of the stack reads (`server_address`), when the
    async front door owns the socket."""

    def __init__(self, host: str, port: int):
        self.server_address = (host, port)

    def shutdown(self) -> None:
        pass

    def server_close(self) -> None:
        pass


class _ThreadedTxn:
    """Transport adapter for the legacy thread-per-connection front
    end: one request on a ThreadingHTTPServer handler thread, driven
    through the same `S3Server._serve_one` core as the async front
    door (`s3/asyncserver._AsyncTxn`)."""

    def __init__(self, handler, raw_path: str, query: str,
                 headers: dict, body: bytes, body_stream, length: int):
        self.h = handler
        self.command = handler.command
        self.raw_path = raw_path
        self.query = query
        self.headers = headers
        self.body = body
        self.body_stream = body_stream  # raw LimitReader (or None)
        self.content_length = length  # -1 = chunked (unknown)
        self.rx_length = max(length, 0)
        self.client_ip = handler.client_address[0]
        self.close_after = False
        self.detached = False

    # -- body hygiene ---------------------------------------------------

    def prepare_body_cleanup(self) -> bool:
        """Keep-alive framing after an early response (shed, burnt
        deadline, auth failure) left body bytes unread: drain the
        remainder inline — per Content-Length, so the next pipelined
        request can never desync. The handler THREAD pays for the
        whole drain here, however large (this transport has no way to
        linger a half-closed socket); the async front door instead
        discards small tails loop-side and closes large ones with a
        lingering FIN."""
        bs = self.body_stream
        if bs is None:
            return False
        if bs.remaining() <= 0:
            return False
        try:
            while bs.read(64 * 1024):
                pass
        except (OSError, ValueError):
            self.set_close()
            return True
        return False

    def set_close(self) -> None:
        self.h.close_connection = True
        self.close_after = True

    # -- response plumbing ----------------------------------------------

    def send_head(self, status: int, headers: list) -> None:
        self.h.send_response(status)
        for k, v in headers:
            self.h.send_header(k, v)
        self.h.end_headers()

    def write(self, data) -> None:
        if data:
            self.h.wfile.write(data)

    def stream_response(self, resp, raw_path: str, finish_fn,
                        root_span) -> bool:
        """Drive the iterator body inline on this handler thread (the
        threaded model: a slow reader parks the thread). Returns False
        — never detaches; finish_fn runs here and again (idempotent)
        in the core's finally."""
        h = self.h
        try:
            for chunk in resp.body:
                if chunk:
                    h.wfile.write(chunk)
        except (BrokenPipeError, ConnectionResetError):
            raise
        except Exception as e:  # noqa: BLE001
            from ..logger import Logger
            Logger.get().log_once(
                f"streaming GET {raw_path} aborted "
                f"mid-body: {type(e).__name__}: {e}",
                "s3-stream-abort")
            h.close_connection = True
        finally:
            close = getattr(resp.body, "close", None)
            if close is not None:
                close()
            # Streaming: the trace closes only now, so it carries the
            # lazy shard-read spans and the duration covers the body
            # transfer.
            finish_fn()
        return False
