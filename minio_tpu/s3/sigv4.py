"""AWS Signature Version 4 — verification and client-side signing
(ref cmd/signature-v4.go, cmd/signature-v4-parser.go).

Covers header auth (Authorization: AWS4-HMAC-SHA256 ...) and presigned
query auth (X-Amz-Signature=...). Streaming aws-chunked signatures (ref
cmd/streaming-signature-v4.go) layer on top when the handlers need them.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
from dataclasses import dataclass

from .errors import (ERR_AUTHORIZATION_HEADER_MALFORMED,
                     ERR_EXPIRED_PRESIGN, ERR_INVALID_ACCESS_KEY_ID,
                     ERR_MISSING_AUTH, ERR_REQUEST_TIME_TOO_SKEWED,
                     ERR_SIGNATURE_DOES_NOT_MATCH, APIError)

SIGN_V4_ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
MAX_SKEW_SECONDS = 15 * 60


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query: str) -> str:
    """Sorted, re-encoded query string; X-Amz-Signature excluded."""
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    enc = sorted((_uri_encode(k), _uri_encode(v)) for k, v in pairs
                 if k != "X-Amz-Signature")
    return "&".join(f"{k}={v}" for k, v in enc)


def _canonical_request(method: str, raw_path: str, query: str,
                       headers: dict[str, str], signed_headers: list[str],
                       payload_hash: str) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    return "\n".join([
        method.upper(), raw_path, canonical_query(query), canon_headers,
        ";".join(signed_headers), payload_hash,
    ])


def _signing_key(secret: str, date: str, region: str, service: str,
                 ) -> bytes:
    k = hmac.new(("AWS4" + secret).encode(), date.encode(),
                 hashlib.sha256).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


def _string_to_sign(amz_date: str, scope: str, canonical: str) -> str:
    return "\n".join([
        SIGN_V4_ALGORITHM, amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])


@dataclass
class Credential:
    access_key: str
    date: str
    region: str
    service: str

    @property
    def scope(self) -> str:
        return f"{self.date}/{self.region}/{self.service}/aws4_request"


def _parse_credential(cred: str) -> Credential:
    parts = cred.split("/")
    if len(parts) != 5 or parts[4] != "aws4_request":
        raise ERR_AUTHORIZATION_HEADER_MALFORMED
    return Credential(parts[0], parts[1], parts[2], parts[3])


def _check_skew(amz_date: str, now: float) -> None:
    try:
        t = time.mktime(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
        t -= time.timezone
    except ValueError:
        raise ERR_AUTHORIZATION_HEADER_MALFORMED
    if abs(now - t) > MAX_SKEW_SECONDS:
        raise ERR_REQUEST_TIME_TOO_SKEWED


def verify_header_auth(method: str, raw_path: str, query: str,
                       headers: dict[str, str], body_sha256: str,
                       lookup_secret, now: float | None = None) -> str:
    """Verify an Authorization-header SigV4 request; returns the access
    key. `headers` keys must be lowercase. `lookup_secret(access_key) ->
    secret | None`. Raises APIError subtypes on failure."""
    auth = headers.get("authorization", "")
    if not auth.startswith(SIGN_V4_ALGORITHM):
        raise ERR_MISSING_AUTH
    fields = {}
    for item in auth[len(SIGN_V4_ALGORITHM):].split(","):
        item = item.strip()
        if "=" not in item:
            raise ERR_AUTHORIZATION_HEADER_MALFORMED
        k, v = item.split("=", 1)
        fields[k.strip()] = v.strip()
    try:
        cred = _parse_credential(fields["Credential"])
        signed_headers = fields["SignedHeaders"].split(";")
        signature = fields["Signature"]
    except KeyError:
        raise ERR_AUTHORIZATION_HEADER_MALFORMED

    secret = lookup_secret(cred.access_key)
    if secret is None:
        raise ERR_INVALID_ACCESS_KEY_ID

    amz_date = headers.get("x-amz-date", "")
    if not amz_date:
        raise ERR_MISSING_AUTH
    _check_skew(amz_date, now if now is not None else time.time())

    payload_hash = headers.get("x-amz-content-sha256", body_sha256)
    canonical = _canonical_request(method, raw_path, query, headers,
                                   signed_headers, payload_hash)
    sts = _string_to_sign(amz_date, cred.scope, canonical)
    want = hmac.new(
        _signing_key(secret, cred.date, cred.region, cred.service),
        sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise ERR_SIGNATURE_DOES_NOT_MATCH
    return cred.access_key


def verify_presigned(method: str, raw_path: str, query: str,
                     headers: dict[str, str], lookup_secret,
                     now: float | None = None) -> str:
    """Verify a presigned-URL request; returns the access key."""
    q = dict(urllib.parse.parse_qsl(query, keep_blank_values=True))
    if q.get("X-Amz-Algorithm") != SIGN_V4_ALGORITHM:
        raise ERR_MISSING_AUTH
    try:
        cred = _parse_credential(q["X-Amz-Credential"])
        amz_date = q["X-Amz-Date"]
        expires = int(q["X-Amz-Expires"])
        signed_headers = q["X-Amz-SignedHeaders"].split(";")
        signature = q["X-Amz-Signature"]
    except (KeyError, ValueError):
        raise ERR_AUTHORIZATION_HEADER_MALFORMED

    secret = lookup_secret(cred.access_key)
    if secret is None:
        raise ERR_INVALID_ACCESS_KEY_ID

    now_t = now if now is not None else time.time()
    try:
        t0 = time.mktime(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
        t0 -= time.timezone
    except ValueError:
        raise ERR_AUTHORIZATION_HEADER_MALFORMED
    if now_t > t0 + expires:
        raise ERR_EXPIRED_PRESIGN

    canonical = _canonical_request(method, raw_path, query, headers,
                                   signed_headers, UNSIGNED_PAYLOAD)
    sts = _string_to_sign(amz_date, cred.scope, canonical)
    want = hmac.new(
        _signing_key(secret, cred.date, cred.region, cred.service),
        sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise ERR_SIGNATURE_DOES_NOT_MATCH
    return cred.access_key


# --- client side (tests, internal RPC, presign generation) -------------------


def sign_request(method: str, path: str, query: str, headers: dict[str, str],
                 body: bytes, access_key: str, secret_key: str,
                 region: str = "us-east-1",
                 amz_time: float | None = None) -> dict[str, str]:
    """Produce headers (lowercase keys) with SigV4 Authorization added.
    `headers` must already include 'host'."""
    t = time.gmtime(amz_time if amz_time is not None else time.time())
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    date = time.strftime("%Y%m%d", t)
    payload_hash = hashlib.sha256(body).hexdigest()
    out = {k.lower(): v for k, v in headers.items()}
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash
    signed = sorted(out)
    cred = Credential(access_key, date, region, "s3")
    canonical = _canonical_request(method, path, query, out, signed,
                                   payload_hash)
    sts = _string_to_sign(amz_date, cred.scope, canonical)
    sig = hmac.new(_signing_key(secret_key, date, region, "s3"),
                   sts.encode(), hashlib.sha256).hexdigest()
    out["authorization"] = (
        f"{SIGN_V4_ALGORITHM} Credential={cred.access_key}/{cred.scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return out


def presign_url(method: str, host: str, path: str, access_key: str,
                secret_key: str, expires: int = 3600,
                region: str = "us-east-1",
                amz_time: float | None = None,
                extra_query: dict[str, str] | None = None) -> str:
    """Generate a presigned URL (ref web-handlers PresignedGet)."""
    t = time.gmtime(amz_time if amz_time is not None else time.time())
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    date = time.strftime("%Y%m%d", t)
    cred = Credential(access_key, date, region, "s3")
    q = {
        "X-Amz-Algorithm": SIGN_V4_ALGORITHM,
        "X-Amz-Credential": f"{access_key}/{cred.scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    }
    q.update(extra_query or {})
    query = urllib.parse.urlencode(q)
    canonical = _canonical_request(method, path, query, {"host": host},
                                   ["host"], UNSIGNED_PAYLOAD)
    sts = _string_to_sign(amz_date, cred.scope, canonical)
    sig = hmac.new(_signing_key(secret_key, date, region, "s3"),
                   sts.encode(), hashlib.sha256).hexdigest()
    return (f"http://{host}{path}?{query}&X-Amz-Signature={sig}")


# --- streaming aws-chunked (ref cmd/streaming-signature-v4.go) ---------------

STREAMING_ALGORITHM = "AWS4-HMAC-SHA256-PAYLOAD"
_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def parse_auth_fields(headers: dict[str, str]) -> tuple[Credential,
                                                        list[str], str]:
    """(credential, signed_headers, signature) from an Authorization
    header (ref parseSignV4, cmd/signature-v4-parser.go)."""
    auth = headers.get("authorization", "")
    if not auth.startswith(SIGN_V4_ALGORITHM):
        raise ERR_MISSING_AUTH
    fields = {}
    for item in auth[len(SIGN_V4_ALGORITHM):].split(","):
        item = item.strip()
        if "=" not in item:
            raise ERR_AUTHORIZATION_HEADER_MALFORMED
        k, v = item.split("=", 1)
        fields[k.strip()] = v.strip()
    try:
        return (_parse_credential(fields["Credential"]),
                fields["SignedHeaders"].split(";"), fields["Signature"])
    except KeyError:
        raise ERR_AUTHORIZATION_HEADER_MALFORMED


def _chunk_string_to_sign(amz_date: str, scope: str, prev_sig: str,
                          chunk: bytes) -> str:
    return "\n".join([
        STREAMING_ALGORITHM, amz_date, scope, prev_sig, _EMPTY_SHA256,
        hashlib.sha256(chunk).hexdigest(),
    ])


def decode_streaming(body: bytes, secret: str, cred: Credential,
                     amz_date: str, seed_signature: str) -> bytes:
    """Decode + verify an aws-chunked body: each chunk's signature
    chains off the previous one, seeded by the header signature (ref
    newSignV4ChunkedReader, cmd/streaming-signature-v4.go:156)."""
    key = _signing_key(secret, cred.date, cred.region, cred.service)
    out = bytearray()
    prev = seed_signature
    pos = 0
    while True:
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise ERR_SIGNATURE_DOES_NOT_MATCH
        header = body[pos:nl].decode("ascii", "replace")
        size_s, _, ext = header.partition(";")
        try:
            size = int(size_s, 16)
        except ValueError:
            raise ERR_SIGNATURE_DOES_NOT_MATCH
        sig = ""
        for kv in ext.split(";"):
            k, _, v = kv.partition("=")
            if k.strip() == "chunk-signature":
                sig = v.strip()
        data = body[nl + 2:nl + 2 + size]
        if len(data) != size:
            raise ERR_SIGNATURE_DOES_NOT_MATCH
        want = hmac.new(
            key, _chunk_string_to_sign(amz_date, cred.scope, prev,
                                       data).encode(),
            hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            raise ERR_SIGNATURE_DOES_NOT_MATCH
        prev = want
        pos = nl + 2 + size
        if body[pos:pos + 2] == b"\r\n":
            pos += 2
        if size == 0:
            break
        out += data
    return bytes(out)


class ChunkedDecoder:
    """Streaming aws-chunked decoder: pulls frames from an inner reader
    one chunk at a time, verifying the chunk-signature chain — the
    incremental twin of decode_streaming for bodies too large to buffer
    (ref newSignV4ChunkedReader, cmd/streaming-signature-v4.go:156).
    read(n) returns decoded payload; raises on any bad signature."""

    def __init__(self, inner, secret: str, cred: Credential,
                 amz_date: str, seed_signature: str):
        self._inner = inner
        self._key = _signing_key(secret, cred.date, cred.region,
                                 cred.service)
        self._scope = cred.scope
        self._amz_date = amz_date
        self._prev = seed_signature
        self._buf = bytearray()  # decoded, not yet returned
        self._raw = bytearray()  # undecoded wire bytes
        self._done = False

    def _fill_raw(self, n: int) -> None:
        while len(self._raw) < n:
            chunk = self._inner.read(64 * 1024)
            if not chunk:
                raise ERR_SIGNATURE_DOES_NOT_MATCH
            self._raw += chunk

    # Chunk headers are tiny ("<hex>;chunk-signature=<64 hex>"); cap the
    # scan so a malformed body can't make us buffer it whole.
    _MAX_HEADER = 4096

    def _read_frame(self) -> None:
        # [hex-size];chunk-signature=<sig>\r\n<data>\r\n
        scanned = 0  # resume the CRLF search where the last one ended
        while True:
            nl = self._raw.find(b"\r\n", max(0, scanned - 1))
            if nl >= 0:
                break
            scanned = len(self._raw)
            if scanned > self._MAX_HEADER:
                raise ERR_SIGNATURE_DOES_NOT_MATCH
            chunk = self._inner.read(4096)
            if not chunk:
                raise ERR_SIGNATURE_DOES_NOT_MATCH
            self._raw += chunk
        if nl > self._MAX_HEADER:
            raise ERR_SIGNATURE_DOES_NOT_MATCH
        header = bytes(self._raw[:nl]).decode("ascii", "replace")
        del self._raw[:nl + 2]
        size_s, _, ext = header.partition(";")
        try:
            size = int(size_s, 16)
        except ValueError:
            raise ERR_SIGNATURE_DOES_NOT_MATCH
        sig = ""
        for kv in ext.split(";"):
            k, _, v = kv.partition("=")
            if k.strip() == "chunk-signature":
                sig = v.strip()
        if size > 0:
            self._fill_raw(size + 2)
            data = bytes(self._raw[:size])
            if bytes(self._raw[size:size + 2]) != b"\r\n":
                raise ERR_SIGNATURE_DOES_NOT_MATCH
            del self._raw[:size + 2]
        else:
            data = b""  # final frame; trailing CRLF optional at EOF
        want = hmac.new(
            self._key,
            _chunk_string_to_sign(self._amz_date, self._scope,
                                  self._prev, data).encode(),
            hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            raise ERR_SIGNATURE_DOES_NOT_MATCH
        self._prev = want
        if size == 0:
            self._done = True
        else:
            self._buf += data

    def read(self, n: int) -> bytes:
        while len(self._buf) < n and not self._done:
            self._read_frame()
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


def sign_streaming_request(method: str, path: str, query: str,
                           headers: dict[str, str], body: bytes,
                           access_key: str, secret_key: str,
                           region: str = "us-east-1",
                           chunk_size: int = 64 * 1024,
                           amz_time: float | None = None,
                           ) -> tuple[dict[str, str], bytes]:
    """Client side: produce (headers, aws-chunked body) for a streaming
    upload (what aws-sdk/mc send for large PUTs)."""
    t = time.gmtime(amz_time if amz_time is not None else time.time())
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    date = time.strftime("%Y%m%d", t)
    out = {k.lower(): v for k, v in headers.items()}
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = STREAMING_PAYLOAD
    out["content-encoding"] = "aws-chunked"
    out["x-amz-decoded-content-length"] = str(len(body))
    signed = sorted(out)
    cred = Credential(access_key, date, region, "s3")
    canonical = _canonical_request(method, path, query, out, signed,
                                   STREAMING_PAYLOAD)
    sts = _string_to_sign(amz_date, cred.scope, canonical)
    key = _signing_key(secret_key, date, region, "s3")
    seed = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    out["authorization"] = (
        f"{SIGN_V4_ALGORITHM} Credential={cred.access_key}/{cred.scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={seed}")

    chunks = []
    prev = seed
    for off in range(0, len(body), chunk_size):
        part = body[off:off + chunk_size]
        sig = hmac.new(key, _chunk_string_to_sign(
            amz_date, cred.scope, prev, part).encode(),
            hashlib.sha256).hexdigest()
        chunks.append(f"{len(part):x};chunk-signature={sig}\r\n".encode()
                      + part + b"\r\n")
        prev = sig
    final = hmac.new(key, _chunk_string_to_sign(
        amz_date, cred.scope, prev, b"").encode(),
        hashlib.sha256).hexdigest()
    chunks.append(f"0;chunk-signature={final}\r\n\r\n".encode())
    wire = b"".join(chunks)
    out["content-length"] = str(len(wire))
    return out, wire


# --- legacy AWS Signature V2 (ref cmd/signature-v2.go) -----------------------

# Sub-resources included in the V2 canonicalized resource, in sorted
# order (ref resourceList, cmd/signature-v2.go).
_V2_SUBRESOURCES = sorted([
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type",
    "response-expires", "select", "select-type", "tagging", "torrent",
    "uploadId", "uploads", "versionId", "versioning", "versions",
    "website", "encryption", "object-lock", "replication", "retention",
    "legal-hold", "cors",
])


def _v2_canonical_resource(raw_path: str, query: str) -> str:
    params = urllib.parse.parse_qsl(query, keep_blank_values=True)
    keep = sorted((k, v) for k, v in params if k in _V2_SUBRESOURCES)
    if not keep:
        return raw_path
    parts = [f"{k}={v}" if v else k for k, v in keep]
    return f"{raw_path}?{'&'.join(parts)}"


def _v2_string_to_sign(method: str, raw_path: str, query: str,
                       headers: dict[str, str]) -> str:
    canon_amz = "".join(
        f"{k}:{headers[k].strip()}\n"
        for k in sorted(h for h in headers if h.startswith("x-amz-")))
    # Spec: when x-amz-date is present it rides in the amz headers
    # and the Date slot is EMPTY (ref doesSignV2Match).
    date_slot = "" if "x-amz-date" in headers else headers.get("date",
                                                               "")
    return "\n".join([
        method.upper(),
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        date_slot,
    ]) + "\n" + canon_amz + _v2_canonical_resource(raw_path, query)


def verify_header_auth_v2(method: str, raw_path: str, query: str,
                          headers: dict[str, str],
                          lookup_secret) -> str:
    """Verify `Authorization: AWS AKID:signature` (HMAC-SHA1); returns
    the access key (ref doesSignV2Match)."""
    import hashlib as _hashlib
    auth = headers.get("authorization", "")
    if not auth.startswith("AWS "):
        raise ERR_MISSING_AUTH
    try:
        access_key, signature = auth[4:].split(":", 1)
    except ValueError:
        raise ERR_AUTHORIZATION_HEADER_MALFORMED
    secret = lookup_secret(access_key)
    if secret is None:
        raise ERR_INVALID_ACCESS_KEY_ID
    sts = _v2_string_to_sign(method, raw_path, query, headers)
    import base64 as _b64
    want = _b64.b64encode(hmac.new(secret.encode(), sts.encode(),
                                   _hashlib.sha1).digest()).decode()
    if not hmac.compare_digest(want, signature):
        raise ERR_SIGNATURE_DOES_NOT_MATCH
    return access_key


def sign_request_v2(method: str, path: str, query: str,
                    headers: dict[str, str], access_key: str,
                    secret_key: str) -> dict[str, str]:
    """Client-side V2 signing (tests / legacy SDK compatibility)."""
    import base64 as _b64
    import hashlib as _hashlib
    out = {k.lower(): v for k, v in headers.items()}
    out.setdefault("date", time.strftime(
        "%a, %d %b %Y %H:%M:%S GMT", time.gmtime()))
    sts = _v2_string_to_sign(method, path, query, out)
    sig = _b64.b64encode(hmac.new(secret_key.encode(), sts.encode(),
                                  _hashlib.sha1).digest()).decode()
    out["authorization"] = f"AWS {access_key}:{sig}"
    return out
