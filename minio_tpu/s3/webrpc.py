"""Web console backend: JSON-RPC 2.0 + JWT + raw up/download routes
(ref cmd/web-router.go:63 registerWebRouter, cmd/web-handlers.go 2404
LoC, pkg/rpc; JWT auth cmd/jwt.go).

Routes (wired by the S3 server's ops handler):
    POST /minio-tpu/webrpc                    JSON-RPC 2.0 envelope
    PUT  /minio-tpu/web/upload/<b>/<key>      Bearer-token upload
    GET  /minio-tpu/web/download/<b>/<key>?token=   token download
Methods mirror the reference's web.* set: Login, ListBuckets,
MakeBucket, DeleteBucket, ListObjects, RemoveObject, PresignedGet,
CreateURLToken, ServerInfo.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse

TOKEN_TTL = 24 * 3600
URL_TOKEN_TTL = 60


class WebError(Exception):
    def __init__(self, message: str, code: int = -32000):
        super().__init__(message)
        self.code = code


# ---------------------------------------------------------------------------
# Minimal HS256 JWT (ref cmd/jwt.go — web tokens are HMAC JWTs over the
# account's secret key)
# ---------------------------------------------------------------------------


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def jwt_sign(claims: dict, secret: str) -> str:
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64(json.dumps(claims, sort_keys=True).encode())
    sig = hmac.new(secret.encode(), f"{header}.{payload}".encode(),
                   hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64(sig)}"


def jwt_verify(token: str, secret: str) -> dict:
    try:
        header, payload, sig = token.split(".")
        want = hmac.new(secret.encode(),
                        f"{header}.{payload}".encode(),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(want, _unb64(sig)):
            raise WebError("invalid token signature")
        claims = json.loads(_unb64(payload))
    except WebError:
        raise
    except Exception:  # binascii/json/unicode garbage == bad token
        raise WebError("malformed token")
    if not isinstance(claims, dict) or \
            claims.get("exp", 0) < time.time():
        raise WebError("token expired")
    return claims


class WebHandlers:
    """JSON-RPC dispatcher over the object layer (the reference's
    webAPIHandlers)."""

    def __init__(self, server):
        self.server = server  # S3Server

    # -- auth -----------------------------------------------------------

    def _authenticate_token(self, headers: dict) -> str:
        auth = headers.get("authorization", "")
        if not auth.startswith("Bearer "):
            raise WebError("authentication required", -32001)
        claims = jwt_verify(auth[len("Bearer "):],
                            self.server.secret_key)
        if claims.get("aud") == "url":
            # Download tokens leak via query strings/logs; they must
            # never grant the full session surface.
            raise WebError("authentication required", -32001)
        return claims.get("sub", "")

    # -- JSON-RPC envelope ----------------------------------------------

    def handle_rpc(self, headers: dict, body: bytes) -> bytes:
        try:
            req = json.loads(body)
        except ValueError:
            return self._err(None, "parse error", -32700)
        if not isinstance(req, dict):
            return self._err(None, "invalid request", -32600)
        method = req.get("method", "")
        params = req.get("params") or {}
        rpc_id = req.get("id")
        if not isinstance(params, dict):
            return self._err(rpc_id, "params must be an object",
                             -32602)
        if not method.startswith("web."):
            return self._err(rpc_id, f"unknown method {method}",
                             -32601)
        name = method[len("web."):]
        fn = getattr(self, f"rpc_{name}", None)
        if fn is None:
            return self._err(rpc_id, f"unknown method {method}",
                             -32601)
        try:
            if name != "Login":  # every other method needs the JWT
                params["_user"] = self._authenticate_token(headers)
            result = fn(params)
            return json.dumps({"jsonrpc": "2.0", "id": rpc_id,
                               "result": result}).encode()
        except WebError as e:
            return self._err(rpc_id, str(e), e.code)
        except Exception as e:  # noqa: BLE001
            return self._err(rpc_id, f"{type(e).__name__}: {e}")

    @staticmethod
    def _err(rpc_id, message: str, code: int = -32000) -> bytes:
        return json.dumps({"jsonrpc": "2.0", "id": rpc_id,
                           "error": {"code": code,
                                     "message": message}}).encode()

    # -- methods (ref web-handlers.go) -----------------------------------

    def rpc_Login(self, p: dict) -> dict:
        user = p.get("username", "")
        password = p.get("password", "")
        secret = self.server._lookup_secret(user)
        if secret is None or not hmac.compare_digest(secret, password):
            raise WebError("invalid credentials", -32001)
        token = jwt_sign({"sub": user, "exp": time.time() + TOKEN_TTL},
                         self.server.secret_key)
        return {"token": token, "uiVersion": "minio-tpu"}

    def _layer(self):
        layer = self.server.layer
        if layer is None:
            raise WebError("server initializing", -32002)
        return layer

    def _check(self, user: str, action: str, resource: str) -> None:
        iam = self.server.iam
        if iam is not None and not iam.is_allowed(user, action,
                                                  resource, {}):
            raise WebError("access denied", -32001)

    @staticmethod
    def _synthetic_request(method: str, bucket: str, key: str,
                           headers: dict | None = None,
                           body: bytes = b""):
        """An S3Request as the S3 handler pipeline would have parsed it
        — web routes funnel through the same handlers so every write/
        read/delete policy applies uniformly."""
        from .server import S3Request
        enc = urllib.parse.quote(key, safe="/-_.~")
        return S3Request(method, f"/{bucket}/{enc}", "",
                         headers or {}, body)

    def rpc_ListBuckets(self, p: dict) -> dict:
        self._check(p["_user"], "s3:ListAllMyBuckets", "*")
        return {"buckets": [
            {"name": b["name"],
             "creationDate": time.strftime(
                 "%Y-%m-%dT%H:%M:%SZ", time.gmtime(b["created"]))}
            for b in self._layer().list_buckets()]}

    def rpc_MakeBucket(self, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        self._check(p["_user"], "s3:CreateBucket", bucket)
        from ..erasure.engine import BucketExists
        try:
            self._layer().make_bucket(bucket)
        except BucketExists:
            raise WebError(f"bucket {bucket!r} already exists")
        return {"ok": True}

    def rpc_DeleteBucket(self, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        self._check(p["_user"], "s3:DeleteBucket", bucket)
        from ..erasure.engine import BucketExists, BucketNotFound
        try:
            self._layer().delete_bucket(bucket)
        except BucketNotFound:
            raise WebError(f"no such bucket {bucket!r}")
        except BucketExists:
            raise WebError(f"bucket {bucket!r} not empty")
        return {"ok": True}

    def rpc_ListObjects(self, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        prefix = p.get("prefix", "")
        self._check(p["_user"], "s3:ListBucket", bucket)
        from ..erasure.engine import BucketNotFound
        try:
            infos = self._layer().list_objects(bucket, prefix=prefix,
                                               max_keys=1000)
        except BucketNotFound:
            raise WebError(f"no such bucket {bucket!r}")
        return {"objects": [
            {"name": o.name, "size": o.size, "etag": o.etag,
             "lastModified": time.strftime(
                 "%Y-%m-%dT%H:%M:%SZ", time.gmtime(o.mod_time))}
            for o in infos]}

    def rpc_RemoveObject(self, p: dict) -> dict:
        """Deletes ride the S3 DELETE pipeline (synthetic request):
        versioned buckets get delete markers, object-lock is enforced,
        events/replication/tier cleanup fire — the reference's web
        RemoveObject goes through the same deleteObject core
        (cmd/web-handlers.go)."""
        bucket = p.get("bucketName", "")
        objects = p.get("objects", [])
        from . import errors as s3err
        self._layer()  # raise "initializing" before any permission check
        # All-or-nothing permission check BEFORE any deletion — a
        # mid-list denial must not leave a half-deleted batch.
        for key in objects:
            self._check(p["_user"], "s3:DeleteObject",
                        f"{bucket}/{key}")
        handlers = self.server.handlers
        removed, errors = [], []
        for key in objects:
            sub = self._synthetic_request("DELETE", bucket, key)
            try:
                handlers.delete_object(sub)  # 204 also for missing keys
                removed.append(key)
            except s3err.APIError as e:
                errors.append({"object": key, "error": e.code})
        out = {"removed": removed}
        if errors:
            out["errors"] = errors
        return out

    def rpc_PresignedGet(self, p: dict) -> dict:
        bucket = p.get("bucketName", "")
        key = p.get("objectName", "")
        expiry = min(int(p.get("expiry", 3600)), 7 * 24 * 3600)
        self._check(p["_user"], "s3:GetObject", f"{bucket}/{key}")
        from . import sigv4
        host = p.get("host") or f"127.0.0.1:{self.server_port()}"
        enc = urllib.parse.quote(key, safe="/-_.~")
        url = sigv4.presign_url(
            "GET", host, f"/{bucket}/{enc}", p["_user"],
            self.server._lookup_secret(p["_user"]), expires=expiry)
        return {"url": url}

    def rpc_CreateURLToken(self, p: dict) -> dict:
        token = jwt_sign({"sub": p["_user"],
                          "exp": time.time() + URL_TOKEN_TTL,
                          "aud": "url"}, self.server.secret_key)
        return {"token": token}

    def rpc_ServerInfo(self, p: dict) -> dict:
        from .. import __version__
        return {"version": __version__,
                "uiVersion": "minio-tpu",
                "region": self.server.region}

    def server_port(self) -> int:
        httpd = self.server._httpd
        return httpd.server_address[1] if httpd else 0

    # -- raw upload / download (ref /minio/upload|download routes) -------

    def handle_upload(self, path: str, headers: dict,
                      body: bytes) -> tuple[int, str, bytes]:
        """Web uploads ride the S3 PUT pipeline (synthetic request), so
        bucket quota, object-lock defaults, bucket-default SSE,
        compression, replication stamping and events all apply — same
        funneling the reference's web Upload handler does through
        putObject (cmd/web-handlers.go)."""
        try:
            user = self._authenticate_token(headers)
        except WebError:
            return 401, "application/json", b'{"error":"auth"}'
        rest = path[len("/minio-tpu/web/upload/"):]
        bucket, _, key = rest.partition("/")
        key = urllib.parse.unquote(key)
        if not bucket or not key:
            return 400, "application/json", b'{"error":"bad path"}'
        from . import errors as s3err
        try:
            self._check(user, "s3:PutObject", f"{bucket}/{key}")
        except WebError:
            return 403, "application/json", b'{"error":"denied"}'
        if self.server.handlers is None:
            return 503, "application/json", b'{"error":"initializing"}'
        sub = self._synthetic_request(
            "PUT", bucket, key,
            {"content-type": headers.get("content-type",
                                         "application/octet-stream")},
            body)
        try:
            self.server.handlers.put_object(sub)
        except s3err.APIError as e:
            status = 403 if e.http_status == 403 else 400
            return status, "application/json", json.dumps(
                {"error": e.code}).encode()
        except Exception as e:  # noqa: BLE001
            return 400, "application/json", json.dumps(
                {"error": str(e)}).encode()
        return 200, "application/json", b'{"ok":true}'

    def handle_download(self, path: str, query: str,
                        ) -> tuple[int, str, bytes]:
        """Web downloads reuse the S3 read tail (_read_object_plain) so
        SSE-S3 objects decrypt, compressed objects decompress, and
        tier-transitioned objects read through their tier — instead of
        serving stored ciphertext verbatim."""
        params = dict(urllib.parse.parse_qsl(query))
        try:
            claims = jwt_verify(params.get("token", ""),
                                self.server.secret_key)
            if claims.get("aud") != "url":
                raise WebError("wrong token type")
        except WebError:
            return 401, "application/json", b'{"error":"auth"}'
        rest = path[len("/minio-tpu/web/download/"):]
        bucket, _, key = rest.partition("/")
        key = urllib.parse.unquote(key)
        from . import errors as s3err
        try:
            self._check(claims.get("sub", ""), "s3:GetObject",
                        f"{bucket}/{key}")
        except WebError:
            return 403, "application/json", b'{"error":"denied"}'
        if self.server.handlers is None:
            return 503, "application/json", b'{"error":"initializing"}'
        sub = self._synthetic_request("GET", bucket, key)
        try:
            data, info = self.server.handlers._read_object_plain(sub)
        except s3err.APIError as e:
            # 4xx/5xx pass through honestly (e.g. SSE-C key errors are
            # 400, not "not found").
            status = e.http_status if 400 <= e.http_status < 600 else 404
            return status, "application/json", json.dumps(
                {"error": e.code}).encode()
        except Exception:  # noqa: BLE001
            return 404, "application/json", b'{"error":"not found"}'
        from ..event import event as ev
        self.server.handlers._notify(ev.OBJECT_ACCESSED_GET, bucket,
                                     key, info)
        return 200, info.metadata.get("content-type",
                                      "application/octet-stream"), data
