"""Minimal XML building/parsing for the S3 wire format (ref
cmd/api-response.go XML marshaling)."""

from __future__ import annotations

import xml.etree.ElementTree as ET
from xml.sax.saxutils import escape

S3_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


class Element:
    """Tiny ordered XML builder."""

    def __init__(self, tag: str, xmlns: str = ""):
        self.tag = tag
        self.xmlns = xmlns
        self.children: list["Element | tuple[str, str]"] = []

    def child(self, tag: str, text: str | int | bool | None = None,
              ) -> "Element":
        if text is None:
            e = Element(tag)
            self.children.append(e)
            return e
        if isinstance(text, bool):
            text = "true" if text else "false"
        self.children.append((tag, str(text)))
        return self

    def append(self, e: "Element") -> "Element":
        self.children.append(e)
        return e

    def _render(self, out: list[str]) -> None:
        attrs = f' xmlns="{self.xmlns}"' if self.xmlns else ""
        out.append(f"<{self.tag}{attrs}>")
        for c in self.children:
            if isinstance(c, Element):
                c._render(out)
            else:
                tag, text = c
                out.append(f"<{tag}>{escape(text)}</{tag}>")
        out.append(f"</{self.tag}>")

    def tobytes(self) -> bytes:
        out: list[str] = ['<?xml version="1.0" encoding="UTF-8"?>']
        self._render(out)
        return "".join(out).encode("utf-8")


def parse(data: bytes) -> ET.Element:
    """Parse a request XML body; strips namespaces for easy lookup."""
    root = ET.fromstring(data)
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root
