from .select import S3SelectError, run_select

__all__ = ["run_select", "S3SelectError"]
