"""Typed column batches for the columnar S3 Select scan engine.

CSV and Parquet inputs decompose into per-column typed arrays instead
of per-row dicts: numeric columns ride as device-eligible float64
arrays (with an ``intish`` flag so integer semantics stay exact),
strings as U-dtype arrays or — when the Parquet page was dictionary
encoded — as (codes, dictionary) pairs so a predicate evaluates once
per DISTINCT value and gathers.  Every column carries three masks:

- ``null``  — SQL NULL cells (Parquet definition level 0)
- ``miss``  — the field is ABSENT (ragged CSV rows): MISSING, which
  ``IS MISSING`` distinguishes from NULL
- a per-row **fallback mask** seeded here (int64 magnitudes past
  float64's 2^53 exact-integer range, >15-digit numeric strings) and
  grown by the compiler (division by zero, complex LIKE survivors):
  rows the vectorized path cannot decide EXACTLY take the row engine
  (s3select/fallback.py), so semantics never drift from the oracle.

The row readers (readers.csv_records / parquet.parquet_records) stay
untouched as the semantics oracle and the fallback execution tier.
"""

from __future__ import annotations

import numpy as np

from . import readers

# Rows per CSV column batch: bounds the U-array working set while
# keeping the vectorized ops wide enough to amortize dispatch.
CSV_BATCH_ROWS = 65536

# A string column whose U-dtype materialization would exceed this is
# not vectorized (one pathological 1MiB field would expand EVERY row
# to that width); the engine then falls back to the row tier.
MAX_U_BYTES = 64 << 20

# Integer-looking strings longer than this many characters can exceed
# float64's exact-integer range; those rows take the row fallback so
# dynamic-typed comparisons stay exact.
SAFE_NUM_CHARS = 15
# float64 exact-integer bound (2^53): int64 cells past it are
# fallback-masked at load, intish intermediates past it at eval.
INT_EXACT = float(1 << 53)

_ABSENT = object()   # py_value marker for a MISSING cell


class Column:
    """One typed column: raw values + null/miss/fallback masks.

    kind is "num" (raw int32/int64/float32/float64), "bool", or
    "str" (raw list[str] / U array / object array, or None when
    dictionary-backed via ``codes`` + ``dict_values``).
    """

    __slots__ = ("name", "kind", "raw", "null", "miss", "intish",
                 "codes", "dict_values", "_f64", "_u", "_strnum",
                 "_nrows")

    def __init__(self, name: str, kind: str, raw=None, null=None,
                 miss=None, intish: bool = False, codes=None,
                 dict_values=None, nrows: int | None = None):
        self.name = name
        self.kind = kind
        self.raw = raw
        self.null = null
        self.miss = miss
        self.intish = intish
        self.codes = codes
        self.dict_values = dict_values
        if nrows is None:
            nrows = len(codes) if raw is None else len(raw)
        self._nrows = nrows
        self._f64 = None
        self._u = None
        self._strnum = None

    @property
    def nrows(self) -> int:
        return self._nrows

    def null_mask(self) -> np.ndarray:
        """NULL-or-MISSING (the SQL `_is_null` notion)."""
        n = self._nrows
        out = np.zeros(n, dtype=bool)
        if self.null is not None:
            out |= self.null
        if self.miss is not None:
            out |= self.miss
        return out

    def miss_mask(self) -> np.ndarray:
        if self.miss is not None:
            return self.miss
        return np.zeros(self._nrows, dtype=bool)

    def data_nbytes(self) -> int:
        """Payload bytes this column carries — the dispatch-size
        input for the autotuner's batch-size bucket."""
        if self.codes is not None:
            return int(self.codes.nbytes) + sum(
                len(s) for s in self.dict_values)
        if isinstance(self.raw, np.ndarray):
            return int(self.raw.nbytes)
        return sum(len(s) for s in self.raw)

    # -- numeric views --------------------------------------------------

    def f64(self) -> tuple[np.ndarray, np.ndarray | None]:
        """(float64 values, fallback mask|None) for a num column."""
        if self._f64 is None:
            vals = np.asarray(self.raw)
            fb = None
            if vals.dtype.kind in "iu":
                if vals.dtype.itemsize >= 8:
                    big = np.abs(vals.astype(np.float64)) >= INT_EXACT
                    if big.any():
                        fb = big
                vals = vals.astype(np.float64)
            elif vals.dtype != np.float64:
                vals = vals.astype(np.float64)
            self._f64 = (vals, fb)
        return self._f64

    def strnum(self) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
        """Dynamic numeric coercion of a str column, vectorized:
        (float64 values, ok mask, fallback mask|None).  Rows that do
        not parse are simply not-ok (a comparison there answers False,
        like the row engine's `_coerced_pair`); parseable cells longer
        than SAFE_NUM_CHARS fall back for exactness."""
        if self._strnum is None:
            if self.codes is not None:
                # dummy-pad an empty (all-null chunk) dictionary, as
                # in str_rep — the rows are null-masked regardless
                dv, dok, dlen = _parse_str_array(self.dict_values
                                                 or [""])
                codes = np.clip(self.codes, 0, None)
                vals = dv[codes]
                ok = dok[codes] & (self.codes >= 0)
                lens = dlen[codes]
            else:
                vals, ok, lens = _parse_str_array(self.raw)
            fb = ok & (lens > SAFE_NUM_CHARS)
            self._strnum = (vals, ok, fb if fb.any() else None)
        return self._strnum

    # -- string views ---------------------------------------------------

    def str_rep(self):
        """Vectorizable string representation:
        ("dict", U-array-of-dict, codes) for dictionary-backed columns
        (predicates evaluate per DISTINCT value, then gather),
        ("u", U-array) otherwise, or None when the U materialization
        would blow the memory cap."""
        if self.codes is not None:
            if self._u is None:
                # An all-null chunk can carry an EMPTY dictionary —
                # pad with one dummy entry so clipped-code gathers
                # stay in bounds (every row is null-masked anyway).
                self._u = np.asarray(self.dict_values or [""],
                                     dtype=np.str_)
            return ("dict", self._u, self.codes)
        if self._u is None:
            arr = self.raw
            if not isinstance(arr, np.ndarray) or arr.dtype.kind != "U":
                total = 0
                maxlen = 0
                for s in arr:
                    ln = len(s)
                    total += ln
                    if ln > maxlen:
                        maxlen = ln
                if maxlen * 4 * max(1, self._nrows) > MAX_U_BYTES:
                    return None
                u = np.asarray(arr, dtype=np.str_)
                # numpy U storage silently DROPS trailing NUL chars;
                # a lossy conversion here would diverge from the row
                # engine on equality/LIKE — refuse it instead.
                if int(np.char.str_len(u).sum()) != total:
                    return None
                arr = u
            self._u = arr
        return ("u", self._u)

    # -- exact materialization ------------------------------------------

    def py_value(self, i: int):
        """The exact python value the row reader would have produced
        for this cell; _ABSENT when the field is missing."""
        if self.miss is not None and self.miss[i]:
            return _ABSENT
        if self.null is not None and self.null[i]:
            return None
        if self.codes is not None:
            return self.dict_values[int(self.codes[i])]
        v = self.raw[i]
        if self.kind == "str":
            return str(v)
        if self.kind == "bool":
            return bool(v)
        dt = np.asarray(self.raw).dtype
        return int(v) if dt.kind in "iu" else float(v)

    def py_values(self, idx: np.ndarray) -> list:
        """Bulk py_value for many rows: column-wise ndarray.tolist()
        (exact python ints/floats/bools/strs) instead of per-cell
        method calls — the projection tail of a high-selectivity scan
        lives here."""
        if self.codes is not None:
            dv = self.dict_values
            vals = [dv[c] if c >= 0 else None
                    for c in self.codes.take(idx).tolist()]
        elif self.kind == "str" and not isinstance(self.raw,
                                                   np.ndarray):
            raw = self.raw
            vals = [raw[i] for i in idx.tolist()]
        else:
            vals = np.asarray(self.raw).take(idx).tolist()
        if self.null is not None:
            for j in np.flatnonzero(self.null.take(idx)).tolist():
                vals[j] = None
        if self.miss is not None:
            for j in np.flatnonzero(self.miss.take(idx)).tolist():
                vals[j] = _ABSENT
        return vals


def _parse_str_array(arr) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(float64 values, parse-ok mask, per-cell char length) for a
    sequence of strings.  Whole-array astype is the fast path; any
    non-conforming cell drops to an exact per-element float() parse
    (the row engine's own coercion) so e.g. '1_0' stays consistent."""
    src = None
    if isinstance(arr, np.ndarray) and arr.dtype.kind == "U":
        u = arr
    else:
        src = list(arr)
        maxlen = max((len(s) for s in src), default=0)
        if maxlen * 4 * max(1, len(src)) > MAX_U_BYTES:
            # one wide cell would inflate EVERY row to its width —
            # the bounded per-element parse below is exact anyway
            u = None
        else:
            u = np.asarray(src, dtype=np.str_)
            # numpy U storage drops trailing NULs — if the conversion
            # was lossy, parse the ORIGINAL strings per element.
            if int(np.char.str_len(u).sum()) != \
                    sum(len(s) for s in src):
                u = None
    if u is not None:
        lens = np.char.str_len(u)
        try:
            with np.errstate(all="ignore"):
                vals = u.astype(np.float64)
            return vals, np.ones(len(u), dtype=bool), lens
        except ValueError:
            src = u.tolist()
    n = len(src)
    lens = np.asarray([len(s) for s in src], dtype=np.int64)
    vals = np.zeros(n, dtype=np.float64)
    ok = np.zeros(n, dtype=bool)
    for i, s in enumerate(src):
        try:
            vals[i] = float(s)
            ok[i] = True
        except ValueError:
            pass
    return vals, ok, lens


class ColumnBatch:
    """One batch of rows as typed columns, plus the exact-record
    escape hatch the fallback tier and the projector use."""

    def __init__(self, names: list[str], cols: dict[str, Column],
                 nrows: int, nbytes: int):
        self.names = names
        self.cols = cols
        self.nrows = nrows
        # Decoded bytes this batch actually processed — the honest
        # BytesProcessed numerator (only the columns that were read).
        self.nbytes = nbytes
        self._lower: dict[str, Column] | None = None

    def col(self, name: str) -> Column | None:
        """Mirror sql.Col's lookup: exact key, else the LAST column
        whose lowercased name matches (the row engine's lowered-dict
        rebuild lets later keys win)."""
        c = self.cols.get(name)
        if c is not None:
            return c
        if self._lower is None:
            self._lower = {n.lower(): self.cols[n] for n in self.names}
        return self._lower.get(name.lower())

    def record(self, i: int) -> dict:
        """The exact dict the row reader would have yielded for row i
        (missing fields absent, not None)."""
        out = {}
        for name in self.names:
            v = self.cols[name].py_value(i)
            if v is not _ABSENT:
                out[name] = v
        return out

    def records(self, idxs) -> list[dict]:
        """Exact reader-identical records for many rows, built
        column-wise.  The no-MISSING common case zips straight into
        dicts; ragged rows drop their absent keys per row."""
        idx = np.asarray(list(idxs), dtype=np.int64)
        if idx.size == 0:
            return []
        per_col = [self.cols[n].py_values(idx) for n in self.names]
        if not any(c.miss is not None and c.miss.take(idx).any()
                   for c in self.cols.values()):
            names = self.names
            return [dict(zip(names, row)) for row in zip(*per_col)]
        out = []
        for j in range(len(idx)):
            rec = {}
            for name, vals in zip(self.names, per_col):
                v = vals[j]
                if v is not _ABSENT:
                    rec[name] = v
            out.append(rec)
        return out


# ---------------------------------------------------------------------------
# CSV -> column batches
# ---------------------------------------------------------------------------


def csv_column_batches(data: bytes, *, file_header_info: str = "NONE",
                       field_delimiter: str = ",",
                       record_delimiter: str = "\n",
                       quote_character: str = '"',
                       quote_escape_character: str = '"',
                       comments: str = "",
                       batch_rows: int = CSV_BATCH_ROWS):
    """Yield ColumnBatch objects from CSV bytes with the same header /
    comment / CRLF semantics as readers.csv_records (the oracle the
    differential suite holds this against)."""
    text = data.decode("utf-8", errors="replace")
    if record_delimiter and record_delimiter != "\n":
        text = text.replace(record_delimiter, "\n")
    delim = field_delimiter or ","
    quote = quote_character or '"'
    escape = quote_escape_character or quote
    mode = (file_header_info or "NONE").upper()

    # Fast vectorized path: quote-free, CR-free, NUL-free (numpy U
    # storage truncates trailing NULs), comment-free input with
    # uniform field counts splits into columns with np.char
    # partitions — no per-cell python.  Anything irregular takes the
    # row-by-row builder below (same output, proven by the oracle).
    if (quote not in text and escape not in text and "\r" not in text
            and "\x00" not in text and not comments):
        yield from _csv_fast_batches(text, delim, mode, batch_rows)
        return
    yield from _csv_slow_batches(text, delim, quote, escape, mode,
                                 comments, batch_rows)


def _csv_names(header: list[str] | None, width: int) -> list[str]:
    if header is None:
        return [f"_{j + 1}" for j in range(width)]
    return [header[j] if j < len(header) else f"_{j + 1}"
            for j in range(width)]


def _batch_bytes(nrows: int, width: int, cell_chars: float) -> int:
    # Processed-bytes estimate for CSV batches: the characters this
    # batch's cells actually carried (delimiters included).
    return int(nrows * width * cell_chars)


def _csv_fast_batches(text: str, delim: str, mode: str,
                      batch_rows: int):
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    lines = [ln for ln in lines if ln]
    if not lines:
        return
    header: list[str] | None = None
    if mode == "USE":
        header = [h.strip() for h in lines[0].split(delim)]
        lines = lines[1:]
    elif mode == "IGNORE":
        lines = lines[1:]
    for start in range(0, len(lines), batch_rows):
        chunk = lines[start:start + batch_rows]
        # U-array width is the WIDEST line: one pathological 1MiB
        # line would inflate every row to that width (nrows x maxlen
        # x 4 bytes) — bound the allocation BEFORE it happens.
        maxlen = max(len(ln) for ln in chunk)
        if maxlen * 4 * len(chunk) > MAX_U_BYTES:
            yield from _rows_to_batches(
                (ln.split(delim) for ln in chunk), header,
                len(chunk), sum(len(ln) for ln in chunk))
            continue
        arr = np.asarray(chunk, dtype=np.str_)
        counts = np.char.count(arr, delim)
        width = int(counts[0]) + 1 if len(counts) else 1
        if not (counts == width - 1).all():
            # Ragged rows: the uniform-width partition trick would
            # conflate "absent field" with "empty field"; per-row path.
            yield from _rows_to_batches(
                (ln.split(delim) for ln in chunk), header,
                len(chunk), sum(len(ln) for ln in chunk))
            continue
        names = _csv_names(header, width)
        cols: dict[str, Column] = {}
        rest = arr
        for j in range(width):
            if j < width - 1:
                part = np.char.partition(rest, delim)
                field, rest = part[:, 0], part[:, 2]
            else:
                field = rest
            cols[names[j]] = Column(names[j], "str", raw=field)
        yield ColumnBatch(names, cols, len(chunk),
                          sum(len(ln) + 1 for ln in chunk))


def _csv_slow_batches(text: str, delim: str, quote: str, escape: str,
                      mode: str, comments: str, batch_rows: int):
    """Row-by-row builder sharing readers' chunked parse (quote
    parity, distinct escape handling, CRLF, comments)."""
    import csv as _csv
    import io

    chunk_chars = (readers.CSV_CHUNK_BYTES if escape == quote
                   else max(len(text), 1))
    header: list[str] | None = None
    first = True
    pend_rows: list[list[str]] = []
    pend_chars = 0

    def flush():
        nonlocal pend_rows, pend_chars
        if pend_rows:
            rows, chars = pend_rows, pend_chars
            pend_rows, pend_chars = [], 0
            yield from _rows_to_batches(rows, header, len(rows), chars)

    for chunk in readers._csv_chunks(text, quote, chunk_chars):
        if quote not in chunk and escape not in chunk:
            rows_iter = []
            for line in chunk.split("\n"):
                if line.endswith("\r"):
                    line = line[:-1]
                if line:
                    rows_iter.append(line.split(delim))
        else:
            reader = _csv.reader(
                io.StringIO(chunk), delimiter=delim, quotechar=quote,
                doublequote=(escape == quote),
                escapechar=(None if escape == quote else escape))
            rows_iter = [row for row in reader if row]
        for row in rows_iter:
            if comments and row[0].startswith(comments):
                continue
            if first:
                first = False
                if mode == "USE":
                    header = [h.strip() for h in row]
                    continue
                if mode == "IGNORE":
                    continue
            pend_rows.append(row)
            pend_chars += sum(len(f) + 1 for f in row)
            if len(pend_rows) >= batch_rows:
                yield from flush()
    yield from flush()


def _rows_to_batches(rows_iter, header: list[str] | None, nrows: int,
                     nbytes: int):
    """list-of-fields rows -> one ColumnBatch (ragged rows carry a
    MISSING mask; extra fields past the header become _N columns)."""
    rows = list(rows_iter)
    if not rows:
        return
    width = max(len(r) for r in rows)
    names = _csv_names(header, width)
    cols: dict[str, Column] = {}
    n = len(rows)
    for j in range(width):
        vals = [""] * n
        miss = None
        for i, r in enumerate(rows):
            if j < len(r):
                vals[i] = r[j]
            else:
                if miss is None:
                    miss = np.zeros(n, dtype=bool)
                miss[i] = True
        cols[names[j]] = Column(names[j], "str", raw=vals, miss=miss)
    yield ColumnBatch(names, cols, n, nbytes)


# ---------------------------------------------------------------------------
# Parquet -> column batches
# ---------------------------------------------------------------------------


def parquet_column_batches(data: bytes, wanted: set[str] | None = None):
    """Yield one ColumnBatch per Parquet row group, decoding ONLY the
    columns the query references (projection/predicate pushdown —
    ``wanted`` None = all).  Numeric pages decode via np.frombuffer,
    dictionary-encoded strings stay as (codes, dictionary) so string
    predicates evaluate once per distinct value."""
    from . import parquet as pq
    cols, groups = pq.read_footer(data)
    by_name = {c.name: c for c in cols}
    names = [c.name for c in cols]
    if wanted is None:
        take = names
    else:
        # sql.Col resolves case-INSENSITIVELY; pruning must keep any
        # column a case-mismatched reference could still resolve to,
        # or the scan silently types it as absent.
        wanted_lower = {w.lower() for w in wanted}
        take = [n for n in names
                if n in wanted or n.lower() in wanted_lower]
    for g in groups:
        nrows = g["num_rows"]
        batch_cols: dict[str, Column] = {}
        nbytes = 0
        for ch in g["chunks"]:
            name = ch.path[-1] if ch.path else ""
            col = by_name.get(name)
            if col is None or name not in take:
                continue
            decoded = pq.decode_chunk_np(data, ch, col)
            nbytes += decoded["unc_bytes"]
            if nrows == 0:
                nrows = decoded["nrows"]
            batch_cols[name] = _parquet_column(name, col, decoded)
        # Columns the query never touches still need MISSING/None
        # semantics on materialized records: represent them as
        # all-null placeholders ONLY when the caller asked for all
        # columns (SELECT *); pruned scans never materialize them.
        yield ColumnBatch([n for n in names if n in batch_cols],
                          batch_cols, nrows, nbytes)


def _parquet_column(name: str, col, decoded: dict) -> Column:
    from . import parquet as pq
    null = decoded["null"]
    if decoded.get("codes") is not None:
        return Column(name, "str", null=null,
                      codes=decoded["codes"],
                      dict_values=decoded["dict"])
    vals = decoded["values"]
    if col.ptype == pq.BOOLEAN:
        return Column(name, "bool", raw=vals, null=null)
    if col.ptype == pq.BYTE_ARRAY:
        return Column(name, "str", raw=vals, null=null)
    return Column(name, "num", raw=vals, null=null,
                  intish=(col.ptype in (pq.INT32, pq.INT64)))
