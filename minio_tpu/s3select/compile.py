"""AST -> vectorized-plan compiler for the columnar scan engine.

Lowers the row engine's predicate/projection AST (sql.py Cmp / Arith /
Between / In / IsNull / BoolOp / Not / Neg / Like / Col / Lit) to a
tree of vectorized ops over ColumnBatch columns.  Everything the
lowering cannot decide EXACTLY lands in one of two escapes:

- ``CompileError`` at compile time (unsupported node — functions,
  nested paths, LIKE over non-string values): the engine runs the
  whole query on the row oracle instead;
- the per-row **fallback mask** at eval time (division by zero where
  the row engine raises, intish intermediates past float64's 2^53
  exact-integer range, complex-LIKE prefilter survivors): those rows
  re-evaluate on the row engine (s3select/fallback.py), so the
  vectorized path never has to approximate.

Values flow as ``VV`` triples-of-masks (SQL three-valued logic):
``valid`` is False where the value is NULL/MISSING, ``miss`` marks
MISSING specifically (``IS MISSING``), ``fb`` is the accumulated
fallback mask.  Numeric math runs in float64 with an ``intish`` flag:
results that stay within 2^53 are bit-exact against the row engine's
python-int arithmetic, results beyond it fall back.

Plans whose ops are all comparisons/boolean logic over float32/int32/
bool columns with float32-exact literals are additionally **jit
eligible**: the same node tree evaluates under ``jax.numpy`` inside
``ops/select_kernels.py`` (device / xla-cpu lanes) without x64,
because every represented value is exact in float32 there too.
"""

from __future__ import annotations

import numpy as np

from . import sql
from .columnar import INT_EXACT, ColumnBatch

# int32 cells past float32's exact-integer range (2^24) fall back when
# a plan runs on the float32 jit lane.
F32_EXACT = float(1 << 24)


class CompileError(Exception):
    """This query (or node) has no exact vectorized lowering; the row
    engine serves it."""


class VV:
    """One vectorized value: kind "num" | "str" | "bool" | "null".

    val:   ndarray or python scalar (literals stay scalar and
           broadcast); for kind "str" a Column object or a python str.
    valid: bool ndarray or True — False = SQL NULL/MISSING.
    miss:  bool ndarray or False — MISSING specifically.
    fb:    bool ndarray or None — rows needing the row-engine fallback.
    intish: numeric value lives in the exact-integer domain (guards
           apply to intermediates).
    """

    __slots__ = ("kind", "val", "valid", "miss", "fb", "intish")

    def __init__(self, kind, val, valid=True, miss=False, fb=None,
                 intish=False):
        self.kind = kind
        self.val = val
        self.valid = valid
        self.miss = miss
        self.fb = fb
        self.intish = intish


def _and(a, b):
    """Logical-and of masks where either side may be a python bool."""
    if a is True:
        return b
    if b is True:
        return a
    if a is False or b is False:
        return False
    return a & b


def _or(a, b):
    if a is True or b is True:
        return True
    if a is False:
        return b
    if b is False:
        return a
    return a | b


def _not(xp, a):
    if a is True:
        return False
    if a is False:
        return True
    return ~a


def _fb_union(*masks):
    out = None
    for m in masks:
        if m is None:
            continue
        out = m if out is None else (out | m)
    return out


def _full(ctx, value: bool):
    return ctx.xp.full(ctx.n, value, dtype=bool)


def _asarray(ctx, mask):
    """Materialize a possibly-scalar mask to a full bool array."""
    if mask is True or mask is False:
        return _full(ctx, bool(mask))
    return mask


class Ctx:
    """Evaluation context: ``xp`` is numpy (host lane) or jax.numpy
    (jit lanes); host contexts carry the ColumnBatch for string ops,
    jit contexts carry pre-bound (vals, valid, miss) arrays."""

    def __init__(self, xp, n: int, batch: ColumnBatch | None = None,
                 arrays: dict | None = None):
        self.xp = xp
        self.n = n
        self.batch = batch
        self.arrays = arrays


# -- nodes ------------------------------------------------------------------


class CNode:
    def run(self, ctx: Ctx) -> VV:  # pragma: no cover - interface
        raise NotImplementedError


class CLit(CNode):
    def __init__(self, value):
        self.value = value
        if value is None:
            self.kind = "null"
        elif isinstance(value, bool):
            self.kind = "bool"
        elif isinstance(value, (int, float)):
            if isinstance(value, int) and abs(value) > INT_EXACT:
                # A float64 image of this literal is lossy while the
                # row engine compares exact ints — no exact lowering.
                raise CompileError("integer literal past 2^53")
            self.kind = "num"
        elif isinstance(value, str):
            self.kind = "str"
        else:
            raise CompileError(f"literal {type(value).__name__}")

    def run(self, ctx: Ctx) -> VV:
        if self.kind == "null":
            return VV("null", None, valid=False)
        if self.kind == "num":
            return VV("num", float(self.value),
                      intish=isinstance(self.value, int))
        return VV(self.kind, self.value)


class CCol(CNode):
    def __init__(self, name: str, kind: str):
        self.name = name
        self.kind = kind   # schema kind from the first batch

    def run(self, ctx: Ctx) -> VV:
        if ctx.arrays is not None:   # jit lane: pre-bound numerics
            vals, valid, miss = ctx.arrays[self.name]
            return VV(self.kind, vals, valid=valid, miss=miss,
                      intish=False)
        col = ctx.batch.col(self.name)
        if col is None:
            absent = _full(ctx, True)
            return VV("null", None, valid=_full(ctx, False),
                      miss=absent)
        if col.kind != self.kind:
            raise CompileError(
                f"column {self.name} changed kind "
                f"({self.kind} -> {col.kind})")
        valid = ~col.null_mask()
        miss = col.miss_mask()
        if col.kind == "num":
            vals, fb = col.f64()
            return VV("num", vals, valid=valid, miss=miss, fb=fb,
                      intish=col.intish)
        if col.kind == "bool":
            return VV("bool", np.asarray(col.raw, dtype=bool),
                      valid=valid, miss=miss)
        return VV("str", col, valid=valid, miss=miss)


def _as_num(ctx, vv: VV):
    """The row engine's `_num` coercion, vectorized:
    (float64 vals, ok mask, fb, intish).  ok is False where coercion
    fails OR the value is NULL — a Cmp treats those differently from
    an Arith, so callers combine with vv.valid themselves."""
    if vv.kind == "num":
        return vv.val, vv.valid, vv.fb, vv.intish
    if vv.kind == "str":
        if isinstance(vv.val, str):
            n = sql._num(vv.val)
            if n is None:
                return 0.0, False, vv.fb, False
            return float(n), vv.valid, vv.fb, isinstance(n, int)
        vals, ok, fb = vv.val.strnum()
        return vals, _and(vv.valid, ok), _fb_union(vv.fb, fb), True
    # bool / null: _num() answers None
    return 0.0, False, vv.fb, False


def _str_apply(ctx, col_or_str, fn):
    """Apply a vectorized string predicate.  Dictionary-backed columns
    evaluate once per DISTINCT value and gather through the codes —
    the dictionary trick that makes string predicates O(cardinality)
    instead of O(rows)."""
    if isinstance(col_or_str, str):
        u = np.asarray([col_or_str], dtype=np.str_)
        return bool(np.asarray(fn(u))[0])
    rep = col_or_str.str_rep()
    if rep is None:
        raise CompileError("string column too wide to vectorize")
    if rep[0] == "dict":
        _, dict_u, codes = rep
        small = np.asarray(fn(dict_u), dtype=bool)
        return small[np.clip(codes, 0, None)]
    return np.asarray(fn(rep[1]), dtype=bool)


def _str_u(col_or_str):
    """Full U-array for a string VV payload (col-vs-col compares)."""
    if isinstance(col_or_str, str):
        return col_or_str
    rep = col_or_str.str_rep()
    if rep is None:
        raise CompileError("string column too wide to vectorize")
    if rep[0] == "dict":
        _, dict_u, codes = rep
        return dict_u[np.clip(codes, 0, None)]
    return rep[1]


_CMP_FNS = {
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


class CCmp(CNode):
    def __init__(self, op: str, left: CNode, right: CNode):
        if op not in _CMP_FNS:
            raise CompileError(f"comparison {op}")
        self.op = op
        self.left = left
        self.right = right

    def run(self, ctx: Ctx) -> VV:
        lv = self.left.run(ctx)
        rv = self.right.run(ctx)
        fn = _CMP_FNS[self.op]
        valid = _and(lv.valid, rv.valid)   # NULL operand -> NULL
        fb = _fb_union(lv.fb, rv.fb)
        kinds = (lv.kind, rv.kind)
        if "bool" in kinds:
            if kinds == ("bool", "bool"):
                val = fn(lv.val, rv.val)
            else:
                val = False   # bool vs non-bool coerces to no-match
            return VV("bool", val, valid=valid, fb=fb)
        if "num" in kinds:
            la, lok, lfb, _ = _as_num(ctx, lv)
            ra, rok, rfb, _ = _as_num(ctx, rv)
            ok = _and(lok, rok)
            with np.errstate(invalid="ignore"):
                cmp = fn(la, ra)
            # coercion failure -> False (not NULL), like _coerced_pair
            val = _and(cmp, ok)
            return VV("bool", val, valid=valid,
                      fb=_fb_union(fb, lfb, rfb))
        if kinds == ("str", "str"):
            lu, ru = _str_u(lv.val), _str_u(rv.val)
            if isinstance(lu, str) and isinstance(ru, str):
                val = fn(lu, ru)
            elif isinstance(ru, str):
                val = _str_apply(ctx, lv.val, lambda u: fn(u, ru))
            elif isinstance(lu, str):
                val = _str_apply(ctx, rv.val, lambda u: fn(lu, u))
            else:
                val = fn(lu, ru)
            return VV("bool", val, valid=valid, fb=fb)
        # null literal somewhere, or unpairable kinds -> False under
        # a defined pair, NULL otherwise (valid already covers it).
        return VV("bool", False, valid=valid, fb=fb)


_ARITH_OPS = ("+", "-", "*", "/", "%")


class CArith(CNode):
    def __init__(self, op: str, left: CNode, right: CNode):
        if op not in _ARITH_OPS:
            raise CompileError(f"arith {op}")
        self.op = op
        self.left = left
        self.right = right

    def run(self, ctx: Ctx) -> VV:
        lv = self.left.run(ctx)
        rv = self.right.run(ctx)
        la, lok, lfb, li = _as_num(ctx, lv)
        ra, rok, rfb, ri = _as_num(ctx, rv)
        # _num failure on either side -> NULL result
        ok = _and(_and(lok, lv.valid), _and(rok, rv.valid))
        fb = _fb_union(lv.fb, rv.fb, lfb, rfb)
        with np.errstate(all="ignore"):
            if self.op == "+":
                val = la + ra
            elif self.op == "-":
                val = la - ra
            elif self.op == "*":
                val = la * ra
            elif self.op == "/":
                div0 = _and(ok, ra == 0)
                val = np.divide(la, np.where(ra == 0, 1.0, ra))
                # the row engine RAISES on division by zero: those
                # rows must re-evaluate there, in row order
                fb = _fb_union(fb, _asarray(ctx, div0)
                               if div0 is not False else None)
            else:  # %
                div0 = _and(ok, ra == 0)
                val = np.mod(la, np.where(ra == 0, 1.0, ra))
                fb = _fb_union(fb, _asarray(ctx, div0)
                               if div0 is not False else None)
        intish = li and ri and self.op != "/"
        if intish:
            with np.errstate(invalid="ignore"):
                big = _and(ok, np.abs(val) >= INT_EXACT)
            if big is not False:
                fb = _fb_union(fb, _asarray(ctx, big))
        return VV("num", val, valid=ok, fb=fb, intish=intish)


class CNeg(CNode):
    def __init__(self, inner: CNode):
        self.inner = inner

    def run(self, ctx: Ctx) -> VV:
        vv = self.inner.run(ctx)
        a, ok, fb, intish = _as_num(ctx, vv)
        return VV("num", -a if ok is not False else 0.0,
                  valid=_and(ok, vv.valid),
                  fb=_fb_union(vv.fb, fb), intish=intish)


class CBetween(CNode):
    def __init__(self, value: CNode, lo: CNode, hi: CNode,
                 negate: bool):
        self.lo_cmp = CCmp(">=", value, lo)
        self.hi_cmp = CCmp("<=", value, hi)
        self.negate = negate

    def run(self, ctx: Ctx) -> VV:
        lo = self.lo_cmp.run(ctx)
        hi = self.hi_cmp.run(ctx)
        # Between NULL-propagates when EITHER bound compare is NULL,
        # even if the other is already False (unlike AND).
        valid = _and(lo.valid, hi.valid)
        val = _and(lo.val, hi.val)
        if self.negate:
            val = _not(ctx.xp, val)
        return VV("bool", val, valid=valid,
                  fb=_fb_union(lo.fb, hi.fb))


class CIn(CNode):
    def __init__(self, value: CNode, options: list[CNode],
                 negate: bool):
        self.value = value
        self.cmps = [CCmp("=", value, o) for o in options]
        self.negate = negate

    def run(self, ctx: Ctx) -> VV:
        vv = self.value.run(ctx)
        hit = False
        fb = vv.fb
        for c in self.cmps:
            cv = c.run(ctx)
            hit = _or(hit, _and(cv.val, cv.valid))
            fb = _fb_union(fb, cv.fb)
        val = _not(ctx.xp, hit) if self.negate else hit
        return VV("bool", val, valid=vv.valid, fb=fb)


class CIsNull(CNode):
    def __init__(self, value: CNode, negate: bool, missing: bool):
        self.value = value
        self.negate = negate
        self.missing = missing

    def run(self, ctx: Ctx) -> VV:
        vv = self.value.run(ctx)
        val = vv.miss if self.missing else _not(ctx.xp, vv.valid)
        if self.negate:
            val = _not(ctx.xp, val)
        return VV("bool", val, fb=vv.fb)


def _truthy(ctx, vv: VV):
    """python bool(value), vectorized — BoolOp applies it to raw
    operand values (a non-empty string is truthy, 0 is not)."""
    if vv.kind == "bool":
        return vv.val
    if vv.kind == "num":
        with np.errstate(invalid="ignore"):
            return vv.val != 0
    if vv.kind == "str":
        if isinstance(vv.val, str):
            return bool(vv.val)
        return _str_apply(ctx, vv.val,
                          lambda u: np.char.str_len(u) > 0)
    return False   # null literal (valid=False masks it anyway)


def _bool_operand(ctx, vv: VV):
    """(truth, defined) of one BoolOp/Not operand.  The row engine
    applies ``bool(value)`` to the RAW operand — and MISSING is a bare
    ``object()``, so ``bool(MISSING)`` is TRUE and defined, unlike
    NULL (None), which is undefined.  Only a literal None is NULL
    here; a missing field participates as truthy."""
    defined = _or(vv.valid, vv.miss)
    truth = _or(_and(_truthy(ctx, vv), vv.valid), vv.miss)
    return truth, defined


class CBool(CNode):
    def __init__(self, op: str, left: CNode, right: CNode):
        self.op = op
        self.left = left
        self.right = right

    def run(self, ctx: Ctx) -> VV:
        lv = self.left.run(ctx)
        rv = self.right.run(ctx)
        ta, va = _bool_operand(ctx, lv)
        tb, vb = _bool_operand(ctx, rv)
        fb = _fb_union(lv.fb, rv.fb)
        both = _and(va, vb)
        if self.op == "and":
            fa = _and(va, _not(ctx.xp, ta))
            fbse = _and(vb, _not(ctx.xp, tb))
            decided_false = _or(fa, fbse)
            valid = _or(decided_false, both)
            val = _and(ta, tb)
            return VV("bool", val, valid=valid, fb=fb)
        decided_true = _or(ta, tb)
        valid = _or(decided_true, both)
        return VV("bool", decided_true, valid=valid, fb=fb)


class CNot(CNode):
    def __init__(self, inner: CNode):
        self.inner = inner

    def run(self, ctx: Ctx) -> VV:
        vv = self.inner.run(ctx)
        t, defined = _bool_operand(ctx, vv)
        return VV("bool", _and(_not(ctx.xp, t), defined),
                  valid=defined, fb=vv.fb)


class CLike(CNode):
    """[NOT] LIKE with a literal pattern.  Patterns without ``_``
    lower EXACTLY (prefix/suffix/ordered-segment containment via
    np.char); patterns with ``_`` vectorize a necessary-condition
    prefilter (longest literal run containment) and hand survivors to
    the per-row fallback."""

    def __init__(self, value: CNode, pattern: str,
                 escape: str | None, negate: bool):
        self.value = value
        self.negate = negate
        self.lead, self.trail, self.runs, self.exact = \
            _like_parse(pattern, escape)

    def run(self, ctx: Ctx) -> VV:
        vv = self.value.run(ctx)
        if vv.kind == "null":   # LIKE over NULL/MISSING -> NULL
            return VV("bool", False, valid=vv.valid, fb=vv.fb)
        if vv.kind != "str":
            raise CompileError("LIKE over a non-string value")
        if self.exact:
            val = _str_apply(
                ctx, vv.val,
                lambda u: _like_vec(u, self.runs, self.lead,
                                    self.trail))
            if self.negate:
                val = _not(ctx.xp, val)
            return VV("bool", val, valid=vv.valid, fb=vv.fb)
        # Complex pattern (`_` present): vectorized prefilter, row
        # fallback for candidates.  Rows failing the prefilter are
        # DEFINITELY non-matching (negate -> definitely matching).
        longest = max(self.runs, key=len, default="")
        if longest:
            cand = _str_apply(
                ctx, vv.val,
                lambda u: np.char.find(u, longest) >= 0)
        else:
            cand = True
        cand = _and(cand, vv.valid)
        val = _full(ctx, self.negate)
        fb = _fb_union(vv.fb, _asarray(ctx, cand))
        return VV("bool", val, valid=vv.valid, fb=fb)


def _like_parse(pattern: str, escape: str | None):
    """Tokenize a LIKE pattern (mirroring sql.like_to_re's escape
    handling) -> (leading %, trailing %, literal runs, exact?)."""
    toks: list[tuple] = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            toks.append(("lit", pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            toks.append(("%",))
        elif ch == "_":
            toks.append(("_",))
        else:
            toks.append(("lit", ch))
        i += 1
    exact = all(t[0] != "_" for t in toks)
    runs: list[str] = []
    cur: list[str] = []
    for t in toks:
        if t[0] == "lit":
            cur.append(t[1])
        else:
            if cur:
                runs.append("".join(cur))
                cur = []
    if cur:
        runs.append("".join(cur))
    lead = bool(toks) and toks[0][0] != "lit"
    trail = bool(toks) and toks[-1][0] != "lit"
    if not toks:
        lead = trail = False
    return lead, trail, runs, exact


def _like_vec(u, runs: list[str], lead: bool, trail: bool):
    """Exact `%`-only LIKE over a U array: greedy leftmost segment
    matching (correct for the *-only glob class)."""
    n = len(u)
    if not runs:
        # only wildcards ("%", "%%", ...) or the empty pattern
        return (np.ones(n, dtype=bool) if lead or trail
                else u == "")
    if not lead and not trail and len(runs) == 1:
        return u == runs[0]
    lens = np.char.str_len(u)
    ok = np.ones(n, dtype=bool)
    pos = np.zeros(n, dtype=np.int64)
    rem = list(runs)
    if not lead:
        s0 = rem.pop(0)
        ok &= np.char.startswith(u, s0)
        pos[:] = len(s0)
    last = rem.pop() if (not trail and rem) else None
    for m in rem:
        idx = np.char.find(u, m, pos)
        ok &= idx >= 0
        pos = np.where(idx >= 0, idx + len(m), pos)
    if last is not None:
        ok &= np.char.endswith(u, last)
        ok &= (lens - len(last)) >= pos
    return ok


# -- lowering ---------------------------------------------------------------


def lower(node: sql.Node, batch: ColumnBatch) -> CNode:
    """One sql.py AST node -> vectorized node, typed against the
    schema of the first batch.  Raises CompileError for anything
    without an exact lowering."""
    if isinstance(node, sql.Lit):
        return CLit(node.value)
    if isinstance(node, sql.Col):
        if len(node.path) != 1 or not isinstance(node.path[0], str):
            raise CompileError("nested column path")
        name = node.path[0]
        col = batch.col(name)
        kind = col.kind if col is not None else "null"
        return CCol(name, kind)
    if isinstance(node, sql.Cmp):
        return CCmp(node.op, lower(node.left, batch),
                    lower(node.right, batch))
    if isinstance(node, sql.Arith):
        return CArith(node.op, lower(node.left, batch),
                      lower(node.right, batch))
    if isinstance(node, sql.Neg):
        return CNeg(lower(node.inner, batch))
    if isinstance(node, sql.Between):
        return CBetween(lower(node.value, batch),
                        lower(node.lo, batch),
                        lower(node.hi, batch), node.negate)
    if isinstance(node, sql.In):
        return CIn(lower(node.value, batch),
                   [lower(o, batch) for o in node.options],
                   node.negate)
    if isinstance(node, sql.IsNull):
        return CIsNull(lower(node.value, batch), node.negate,
                       node.missing)
    if isinstance(node, sql.BoolOp):
        return CBool(node.op, lower(node.left, batch),
                     lower(node.right, batch))
    if isinstance(node, sql.Not):
        return CNot(lower(node.inner, batch))
    if isinstance(node, sql.Like):
        if not isinstance(node.pattern, sql.Lit) or \
                not isinstance(node.pattern.value, str):
            raise CompileError("non-literal LIKE pattern")
        vc = lower(node.value, batch)
        if isinstance(vc, CCol) and vc.kind in ("num", "bool"):
            # str(numeric) formatting has no exact vectorized twin
            raise CompileError("LIKE over a non-string column")
        if isinstance(vc, CLit) and vc.kind not in ("str", "null"):
            raise CompileError("LIKE over a non-string literal")
        return CLike(vc, node.pattern.value, node.escape, node.negate)
    raise CompileError(f"no lowering for {type(node).__name__}")


class Plan:
    """A lowered predicate/expression plus its dispatch metadata."""

    def __init__(self, root: CNode):
        self.root = root
        self.cols: list[str] = []
        self.col_kinds: dict[str, str] = {}
        self.has_str = False
        self.has_arith = False
        self.f32_safe = True
        # A non-bool root (WHERE age) never passes — passing_mask
        # handles it on the host; the jit image would hand back a
        # float array that & cannot combine.
        self.root_bool = isinstance(
            root, (CCmp, CBool, CNot, CBetween, CIn, CIsNull, CLike))
        self._walk(root)
        self._jit_fn = None

    def _walk(self, node: CNode) -> None:
        if isinstance(node, CCol):
            if node.name not in self.col_kinds:
                self.cols.append(node.name)
                self.col_kinds[node.name] = node.kind
            if node.kind == "str":
                self.has_str = True
        elif isinstance(node, CLit):
            if node.kind == "str":
                self.has_str = True
            elif node.kind == "num":
                v = node.value
                if not (abs(v) <= F32_EXACT
                        and float(np.float32(v)) == float(v)):
                    self.f32_safe = False
        elif isinstance(node, (CArith, CNeg)):
            self.has_arith = True
        elif isinstance(node, CLike):
            self.has_str = True
        for attr in ("left", "right", "inner", "value", "lo_cmp",
                     "hi_cmp"):
            child = getattr(node, attr, None)
            if isinstance(child, CNode):
                self._walk(child)
        for child in getattr(node, "cmps", ()):
            self._walk(child)

    @property
    def jit_ok(self) -> bool:
        """Exact under float32: comparisons/boolean logic only over a
        bool-producing root, no string ops, f32-exact literals —
        int64/float64 columns are excluded at bind (their f32 image
        is lossy)."""
        return (self.root_bool and not self.has_str
                and not self.has_arith and self.f32_safe)

    def eval_host(self, batch: ColumnBatch) -> VV:
        return self.root.run(Ctx(np, batch.nrows, batch=batch))


def passing_mask(vv: VV, n: int) -> tuple[np.ndarray, np.ndarray]:
    """(pass, fb) row masks from a root predicate VV: a row passes
    iff the value `is True` — a non-bool result (WHERE age) never
    passes, NULL never passes.  fb rows are undecided and excluded
    from pass."""
    fb = (np.zeros(n, dtype=bool) if vv.fb is None
          else np.broadcast_to(np.asarray(vv.fb), (n,)))
    if vv.kind != "bool":
        return np.zeros(n, dtype=bool), fb
    val = np.broadcast_to(np.asarray(vv.val), (n,))
    valid = np.broadcast_to(np.asarray(vv.valid), (n,))
    return val & valid & ~fb, fb
