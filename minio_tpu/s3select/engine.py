"""Columnar scan engine for SelectObjectContent.

Drives typed column batches (s3select/columnar.py) through the
compiled vectorized predicate (s3select/compile.py) dispatched by
ops/select_kernels.py, with the row engine (sql.execute) kept as the
semantics oracle and the fallback tier:

- queries the compiler cannot lower EXACTLY raise ``Unsupported`` and
  the caller runs the row oracle on the whole object;
- rows the vectorized path cannot decide (fallback mask) re-evaluate
  on the row tier IN ROW ORDER — including LIMIT interactions and the
  row engine's raise-on-division-by-zero behavior;
- output rows materialize as exact records and project through the
  row engine's projection code (s3select/fallback.py), so formatted
  output is byte-identical to the oracle;
- aggregates accumulate vectorized (COUNT/SUM/AVG via masked
  reductions with a LEFT-FOLD cumsum so float rounding matches the
  row engine's sequential ``total += n``; MIN/MAX recover the exact
  python-typed winner by re-evaluating the single winning row).

Set ``MINIO_SELECT_ENGINE=row`` to pin the row oracle (the bench's
paired runs and the differential suite use it).
"""

from __future__ import annotations

import os

import numpy as np

from . import fallback, sql
from .columnar import csv_column_batches, parquet_column_batches
from .compile import CompileError, Plan, lower

ROW = "row"
COLUMNAR = "columnar"


class Unsupported(Exception):
    """No exact columnar lowering — the row oracle serves the query."""


def engine_mode() -> str:
    return os.environ.get("MINIO_SELECT_ENGINE", "").strip().lower()


def referenced_columns(query: sql.Query) -> set[str] | None:
    """Top-level column names the query touches, or None when it
    needs every column (SELECT *, bare-alias Star, nested paths)."""
    if query.projections is None:
        return None
    names: set[str] = set()
    nodes: list = [p.expr for p in query.projections]
    if query.where is not None:
        nodes.append(query.where)
    nodes.extend(a.arg for a in query.aggregates
                 if a.arg is not None)
    while nodes:
        node = nodes.pop()
        if isinstance(node, sql.Star):
            return None
        if isinstance(node, sql.Col):
            if not node.path or not isinstance(node.path[0], str):
                return None
            names.add(node.path[0])
            continue
        for attr in ("left", "right", "inner", "value", "lo", "hi",
                     "pattern", "arg"):
            child = getattr(node, attr, None)
            if isinstance(child, sql.Node):
                nodes.append(child)
        for child in getattr(node, "options", ()) or ():
            nodes.append(child)
        for child in getattr(node, "args", ()) or ():
            nodes.append(child)
    return names


def scan(query: sql.Query, fmt: str, data: bytes,
         csv_cfg: dict | None) -> tuple[list, dict]:
    """Run the query columnar -> (rows, info) with info carrying
    processed bytes / scanned rows / fallback-row count.  Raises
    Unsupported when the row oracle must serve it instead."""
    if engine_mode() == ROW:
        raise Unsupported("engine pinned to row")
    if query.table_path:
        raise Unsupported("FROM S3Object.path input")
    if fmt == "Parquet":
        wanted = referenced_columns(query)
        batches = parquet_column_batches(data, wanted)
    elif fmt == "CSV":
        c = csv_cfg or {}
        batches = csv_column_batches(
            data,
            file_header_info=c.get("FileHeaderInfo", "NONE"),
            field_delimiter=c.get("FieldDelimiter", ","),
            record_delimiter=c.get("RecordDelimiter", "\n"),
            quote_character=c.get("QuoteCharacter", '"'),
            quote_escape_character=c.get("QuoteEscapeCharacter", '"'),
            comments=c.get("Comments", ""))
    else:
        raise Unsupported(f"format {fmt}")
    return _run(query, batches)


class _Scan:
    """Per-query compiled state, built against the first batch."""

    def __init__(self, query: sql.Query, first_batch):
        self.query = query
        self.where_plan = (Plan(lower(query.where, first_batch))
                           if query.where is not None else None)
        self.arg_plans = [
            (Plan(lower(a.arg, first_batch))
             if a.arg is not None else None)
            for a in query.aggregates]


def _run(query: sql.Query, batches) -> tuple[list, dict]:
    from ..ops import select_kernels
    info = {"processed": 0, "rows": 0, "fallback_rows": 0,
            "engine": COLUMNAR}
    out: list = []
    limit = query.limit
    scan_state: _Scan | None = None
    agg_states = ([sql._AggState(a.name) for a in query.aggregates]
                  if query.aggregates else None)

    for batch in batches:
        info["processed"] += batch.nbytes
        info["rows"] += batch.nrows
        if scan_state is None:
            # CompileError here = no exact lowering for this query:
            # Unsupported, the caller reruns on the row oracle.
            try:
                scan_state = _Scan(query, batch)
            except CompileError as e:
                raise Unsupported(str(e))
        row_tier = False
        ok = fb = None
        if scan_state.where_plan is not None:
            try:
                ok, fb = select_kernels.eval_predicate(
                    scan_state.where_plan, batch)
            except CompileError:
                # batch-shape drift (schema change, over-wide
                # strings): this one batch runs on the row tier
                row_tier = True
        else:
            ok = np.ones(batch.nrows, dtype=bool)
            fb = np.zeros(batch.nrows, dtype=bool)

        if agg_states is not None:
            done = _agg_batch(query, scan_state, agg_states, batch,
                              ok, fb, row_tier, info)
            if not done:
                _agg_batch_rows(query, agg_states, batch, info)
            continue

        if row_tier:
            if _emit_batch_rows(query, batch, out, limit, info):
                break
            continue
        if _emit_batch(query, batch, ok, fb, out, limit, info):
            break

    if agg_states is not None:
        # Swap Agg nodes for computed values and project once — the
        # row engine's own finalize (sql.execute's aggregate tail).
        for a, st in zip(query.aggregates, agg_states):
            a.eval = sql._AggValue(st.result()).eval  # type: ignore
        return [fallback.project_one(query, {})], info
    return out, info


# -- row emission ------------------------------------------------------------


def _project_cols(query: sql.Query, batch, sel: list) -> list | None:
    """Vectorized projection for plain-Col (or SELECT *) projections:
    output dicts build column-wise from exact py values, skipping the
    per-row projector entirely.  None when any projection needs the
    row projector (computed expressions, aliases over functions) —
    value-identical either way: Col.eval on a materialized record IS
    the cell's py value, and MISSING projects as None."""
    if query.projections is None:
        return batch.records(sel)
    names: list[str] = []
    refs: list[str] = []
    for i, p in enumerate(query.projections):
        e = p.expr
        if not isinstance(e, sql.Col) or len(e.path) != 1 or \
                not isinstance(e.path[0], str):
            return None
        names.append(p.alias or sql._projection_name(e, i))
        refs.append(e.path[0])
    idx = np.asarray(sel, dtype=np.int64)
    cols_vals = []
    from .columnar import _ABSENT
    for cname in refs:
        col = batch.col(cname)
        if col is None:
            cols_vals.append([None] * len(idx))
            continue
        vals = col.py_values(idx)
        if col.miss is not None:
            vals = [None if v is _ABSENT else v for v in vals]
        cols_vals.append(vals)
    out = []
    for row in zip(*cols_vals):
        rec: dict = {}
        for n, v in zip(names, row):
            rec[n] = v
        out.append(rec)
    return out


def _emit_batch(query, batch, ok, fb, out: list, limit, info) -> bool:
    """Vectorized selection with in-order fallback resolution.
    Returns True when LIMIT is satisfied."""
    room = None if limit is None else limit - len(out)
    if room is not None and room <= 0:
        return True
    if not fb.any():
        idx = np.flatnonzero(ok)
        if room is not None:
            idx = idx[:room]
        sel = [int(i) for i in idx]
    else:
        # Fallback rows resolve in row order, exactly when the oracle
        # would reach them (a division-by-zero past LIMIT stays
        # unraised) — but the ok-runs BETWEEN fallback positions stay
        # vectorized, so one poisoned cell in an 8M-row batch doesn't
        # degrade the whole emission to a per-row python walk.
        sel: list = []
        start = 0
        full = False

        def take_run(end: int | None) -> bool:
            nonlocal start
            seg = np.flatnonzero(ok[start:end])
            if start:
                seg = seg + start
            if room is not None and len(sel) + len(seg) >= room:
                sel.extend(int(i) for i in seg[:room - len(sel)])
                return True
            sel.extend(int(i) for i in seg)
            return False

        for f in np.flatnonzero(fb).tolist():
            if take_run(f):
                full = True
                break
            info["fallback_rows"] += 1
            if fallback.eval_where(query.where, batch.record(f)):
                sel.append(f)
                if room is not None and len(sel) >= room:
                    full = True
                    break
            start = f + 1
        if not full:
            take_run(None)
    fast = _project_cols(query, batch, sel)
    if fast is None:
        fast = fallback.project_rows(query, batch.records(sel))
    out.extend(fast)
    return limit is not None and len(out) >= limit


def _emit_batch_rows(query, batch, out: list, limit, info) -> bool:
    """Whole batch on the row tier (compiler refused its shape)."""
    for i in range(batch.nrows):
        info["fallback_rows"] += 1
        rec = batch.record(i)
        if not fallback.eval_where(query.where, rec):
            continue
        out.append(fallback.project_one(query, rec))
        if limit is not None and len(out) >= limit:
            return True
    return False


# -- aggregates --------------------------------------------------------------


def _agg_batch(query, scan_state, states, batch, ok, fb, row_tier,
               info) -> bool:
    """Vectorized aggregate accumulation for one batch; returns False
    when the batch needs the ORDER-EXACT row tier instead (fallback
    rows present, NaN min/max poisoning, arg fallback)."""
    if row_tier or fb is None or fb.any():
        return False
    from .compile import Ctx, _as_num
    ctx = Ctx(np, batch.nrows, batch=batch)
    updates = []
    for a, st, aplan in zip(query.aggregates, states,
                            scan_state.arg_plans):
        if aplan is None:   # COUNT(*)
            updates.append((st, "count*", int(ok.sum()), None, None))
            continue
        try:
            vv = aplan.root.run(ctx)
        except CompileError:
            return False
        if vv.fb is not None and (ok & vv.fb).any():
            return False
        valid = np.broadcast_to(np.asarray(vv.valid),
                                (batch.nrows,))
        if a.name == "count":
            # COUNT(expr) counts non-NULL values, parseable or not
            updates.append((st, "count*", int((ok & valid).sum()),
                            None, None))
            continue
        vals, nok, nfb, _ = _as_num(ctx, vv)
        if nfb is not None and (ok & nfb).any():
            return False
        m = ok & valid & np.broadcast_to(np.asarray(nok),
                                         (batch.nrows,))
        vals = np.broadcast_to(np.asarray(vals, dtype=np.float64),
                               (batch.nrows,))
        sel = vals[m]
        if a.name in ("min", "max") and len(sel) and \
                np.isnan(sel).any():
            # python min/max treat NaN positionally; row tier decides
            return False
        updates.append((st, a.name, int(m.sum()), sel,
                        (a, np.flatnonzero(m))))
    # All aggregates vectorizable: commit the batch's updates.
    for st, name, cnt, sel, winner in updates:
        if name == "count*":
            st.count += cnt
            continue
        st.count += cnt
        if sel is None or not len(sel):
            continue
        # LEFT-FOLD sum: cumsum is sequential, so float rounding
        # matches the row engine's per-row `total += n` exactly.
        st.total = float(np.cumsum(
            np.concatenate(([st.total], sel)))[-1])
        if name in ("min", "max"):
            a, idxs = winner
            j = int(idxs[np.argmin(sel) if name == "min"
                         else np.argmax(sel)])
            cand = sql._num(fallback.eval_arg(a.arg,
                                              batch.record(j)))
            if name == "min":
                st.minv = (cand if st.minv is None
                           else min(st.minv, cand))
            else:
                st.maxv = (cand if st.maxv is None
                           else max(st.maxv, cand))
    return True


def _agg_batch_rows(query, states, batch, info) -> None:
    """Order-exact aggregate accumulation on the row tier."""
    for i in range(batch.nrows):
        info["fallback_rows"] += 1
        rec = batch.record(i)
        if fallback.eval_where(query.where, rec):
            fallback.agg_update(query, states, rec)
