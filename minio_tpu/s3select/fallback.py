"""Row-engine tier of the columnar scan engine — the ONE module the
columnar scan path may evaluate ``sql.Node.eval`` per record from
(mtpu-lint R10 pins that boundary; everything else in the scan path
must stay vectorized).

Three jobs:

- **fallback rows**: rows the vectorized predicate marked undecidable
  (division by zero, exact-integer overflow, complex-LIKE prefilter
  survivors) re-evaluate here with full row semantics, in row order —
  including the row engine's raise-on-division-by-zero behavior;
- **row-tier batches**: a batch whose shape the compiler refused
  (schema drift, over-wide strings) runs entirely here;
- **projection**: output rows materialize through the row engine's
  projection semantics (alias naming, MISSING -> None), evaluated only
  for rows that PASSED the scan — at low selectivity this is the
  cheap tail of the query, and it is exactly oracle-identical.
"""

from __future__ import annotations

from . import sql
from .sql import MISSING


def eval_where(where: sql.Node | None, rec: dict) -> bool:
    """Row-engine WHERE semantics for one record (raises SQLError
    exactly where the row engine would, e.g. division by zero)."""
    return where is None or where.eval(rec) is True


def eval_arg(node: sql.Node, rec: dict):
    """Row-engine evaluation of one expression (aggregate args, the
    exact-typed min/max winner)."""
    return node.eval(rec)


def project_one(query: sql.Query, rec: dict):
    """The row engine's projection of one record (sql.execute's inner
    ``project``, verbatim semantics)."""
    if query.projections is None:
        return rec
    row = {}
    for i, p in enumerate(query.projections):
        v = p.expr.eval(rec)
        if v is MISSING:
            v = None
        row[p.alias or sql._projection_name(p.expr, i)] = v
    return row


def project_rows(query: sql.Query, recs: list[dict]) -> list[dict]:
    return [project_one(query, rec) for rec in recs]


def agg_update(query: sql.Query, states: list, rec: dict) -> None:
    """One record's aggregate accumulation (row engine semantics —
    COUNT(expr) skips NULL/MISSING, numeric coercion per value)."""
    for a, st in zip(query.aggregates, states):
        st.update(a.arg.eval(rec) if a.arg is not None else 1)
