"""AWS event-stream framing for SelectObjectContent responses (ref
pkg/s3select/message.go — same binary protocol: 4-byte total length,
4-byte headers length, 4-byte prelude CRC32, headers, payload, 4-byte
message CRC32; headers are (name-len, name, type=7, value-len, value)).
"""

from __future__ import annotations

import struct
import zlib


def _header(name: str, value: str) -> bytes:
    nb = name.encode()
    vb = value.encode()
    return (bytes([len(nb)]) + nb + b"\x07"
            + struct.pack(">H", len(vb)) + vb)


def encode_message(headers: list[tuple[str, str]], payload: bytes) -> bytes:
    hdr = b"".join(_header(n, v) for n, v in headers)
    total = 16 + len(hdr) + len(payload)
    prelude = struct.pack(">II", total, len(hdr))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))


def records_message(payload: bytes) -> bytes:
    return encode_message(
        [(":message-type", "event"), (":event-type", "Records"),
         (":content-type", "application/octet-stream")], payload)


def continuation_message() -> bytes:
    return encode_message(
        [(":message-type", "event"), (":event-type", "Cont")], b"")


def progress_message(scanned: int, processed: int, returned: int) -> bytes:
    xml = (f"<Progress><BytesScanned>{scanned}</BytesScanned>"
           f"<BytesProcessed>{processed}</BytesProcessed>"
           f"<BytesReturned>{returned}</BytesReturned></Progress>"
           ).encode()
    return encode_message(
        [(":message-type", "event"), (":event-type", "Progress"),
         (":content-type", "text/xml")], xml)


def stats_message(scanned: int, processed: int, returned: int) -> bytes:
    xml = (f"<Stats><BytesScanned>{scanned}</BytesScanned>"
           f"<BytesProcessed>{processed}</BytesProcessed>"
           f"<BytesReturned>{returned}</BytesReturned></Stats>").encode()
    return encode_message(
        [(":message-type", "event"), (":event-type", "Stats"),
         (":content-type", "text/xml")], xml)


def end_message() -> bytes:
    return encode_message(
        [(":message-type", "event"), (":event-type", "End")], b"")


def error_message(code: str, description: str) -> bytes:
    return encode_message(
        [(":message-type", "error"), (":error-code", code),
         (":error-message", description)], b"")


def decode_messages(stream: bytes) -> list[dict]:
    """Parse a response byte stream back into messages (client/test
    side). Returns [{"headers": {...}, "payload": bytes}, ...]."""
    out = []
    pos = 0
    while pos + 16 <= len(stream):
        total, hlen = struct.unpack_from(">II", stream, pos)
        (pcrc,) = struct.unpack_from(">I", stream, pos + 8)
        if zlib.crc32(stream[pos:pos + 8]) != pcrc:
            raise ValueError("prelude CRC mismatch")
        body = stream[pos:pos + total - 4]
        (mcrc,) = struct.unpack_from(">I", stream, pos + total - 4)
        if zlib.crc32(body) != mcrc:
            raise ValueError("message CRC mismatch")
        hdrs = {}
        hpos = pos + 12
        hend = hpos + hlen
        while hpos < hend:
            nlen = stream[hpos]
            hpos += 1
            name = stream[hpos:hpos + nlen].decode()
            hpos += nlen
            if stream[hpos] != 7:
                raise ValueError("unsupported header value type")
            hpos += 1
            (vlen,) = struct.unpack_from(">H", stream, hpos)
            hpos += 2
            hdrs[name] = stream[hpos:hpos + vlen].decode()
            hpos += vlen
        payload = stream[hend:pos + total - 4]
        out.append({"headers": hdrs, "payload": payload})
        pos += total
    return out
