"""Pure-Python Parquet reader + writer for S3 Select (ref
pkg/s3select/internal/parquet-go — the reference vendors an 18k-LoC
Go parquet stack; this is a from-scratch minimal implementation of the
same on-wire format).

Supported (flat schemas, the S3 Select case):
  - thrift compact protocol (the only parquet metadata encoding)
  - PLAIN encoding for BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY
  - RLE/bit-packed hybrid for definition levels and RLE_DICTIONARY
    indices (+ dictionary pages)
  - UNCOMPRESSED, SNAPPY (utils/snappy.py) and GZIP pages
  - OPTIONAL columns (nulls via def level 0)
Writer emits one row group, PLAIN, optionally snappy/gzip-compressed —
enough for tests and CONVERT-style tooling; reader handles
dictionary-encoded files too.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

MAGIC = b"PAR1"

# parquet.thrift Type
BOOLEAN, INT32, INT64, INT96, FLOAT, DOUBLE, BYTE_ARRAY, FIXED = range(8)
# Encoding
ENC_PLAIN, ENC_PLAIN_DICT, ENC_RLE = 0, 2, 3
ENC_RLE_DICT = 8
# Codec
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
_CODEC_NAMES = {None: CODEC_UNCOMPRESSED, "snappy": CODEC_SNAPPY,
                "gzip": CODEC_GZIP}
# Repetition
REQUIRED, OPTIONAL, REPEATED = 0, 1, 2
# PageType
PAGE_DATA, PAGE_INDEX, PAGE_DICT = 0, 1, 2


class ParquetError(Exception):
    pass


# ---------------------------------------------------------------------------
# thrift compact protocol
# ---------------------------------------------------------------------------

CT_STOP, CT_TRUE, CT_FALSE, CT_BYTE, CT_I16, CT_I32, CT_I64, \
    CT_DOUBLE, CT_BINARY, CT_LIST, CT_SET, CT_MAP, CT_STRUCT = range(13)


class TReader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_binary(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def skip(self, ctype: int) -> None:
        if ctype in (CT_TRUE, CT_FALSE):
            return
        if ctype == CT_BYTE:
            self.pos += 1
        elif ctype in (CT_I16, CT_I32, CT_I64):
            self.varint()
        elif ctype == CT_DOUBLE:
            self.pos += 8
        elif ctype == CT_BINARY:
            self.read_binary()
        elif ctype in (CT_LIST, CT_SET):
            size, et = self.list_header()
            for _ in range(size):
                self.skip(et)
        elif ctype == CT_MAP:
            size = self.varint()
            if size:
                kv = self.buf[self.pos]
                self.pos += 1
                for _ in range(size):
                    self.skip(kv >> 4)
                    self.skip(kv & 0x0F)
        elif ctype == CT_STRUCT:
            for _fid, ft in self.fields():
                self.skip(ft)
        else:
            raise ParquetError(f"bad thrift type {ctype}")

    def fields(self):
        """Yield (field_id, ctype) until STOP; caller must consume or
        skip each value (bools are consumed by the header itself and
        yielded as CT_TRUE/CT_FALSE)."""
        last = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            if b == 0:
                return
            delta = b >> 4
            ctype = b & 0x0F
            fid = (last + delta) if delta else self.zigzag()
            last = fid
            yield fid, ctype

    def list_header(self) -> tuple[int, int]:
        b = self.buf[self.pos]
        self.pos += 1
        size = b >> 4
        if size == 15:
            size = self.varint()
        return size, b & 0x0F


class TWriter:
    def __init__(self):
        self.out = bytearray()
        self._last: list[int] = [0]

    def varint(self, v: int) -> None:
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def zigzag(self, v: int) -> None:
        self.varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field(self, fid: int, ctype: int) -> None:
        delta = fid - self._last[-1]
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ctype)
        else:
            self.out.append(ctype)
            self.zigzag(fid)
        self._last[-1] = fid

    def i32(self, fid: int, v: int) -> None:
        self.field(fid, CT_I32)
        self.zigzag(v)

    def i64(self, fid: int, v: int) -> None:
        self.field(fid, CT_I64)
        self.zigzag(v)

    def binary(self, fid: int, v: bytes) -> None:
        self.field(fid, CT_BINARY)
        self.varint(len(v))
        self.out += v

    def begin_struct(self, fid: int) -> None:
        self.field(fid, CT_STRUCT)
        self._last.append(0)

    def end_struct(self) -> None:
        self.out.append(0)  # STOP
        self._last.pop()

    def list_begin(self, fid: int, etype: int, size: int) -> None:
        self.field(fid, CT_LIST)
        if size < 15:
            self.out.append((size << 4) | etype)
        else:
            self.out.append((15 << 4) | etype)
            self.varint(size)

    def stop(self) -> None:
        self.out.append(0)


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (levels + dictionary indices)
# ---------------------------------------------------------------------------


def rle_decode(data: bytes, bit_width: int, count: int) -> list[int]:
    out: list[int] = []
    r = TReader(data)
    byte_w = (bit_width + 7) // 8
    while len(out) < count and r.pos < len(data):
        header = r.varint()
        if header & 1:  # bit-packed groups
            groups = header >> 1
            n_bits = groups * 8 * bit_width
            raw = r.buf[r.pos:r.pos + (n_bits + 7) // 8]
            r.pos += (n_bits + 7) // 8
            acc = int.from_bytes(raw, "little")
            mask = (1 << bit_width) - 1
            for i in range(groups * 8):
                out.append((acc >> (i * bit_width)) & mask)
        else:  # RLE run
            run = header >> 1
            v = int.from_bytes(r.buf[r.pos:r.pos + byte_w], "little")
            r.pos += byte_w
            out.extend([v] * run)
    return out[:count]


def rle_encode(values, bit_width: int) -> bytes:
    """RLE runs only (adequate for levels and our writer).  Run
    boundaries found vectorized — an 8M-row all-present level column
    is one run, not 8M python comparisons."""
    import numpy as np
    arr = np.asarray(values, dtype=np.int64)
    if arr.size == 0:
        return b""
    change = np.flatnonzero(arr[1:] != arr[:-1])
    starts = np.concatenate(([0], change + 1))
    ends = np.concatenate((change + 1, [arr.size]))
    w = TWriter()
    byte_w = max(1, (bit_width + 7) // 8)
    for s, e in zip(starts.tolist(), ends.tolist()):
        w.varint((e - s) << 1)
        w.out += int(arr[s]).to_bytes(byte_w, "little")
    return bytes(w.out)


# ---------------------------------------------------------------------------
# schema model
# ---------------------------------------------------------------------------


@dataclass
class Column:
    name: str
    ptype: int               # parquet physical type
    optional: bool = True
    is_string: bool = False  # BYTE_ARRAY rendered as str


@dataclass
class _Chunk:
    ptype: int
    codec: int
    data_off: int = 0
    dict_off: int = 0
    num_values: int = 0
    total_uncompressed: int = 0
    path: list[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------


_NP_ENC_DTYPES = {INT32: "<i4", INT64: "<i8", FLOAT: "<f4",
                  DOUBLE: "<f8"}


def _plain_encode(ptype: int, values) -> bytes:
    import numpy as np
    if ptype == BOOLEAN:
        arr = np.asarray(values, dtype=bool)
        return np.packbits(arr, bitorder="little").tobytes()
    if ptype in _NP_ENC_DTYPES:
        # np serialization is byte-identical to the struct.pack loop
        # (explicit little-endian dtypes) and vectorized — the 256MiB
        # bench fixture writes in seconds, not minutes.  ndarray
        # inputs need EXPLICIT range/kind checks: np casts unsafely
        # where struct.pack raised (int64 2^40 -> int32 would wrap
        # silently, a float array would truncate to int).
        want = np.dtype(_NP_ENC_DTYPES[ptype])
        try:
            if isinstance(values, np.ndarray):
                arr = values
                if arr.dtype != want:
                    if want.kind == "i":
                        if arr.dtype.kind not in "iu":
                            raise ParquetError(
                                f"unencodable values: {arr.dtype} "
                                "array for an integer column")
                        info = np.iinfo(want)
                        if arr.size and (int(arr.min()) < info.min
                                         or int(arr.max())
                                         > info.max):
                            raise ParquetError(
                                "unencodable values: out of range "
                                f"for {want}")
                    elif want == np.dtype("<f4") \
                            and arr.dtype.kind == "f" and arr.size:
                        finite = arr[np.isfinite(arr)]
                        if finite.size and float(np.abs(finite).max()) \
                                > float(np.finfo(np.float32).max):
                            raise ParquetError(
                                "unencodable values: float too "
                                "large for FLOAT")
                    arr = arr.astype(want)
            else:
                # the direct constructor RAISES on out-of-range
                # python ints, matching the old struct.pack behavior
                arr = np.asarray(values, dtype=want)
        except (OverflowError, TypeError, ValueError) as e:
            raise ParquetError(f"unencodable values: {e}")
        return np.ascontiguousarray(arr).tobytes()
    if ptype == BYTE_ARRAY:
        out = bytearray()
        for v in values:
            b = v.encode() if isinstance(v, str) else bytes(v)
            out += struct.pack("<I", len(b)) + b
        return bytes(out)
    raise ParquetError(f"unsupported type {ptype}")


def write_parquet(columns: list[Column], rows: list[dict],
                  codec: str | None = None) -> bytes:
    """One row group, PLAIN; codec None | "snappy" | "gzip" compresses
    every data page (fixture generation + CONVERT tooling parity with
    the reference's compressed-page support)."""
    return write_parquet_columns(
        columns, {c.name: [r.get(c.name) for r in rows]
                  for c in columns}, len(rows), codec)


def write_parquet_columns(columns: list[Column], col_data: dict,
                          num_rows: int,
                          codec: str | None = None) -> bytes:
    """Column-major writer entry: ``col_data`` maps column name to a
    list (None = null) or an ndarray (no nulls) of ``num_rows``
    values.  The bench's 256MiB fixtures hand arrays straight through
    to the vectorized PLAIN encoder instead of transposing dict rows."""
    import numpy as np
    codec_id = _CODEC_NAMES[codec]
    out = bytearray(MAGIC)
    chunks = []
    for col in columns:
        raw = col_data[col.name]
        if len(raw) != num_rows:
            raise ParquetError(
                f"column {col.name}: {len(raw)} values, "
                f"expected {num_rows}")
        if isinstance(raw, np.ndarray):
            def_levels = (np.ones(num_rows, dtype=np.int64)
                          if col.optional else [])
            values = raw
        elif col.optional:
            def_levels = [0 if v is None else 1 for v in raw]
            values = [v for v in raw if v is not None]
        else:
            if any(v is None for v in raw):
                raise ParquetError(f"null in REQUIRED column "
                                   f"{col.name}")
            def_levels = []
            values = raw
        body = bytearray()
        if col.optional:
            lv = rle_encode(def_levels, 1)
            body += struct.pack("<I", len(lv)) + lv
        body += _plain_encode(col.ptype, values)

        uncomp_len = len(body)
        if codec_id == CODEC_SNAPPY:
            from ..utils import snappy
            body = bytearray(snappy.compress(bytes(body)))
        elif codec_id == CODEC_GZIP:
            import gzip as _gzip
            body = bytearray(_gzip.compress(bytes(body)))

        ph = TWriter()
        ph.i32(1, PAGE_DATA)
        ph.i32(2, uncomp_len)
        ph.i32(3, len(body))
        ph.begin_struct(5)  # DataPageHeader
        ph.i32(1, num_rows)
        ph.i32(2, ENC_PLAIN)
        ph.i32(3, ENC_RLE)  # def levels
        ph.i32(4, ENC_RLE)  # rep levels (absent for flat)
        ph.end_struct()
        ph.stop()

        off = len(out)
        out += bytes(ph.out) + body
        chunks.append((col, off, len(ph.out) + len(body), num_rows,
                       len(ph.out) + uncomp_len))

    # FileMetaData footer (thrift list items are bare structs encoded
    # back-to-back — no field headers between them).
    fm2 = TWriter()
    fm2.i32(1, 1)  # version
    fm2.list_begin(2, CT_STRUCT, len(columns) + 1)  # schema

    def schema_element(w, name, ptype=None, repetition=None,
                       num_children=None):
        w._last.append(0)
        if ptype is not None:
            w.i32(1, ptype)
        if repetition is not None:
            w.i32(3, repetition)
        w.binary(4, name.encode())
        if num_children is not None:
            w.i32(5, num_children)
        w.out.append(0)
        w._last.pop()

    schema_element(fm2, "schema", num_children=len(columns))
    for col in columns:
        schema_element(fm2, col.name, ptype=col.ptype,
                       repetition=OPTIONAL if col.optional
                       else REQUIRED)
    fm2.i64(3, num_rows)
    fm2.list_begin(4, CT_STRUCT, 1)  # row_groups
    # RowGroup struct (list item: no field header)
    fm2._last.append(0)
    fm2.list_begin(1, CT_STRUCT, len(columns))  # columns
    total = 0  # RowGroup.total_byte_size is UNCOMPRESSED per the spec
    for col, off, clen, nvals, uclen in chunks:
        total += uclen
        fm2._last.append(0)  # ColumnChunk
        fm2.i64(2, off)  # file_offset
        fm2.begin_struct(3)  # ColumnMetaData
        fm2.i32(1, col.ptype)
        fm2.list_begin(2, CT_I32, 1)
        fm2.zigzag(ENC_PLAIN)
        fm2.list_begin(3, CT_BINARY, 1)
        fm2.varint(len(col.name.encode()))
        fm2.out += col.name.encode()
        fm2.i32(4, codec_id)
        fm2.i64(5, nvals)
        fm2.i64(6, uclen)
        fm2.i64(7, clen)
        fm2.i64(9, off)  # data_page_offset
        fm2.end_struct()
        fm2.out.append(0)  # end ColumnChunk
        fm2._last.pop()
    fm2.i64(2, total)
    fm2.i64(3, num_rows)
    fm2.out.append(0)  # end RowGroup
    fm2._last.pop()
    fm2.stop()

    footer = bytes(fm2.out)
    out += footer
    out += struct.pack("<I", len(footer))
    out += MAGIC
    return bytes(out)


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------


def _read_schema(r: TReader) -> list[Column]:
    size, _ = r.list_header()
    cols: list[Column] = []
    for i in range(size):
        name = ""
        ptype = None
        rep = REQUIRED
        nchild = 0
        for fid, ct in r.fields():
            if fid == 1:
                ptype = r.zigzag()
            elif fid == 3:
                rep = r.zigzag()
            elif fid == 4:
                name = r.read_binary().decode()
            elif fid == 5:
                nchild = r.zigzag()
            else:
                r.skip(ct)
        if i == 0:
            continue  # root
        if nchild:
            raise ParquetError(
                f"nested schema (group {name!r}) not supported — "
                "flat schemas only")
        if rep == REPEATED:
            raise ParquetError(
                f"REPEATED column {name!r} not supported")
        cols.append(Column(name=name, ptype=ptype,
                           optional=(rep == OPTIONAL),
                           is_string=(ptype == BYTE_ARRAY)))
    return cols


def _read_column_meta(r: TReader) -> _Chunk:
    ch = _Chunk(ptype=0, codec=0)
    for fid, ct in r.fields():
        if fid == 1:
            ch.ptype = r.zigzag()
        elif fid == 3:
            size, _ = r.list_header()
            ch.path = [r.read_binary().decode() for _ in range(size)]
        elif fid == 4:
            ch.codec = r.zigzag()
        elif fid == 5:
            ch.num_values = r.zigzag()
        elif fid == 6:
            ch.total_uncompressed = r.zigzag()
        elif fid == 9:
            ch.data_off = r.zigzag()
        elif fid == 11:
            ch.dict_off = r.zigzag()
        else:
            r.skip(ct)
    return ch


def _plain_decode(ptype: int, buf: bytes, pos: int, n: int,
                  as_str: bool) -> tuple[list, int]:
    if ptype == BOOLEAN:
        acc = int.from_bytes(buf[pos:pos + (n + 7) // 8], "little")
        return [bool((acc >> i) & 1) for i in range(n)], \
            pos + (n + 7) // 8
    if ptype in (INT32, FLOAT):
        fmt = "<i" if ptype == INT32 else "<f"
        vals = [struct.unpack_from(fmt, buf, pos + 4 * i)[0]
                for i in range(n)]
        return vals, pos + 4 * n
    if ptype in (INT64, DOUBLE):
        fmt = "<q" if ptype == INT64 else "<d"
        vals = [struct.unpack_from(fmt, buf, pos + 8 * i)[0]
                for i in range(n)]
        return vals, pos + 8 * n
    if ptype == BYTE_ARRAY:
        vals = []
        for _ in range(n):
            ln = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
            v = buf[pos:pos + ln]
            pos += ln
            vals.append(v.decode("utf-8", "replace") if as_str else v)
        return vals, pos
    raise ParquetError(f"unsupported physical type {ptype}")


def _read_page_header(r: TReader) -> dict:
    h = {"type": None, "comp_size": 0, "uncomp_size": 0,
         "num_values": 0, "encoding": ENC_PLAIN,
         "def_encoding": ENC_RLE}
    for fid, ct in r.fields():
        if fid == 1:
            h["type"] = r.zigzag()
        elif fid == 2:
            h["uncomp_size"] = r.zigzag()
        elif fid == 3:
            h["comp_size"] = r.zigzag()
        elif fid in (5, 7):  # DataPageHeader / DictionaryPageHeader
            for f2, c2 in r.fields():
                if f2 == 1:
                    h["num_values"] = r.zigzag()
                elif f2 == 2:
                    h["encoding"] = r.zigzag()
                elif f2 == 3:
                    h["def_encoding"] = r.zigzag()
                else:
                    r.skip(c2)
        else:
            r.skip(ct)
    return h


def _decompress(codec: int, data: bytes, uncomp: int) -> bytes:
    """Page decompression: UNCOMPRESSED, SNAPPY (raw block format,
    utils/snappy.py) and GZIP — the codecs the reference's vendored
    parquet stack supports (pkg/s3select/internal/parquet-go; real-
    world parquet is nearly always snappy)."""
    if codec == CODEC_UNCOMPRESSED:
        return data
    if codec == CODEC_SNAPPY:
        from ..utils import snappy
        try:
            out = snappy.decompress(data)
        except snappy.SnappyError as e:
            raise ParquetError(f"bad snappy page: {e}")
    elif codec == CODEC_GZIP:
        import zlib
        try:
            out = zlib.decompress(data, 47)  # gzip or zlib wrapper
        except zlib.error as e:
            raise ParquetError(f"bad gzip page: {e}")
    else:
        raise ParquetError(f"unsupported parquet codec {codec}")
    if len(out) != uncomp:
        raise ParquetError(
            f"page inflated to {len(out)}, header says {uncomp}")
    return out


def read_parquet(data: bytes) -> tuple[list[Column], list[dict]]:
    """Full decode of a flat parquet file -> (schema columns, rows).
    Any malformed input surfaces as ParquetError."""
    try:
        return _read_parquet(data)
    except ParquetError:
        raise
    except (IndexError, ValueError, struct.error, KeyError,
            OverflowError, UnicodeDecodeError) as e:
        raise ParquetError(f"malformed parquet: "
                           f"{type(e).__name__}: {e}")


def read_footer(data: bytes) -> tuple[list[Column], list[dict]]:
    """Parse the FileMetaData footer: (schema columns, row groups as
    {"chunks": [_Chunk], "num_rows": int}).  Shared by the row reader
    and the columnar batch reader (s3select/columnar.py); per-group
    row counts fall back to the widest chunk's num_values for writers
    that omit RowGroup.num_rows."""
    if data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ParquetError("not a parquet file")
    flen = struct.unpack("<I", data[-8:-4])[0]
    r = TReader(data, len(data) - 8 - flen)

    cols: list[Column] = []
    num_rows = 0
    groups: list[dict] = []
    for fid, ct in r.fields():
        if fid == 2:
            cols = _read_schema(r)
        elif fid == 3:
            num_rows = r.zigzag()
        elif fid == 4:
            size, _ = r.list_header()
            for _ in range(size):
                chunks: list[_Chunk] = []
                g_rows = 0
                for f2, c2 in r.fields():
                    if f2 == 1:
                        n, _ = r.list_header()
                        for _ in range(n):
                            chunk = None
                            for f3, c3 in r.fields():
                                if f3 == 3:
                                    chunk = _read_column_meta(r)
                                else:
                                    r.skip(c3)
                            if chunk is not None:
                                chunks.append(chunk)
                    elif f2 == 3:
                        g_rows = r.zigzag()
                    else:
                        r.skip(c2)
                if not g_rows:
                    g_rows = max((c.num_values for c in chunks),
                                 default=0)
                groups.append({"chunks": chunks, "num_rows": g_rows})
        else:
            r.skip(ct)
    if num_rows and not groups:
        raise ParquetError("row count without row groups")
    return cols, groups


def uncompressed_size(data: bytes) -> int:
    """Total uncompressed bytes across all column chunks — the honest
    BytesProcessed for a whole-file (row engine) Parquet scan."""
    _, groups = read_footer(data)
    return sum(c.total_uncompressed for g in groups
               for c in g["chunks"])


def _read_parquet(data: bytes) -> tuple[list[Column], list[dict]]:
    cols, groups = read_footer(data)
    num_rows = sum(g["num_rows"] for g in groups)
    row_groups = [g["chunks"] for g in groups]

    by_name = {c.name: c for c in cols}
    columns_data: dict[str, list] = {c.name: [] for c in cols}
    for chunks in row_groups:
        for ch in chunks:
            name = ch.path[-1] if ch.path else ""
            col = by_name.get(name)
            if col is None:
                continue
            columns_data[name].extend(
                _read_chunk_values(data, ch, col))
    rows = []
    for i in range(num_rows):
        rows.append({c.name: (columns_data[c.name][i]
                              if i < len(columns_data[c.name]) else None)
                     for c in cols})
    return cols, rows


def _read_chunk_values(data: bytes, ch: _Chunk, col: Column) -> list:
    out: list = []
    dictionary: list | None = None
    pos = ch.dict_off or ch.data_off
    remaining = ch.num_values
    while remaining > 0:
        r = TReader(data, pos)
        h = _read_page_header(r)
        body = _decompress(
            ch.codec, data[r.pos:r.pos + h["comp_size"]],
            h["uncomp_size"])
        pos = r.pos + h["comp_size"]
        if h["type"] == PAGE_DICT:
            dictionary, _ = _plain_decode(
                col.ptype, body, 0, h["num_values"], col.is_string)
            continue
        if h["type"] == PAGE_INDEX:
            continue  # index pages carry no values
        if h["type"] != PAGE_DATA:
            raise ParquetError(
                f"unsupported page type {h['type']} "
                "(data page v1 only)")
        n = h["num_values"]
        bpos = 0
        if col.optional:
            lv_len = struct.unpack_from("<I", body, 0)[0]
            levels = rle_decode(body[4:4 + lv_len], 1, n)
            bpos = 4 + lv_len
        else:
            levels = [1] * n
        present = sum(levels)
        if h["encoding"] in (ENC_RLE_DICT, ENC_PLAIN_DICT):
            if dictionary is None:
                raise ParquetError("dictionary page missing")
            bit_width = body[bpos]
            idx = rle_decode(body[bpos + 1:], bit_width, present)
            vals = [dictionary[i] for i in idx]
        else:
            vals, _ = _plain_decode(col.ptype, body, bpos, present,
                                    col.is_string)
        it = iter(vals)
        out.extend(next(it) if lv else None for lv in levels)
        remaining -= n
    return out


def parquet_records(data: bytes):
    """Yield dict records for the SQL engine (ref the parquet reader
    feeding pkg/s3select/select.go)."""
    _, rows = read_parquet(data)
    yield from rows


# ---------------------------------------------------------------------------
# columnar (vectorized) decode — the scan engine's fast path
# ---------------------------------------------------------------------------


def rle_decode_np(data: bytes, bit_width: int,
                  count: int) -> "np.ndarray":
    """Vectorized RLE/bit-packed hybrid decode -> int64 array.
    Byte-identical to rle_decode (tested); bit-packed groups unpack
    through np.unpackbits instead of a per-value python loop."""
    import numpy as np
    out = np.empty(count, dtype=np.int64)
    filled = 0
    r = TReader(data)
    byte_w = (bit_width + 7) // 8
    weights = (np.int64(1) << np.arange(max(bit_width, 1),
                                        dtype=np.int64))
    while filled < count and r.pos < len(data):
        header = r.varint()
        if header & 1:  # bit-packed groups
            groups = header >> 1
            nvals = groups * 8
            nbytes = (nvals * bit_width + 7) // 8
            raw = np.frombuffer(r.buf, np.uint8, nbytes, r.pos)
            r.pos += nbytes
            if bit_width == 0:
                vals = np.zeros(nvals, dtype=np.int64)
            else:
                bits = np.unpackbits(raw, bitorder="little")
                usable = (bits.size // bit_width) * bit_width
                vals = (bits[:usable].astype(np.int64)
                        .reshape(-1, bit_width) @ weights)
            take = min(nvals, count - filled, len(vals))
            out[filled:filled + take] = vals[:take]
            filled += take
        else:  # RLE run
            run = header >> 1
            v = int.from_bytes(bytes(r.buf[r.pos:r.pos + byte_w]),
                               "little")
            r.pos += byte_w
            take = min(run, count - filled)
            out[filled:filled + take] = v
            filled += take
    return out[:filled]


def _plain_decode_np(ptype: int, buf: bytes, pos: int, n: int,
                     as_str: bool):
    """PLAIN page decode, vectorized: numeric types come back as a
    zero-copy np view over the page body (the row reader's per-value
    struct.unpack loop is the single hottest line of the old scan)."""
    import numpy as np
    if ptype == BOOLEAN:
        raw = np.frombuffer(buf, np.uint8, (n + 7) // 8, pos)
        return np.unpackbits(raw, bitorder="little")[:n].astype(bool)
    if ptype in (INT32, FLOAT):
        return np.frombuffer(buf, "<i4" if ptype == INT32 else "<f4",
                             n, pos)
    if ptype in (INT64, DOUBLE):
        return np.frombuffer(buf, "<i8" if ptype == INT64 else "<f8",
                             n, pos)
    if ptype == BYTE_ARRAY:
        vals, _ = _plain_decode(ptype, buf, pos, n, as_str)
        return vals
    raise ParquetError(f"unsupported physical type {ptype}")


def decode_chunk_np(data: bytes, ch: _Chunk, col: Column) -> dict:
    """One column chunk -> typed arrays for the scan engine:
    {"values": ndarray|list|None, "null": bool ndarray|None,
     "codes": int ndarray|None, "dict": list|None,
     "nrows": int, "unc_bytes": int}.

    Dictionary-encoded BYTE_ARRAY pages keep their (codes, dictionary)
    form — a string predicate then evaluates once per DISTINCT value
    and gathers, instead of once per row."""
    import numpy as np
    pos = ch.dict_off or ch.data_off
    remaining = ch.num_values
    parts: list[tuple] = []   # ("vals", arr|list) | ("codes", arr)
    nullparts: list = []
    dictionary = None
    unc = 0
    while remaining > 0:
        r = TReader(data, pos)
        h = _read_page_header(r)
        body = _decompress(
            ch.codec, data[r.pos:r.pos + h["comp_size"]],
            h["uncomp_size"])
        pos = r.pos + h["comp_size"]
        if h["type"] == PAGE_DICT:
            dictionary = _plain_decode_np(
                col.ptype, body, 0, h["num_values"], col.is_string)
            unc += h["uncomp_size"]
            continue
        if h["type"] == PAGE_INDEX:
            continue
        if h["type"] != PAGE_DATA:
            raise ParquetError(
                f"unsupported page type {h['type']} "
                "(data page v1 only)")
        unc += h["uncomp_size"]
        n = h["num_values"]
        bpos = 0
        present_mask = None
        if col.optional:
            lv_len = struct.unpack_from("<I", body, 0)[0]
            levels = rle_decode_np(body[4:4 + lv_len], 1, n)
            if len(levels) < n:
                raise ParquetError("truncated definition levels")
            present_mask = levels.astype(bool)
            present = int(present_mask.sum())
            bpos = 4 + lv_len
            nullparts.append(~present_mask)
        else:
            present = n
            nullparts.append(np.zeros(n, dtype=bool))
        if h["encoding"] in (ENC_RLE_DICT, ENC_PLAIN_DICT):
            if dictionary is None:
                raise ParquetError("dictionary page missing")
            bw = body[bpos]
            idx = rle_decode_np(body[bpos + 1:], bw, present)
            if len(idx) < present:
                raise ParquetError("truncated dictionary indices")
            if col.is_string:
                codes = np.full(n, -1, dtype=np.int64)
                if present_mask is None:
                    codes[:] = idx
                else:
                    codes[present_mask] = idx
                parts.append(("codes", codes))
            else:
                darr = np.asarray(dictionary)
                parts.append(("vals", _scatter_np(
                    darr[idx], n, present_mask)))
        else:
            vals = _plain_decode_np(col.ptype, body, bpos, present,
                                    col.is_string)
            if col.is_string:
                if present_mask is None:
                    parts.append(("vals", vals))
                else:
                    full = [""] * n
                    it = iter(vals)
                    for i, p in enumerate(present_mask.tolist()):
                        if p:
                            full[i] = next(it)
                    parts.append(("vals", full))
            else:
                parts.append(("vals", _scatter_np(
                    np.asarray(vals), n, present_mask)))
        remaining -= n
    null = None
    if col.optional and nullparts:
        null = (nullparts[0] if len(nullparts) == 1
                else np.concatenate(nullparts))
        if not null.any():
            null = None
    out = {"values": None, "null": null, "codes": None, "dict": None,
           "nrows": ch.num_values, "unc_bytes": unc}
    kinds = {k for k, _ in parts}
    if kinds == {"codes"}:
        codes = (parts[0][1] if len(parts) == 1
                 else np.concatenate([p[1] for p in parts]))
        out["codes"] = codes
        out["dict"] = list(dictionary)
        return out
    vals_list: list = []
    for kind, p in parts:
        if kind == "codes":
            # Mixed plain/dict pages in one chunk: resolve codes so
            # the chunk presents one uniform values sequence.
            p = [dictionary[i] if i >= 0 else "" for i in p.tolist()]
        vals_list.append(p)
    if not vals_list:
        out["values"] = [] if col.is_string else np.zeros(0)
        return out
    if col.is_string:
        merged: list = []
        for p in vals_list:
            merged.extend(p if isinstance(p, list) else list(p))
        out["values"] = merged
    else:
        out["values"] = (vals_list[0] if len(vals_list) == 1
                         else np.concatenate(vals_list))
    return out


def _scatter_np(vals, n: int, mask):
    import numpy as np
    if mask is None:
        return vals
    out = np.zeros(n, dtype=vals.dtype)
    out[mask] = vals
    return out
