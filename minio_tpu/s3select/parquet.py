"""Pure-Python Parquet reader + writer for S3 Select (ref
pkg/s3select/internal/parquet-go — the reference vendors an 18k-LoC
Go parquet stack; this is a from-scratch minimal implementation of the
same on-wire format).

Supported (flat schemas, the S3 Select case):
  - thrift compact protocol (the only parquet metadata encoding)
  - PLAIN encoding for BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY
  - RLE/bit-packed hybrid for definition levels and RLE_DICTIONARY
    indices (+ dictionary pages)
  - UNCOMPRESSED, SNAPPY (utils/snappy.py) and GZIP pages
  - OPTIONAL columns (nulls via def level 0)
Writer emits one row group, PLAIN, optionally snappy/gzip-compressed —
enough for tests and CONVERT-style tooling; reader handles
dictionary-encoded files too.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

MAGIC = b"PAR1"

# parquet.thrift Type
BOOLEAN, INT32, INT64, INT96, FLOAT, DOUBLE, BYTE_ARRAY, FIXED = range(8)
# Encoding
ENC_PLAIN, ENC_PLAIN_DICT, ENC_RLE = 0, 2, 3
ENC_RLE_DICT = 8
# Codec
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
_CODEC_NAMES = {None: CODEC_UNCOMPRESSED, "snappy": CODEC_SNAPPY,
                "gzip": CODEC_GZIP}
# Repetition
REQUIRED, OPTIONAL, REPEATED = 0, 1, 2
# PageType
PAGE_DATA, PAGE_INDEX, PAGE_DICT = 0, 1, 2


class ParquetError(Exception):
    pass


# ---------------------------------------------------------------------------
# thrift compact protocol
# ---------------------------------------------------------------------------

CT_STOP, CT_TRUE, CT_FALSE, CT_BYTE, CT_I16, CT_I32, CT_I64, \
    CT_DOUBLE, CT_BINARY, CT_LIST, CT_SET, CT_MAP, CT_STRUCT = range(13)


class TReader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_binary(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def skip(self, ctype: int) -> None:
        if ctype in (CT_TRUE, CT_FALSE):
            return
        if ctype == CT_BYTE:
            self.pos += 1
        elif ctype in (CT_I16, CT_I32, CT_I64):
            self.varint()
        elif ctype == CT_DOUBLE:
            self.pos += 8
        elif ctype == CT_BINARY:
            self.read_binary()
        elif ctype in (CT_LIST, CT_SET):
            size, et = self.list_header()
            for _ in range(size):
                self.skip(et)
        elif ctype == CT_MAP:
            size = self.varint()
            if size:
                kv = self.buf[self.pos]
                self.pos += 1
                for _ in range(size):
                    self.skip(kv >> 4)
                    self.skip(kv & 0x0F)
        elif ctype == CT_STRUCT:
            for _fid, ft in self.fields():
                self.skip(ft)
        else:
            raise ParquetError(f"bad thrift type {ctype}")

    def fields(self):
        """Yield (field_id, ctype) until STOP; caller must consume or
        skip each value (bools are consumed by the header itself and
        yielded as CT_TRUE/CT_FALSE)."""
        last = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            if b == 0:
                return
            delta = b >> 4
            ctype = b & 0x0F
            fid = (last + delta) if delta else self.zigzag()
            last = fid
            yield fid, ctype

    def list_header(self) -> tuple[int, int]:
        b = self.buf[self.pos]
        self.pos += 1
        size = b >> 4
        if size == 15:
            size = self.varint()
        return size, b & 0x0F


class TWriter:
    def __init__(self):
        self.out = bytearray()
        self._last: list[int] = [0]

    def varint(self, v: int) -> None:
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def zigzag(self, v: int) -> None:
        self.varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field(self, fid: int, ctype: int) -> None:
        delta = fid - self._last[-1]
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ctype)
        else:
            self.out.append(ctype)
            self.zigzag(fid)
        self._last[-1] = fid

    def i32(self, fid: int, v: int) -> None:
        self.field(fid, CT_I32)
        self.zigzag(v)

    def i64(self, fid: int, v: int) -> None:
        self.field(fid, CT_I64)
        self.zigzag(v)

    def binary(self, fid: int, v: bytes) -> None:
        self.field(fid, CT_BINARY)
        self.varint(len(v))
        self.out += v

    def begin_struct(self, fid: int) -> None:
        self.field(fid, CT_STRUCT)
        self._last.append(0)

    def end_struct(self) -> None:
        self.out.append(0)  # STOP
        self._last.pop()

    def list_begin(self, fid: int, etype: int, size: int) -> None:
        self.field(fid, CT_LIST)
        if size < 15:
            self.out.append((size << 4) | etype)
        else:
            self.out.append((15 << 4) | etype)
            self.varint(size)

    def stop(self) -> None:
        self.out.append(0)


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (levels + dictionary indices)
# ---------------------------------------------------------------------------


def rle_decode(data: bytes, bit_width: int, count: int) -> list[int]:
    out: list[int] = []
    r = TReader(data)
    byte_w = (bit_width + 7) // 8
    while len(out) < count and r.pos < len(data):
        header = r.varint()
        if header & 1:  # bit-packed groups
            groups = header >> 1
            n_bits = groups * 8 * bit_width
            raw = r.buf[r.pos:r.pos + (n_bits + 7) // 8]
            r.pos += (n_bits + 7) // 8
            acc = int.from_bytes(raw, "little")
            mask = (1 << bit_width) - 1
            for i in range(groups * 8):
                out.append((acc >> (i * bit_width)) & mask)
        else:  # RLE run
            run = header >> 1
            v = int.from_bytes(r.buf[r.pos:r.pos + byte_w], "little")
            r.pos += byte_w
            out.extend([v] * run)
    return out[:count]


def rle_encode(values: list[int], bit_width: int) -> bytes:
    """RLE runs only (adequate for levels and our writer)."""
    w = TWriter()
    byte_w = max(1, (bit_width + 7) // 8)
    i = 0
    while i < len(values):
        j = i
        while j < len(values) and values[j] == values[i]:
            j += 1
        w.varint((j - i) << 1)
        w.out += values[i].to_bytes(byte_w, "little")
        i = j
    return bytes(w.out)


# ---------------------------------------------------------------------------
# schema model
# ---------------------------------------------------------------------------


@dataclass
class Column:
    name: str
    ptype: int               # parquet physical type
    optional: bool = True
    is_string: bool = False  # BYTE_ARRAY rendered as str


@dataclass
class _Chunk:
    ptype: int
    codec: int
    data_off: int = 0
    dict_off: int = 0
    num_values: int = 0
    path: list[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------


def _plain_encode(ptype: int, values: list) -> bytes:
    if ptype == BOOLEAN:
        acc = 0
        for i, v in enumerate(values):
            if v:
                acc |= 1 << i
        return acc.to_bytes((len(values) + 7) // 8, "little")
    if ptype == INT32:
        return struct.pack(f"<{len(values)}i", *values)
    if ptype == INT64:
        return struct.pack(f"<{len(values)}q", *values)
    if ptype == FLOAT:
        return struct.pack(f"<{len(values)}f", *values)
    if ptype == DOUBLE:
        return struct.pack(f"<{len(values)}d", *values)
    if ptype == BYTE_ARRAY:
        out = bytearray()
        for v in values:
            b = v.encode() if isinstance(v, str) else bytes(v)
            out += struct.pack("<I", len(b)) + b
        return bytes(out)
    raise ParquetError(f"unsupported type {ptype}")


def write_parquet(columns: list[Column], rows: list[dict],
                  codec: str | None = None) -> bytes:
    """One row group, PLAIN; codec None | "snappy" | "gzip" compresses
    every data page (fixture generation + CONVERT tooling parity with
    the reference's compressed-page support)."""
    codec_id = _CODEC_NAMES[codec]
    out = bytearray(MAGIC)
    chunks = []
    for col in columns:
        raw = [r.get(col.name) for r in rows]
        if col.optional:
            def_levels = [0 if v is None else 1 for v in raw]
            values = [v for v in raw if v is not None]
        else:
            if any(v is None for v in raw):
                raise ParquetError(f"null in REQUIRED column "
                                   f"{col.name}")
            def_levels = []
            values = raw
        body = bytearray()
        if col.optional:
            lv = rle_encode(def_levels, 1)
            body += struct.pack("<I", len(lv)) + lv
        body += _plain_encode(col.ptype, values)

        uncomp_len = len(body)
        if codec_id == CODEC_SNAPPY:
            from ..utils import snappy
            body = bytearray(snappy.compress(bytes(body)))
        elif codec_id == CODEC_GZIP:
            import gzip as _gzip
            body = bytearray(_gzip.compress(bytes(body)))

        ph = TWriter()
        ph.i32(1, PAGE_DATA)
        ph.i32(2, uncomp_len)
        ph.i32(3, len(body))
        ph.begin_struct(5)  # DataPageHeader
        ph.i32(1, len(rows))
        ph.i32(2, ENC_PLAIN)
        ph.i32(3, ENC_RLE)  # def levels
        ph.i32(4, ENC_RLE)  # rep levels (absent for flat)
        ph.end_struct()
        ph.stop()

        off = len(out)
        out += bytes(ph.out) + body
        chunks.append((col, off, len(ph.out) + len(body), len(rows),
                       len(ph.out) + uncomp_len))

    # FileMetaData footer (thrift list items are bare structs encoded
    # back-to-back — no field headers between them).
    fm2 = TWriter()
    fm2.i32(1, 1)  # version
    fm2.list_begin(2, CT_STRUCT, len(columns) + 1)  # schema

    def schema_element(w, name, ptype=None, repetition=None,
                       num_children=None):
        w._last.append(0)
        if ptype is not None:
            w.i32(1, ptype)
        if repetition is not None:
            w.i32(3, repetition)
        w.binary(4, name.encode())
        if num_children is not None:
            w.i32(5, num_children)
        w.out.append(0)
        w._last.pop()

    schema_element(fm2, "schema", num_children=len(columns))
    for col in columns:
        schema_element(fm2, col.name, ptype=col.ptype,
                       repetition=OPTIONAL if col.optional
                       else REQUIRED)
    fm2.i64(3, len(rows))
    fm2.list_begin(4, CT_STRUCT, 1)  # row_groups
    # RowGroup struct (list item: no field header)
    fm2._last.append(0)
    fm2.list_begin(1, CT_STRUCT, len(columns))  # columns
    total = 0  # RowGroup.total_byte_size is UNCOMPRESSED per the spec
    for col, off, clen, nvals, uclen in chunks:
        total += uclen
        fm2._last.append(0)  # ColumnChunk
        fm2.i64(2, off)  # file_offset
        fm2.begin_struct(3)  # ColumnMetaData
        fm2.i32(1, col.ptype)
        fm2.list_begin(2, CT_I32, 1)
        fm2.zigzag(ENC_PLAIN)
        fm2.list_begin(3, CT_BINARY, 1)
        fm2.varint(len(col.name.encode()))
        fm2.out += col.name.encode()
        fm2.i32(4, codec_id)
        fm2.i64(5, nvals)
        fm2.i64(6, uclen)
        fm2.i64(7, clen)
        fm2.i64(9, off)  # data_page_offset
        fm2.end_struct()
        fm2.out.append(0)  # end ColumnChunk
        fm2._last.pop()
    fm2.i64(2, total)
    fm2.i64(3, len(rows))
    fm2.out.append(0)  # end RowGroup
    fm2._last.pop()
    fm2.stop()

    footer = bytes(fm2.out)
    out += footer
    out += struct.pack("<I", len(footer))
    out += MAGIC
    return bytes(out)


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------


def _read_schema(r: TReader) -> list[Column]:
    size, _ = r.list_header()
    cols: list[Column] = []
    for i in range(size):
        name = ""
        ptype = None
        rep = REQUIRED
        nchild = 0
        for fid, ct in r.fields():
            if fid == 1:
                ptype = r.zigzag()
            elif fid == 3:
                rep = r.zigzag()
            elif fid == 4:
                name = r.read_binary().decode()
            elif fid == 5:
                nchild = r.zigzag()
            else:
                r.skip(ct)
        if i == 0:
            continue  # root
        if nchild:
            raise ParquetError(
                f"nested schema (group {name!r}) not supported — "
                "flat schemas only")
        if rep == REPEATED:
            raise ParquetError(
                f"REPEATED column {name!r} not supported")
        cols.append(Column(name=name, ptype=ptype,
                           optional=(rep == OPTIONAL),
                           is_string=(ptype == BYTE_ARRAY)))
    return cols


def _read_column_meta(r: TReader) -> _Chunk:
    ch = _Chunk(ptype=0, codec=0)
    for fid, ct in r.fields():
        if fid == 1:
            ch.ptype = r.zigzag()
        elif fid == 3:
            size, _ = r.list_header()
            ch.path = [r.read_binary().decode() for _ in range(size)]
        elif fid == 4:
            ch.codec = r.zigzag()
        elif fid == 5:
            ch.num_values = r.zigzag()
        elif fid == 9:
            ch.data_off = r.zigzag()
        elif fid == 11:
            ch.dict_off = r.zigzag()
        else:
            r.skip(ct)
    return ch


def _plain_decode(ptype: int, buf: bytes, pos: int, n: int,
                  as_str: bool) -> tuple[list, int]:
    if ptype == BOOLEAN:
        acc = int.from_bytes(buf[pos:pos + (n + 7) // 8], "little")
        return [bool((acc >> i) & 1) for i in range(n)], \
            pos + (n + 7) // 8
    if ptype in (INT32, FLOAT):
        fmt = "<i" if ptype == INT32 else "<f"
        vals = [struct.unpack_from(fmt, buf, pos + 4 * i)[0]
                for i in range(n)]
        return vals, pos + 4 * n
    if ptype in (INT64, DOUBLE):
        fmt = "<q" if ptype == INT64 else "<d"
        vals = [struct.unpack_from(fmt, buf, pos + 8 * i)[0]
                for i in range(n)]
        return vals, pos + 8 * n
    if ptype == BYTE_ARRAY:
        vals = []
        for _ in range(n):
            ln = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
            v = buf[pos:pos + ln]
            pos += ln
            vals.append(v.decode("utf-8", "replace") if as_str else v)
        return vals, pos
    raise ParquetError(f"unsupported physical type {ptype}")


def _read_page_header(r: TReader) -> dict:
    h = {"type": None, "comp_size": 0, "uncomp_size": 0,
         "num_values": 0, "encoding": ENC_PLAIN,
         "def_encoding": ENC_RLE}
    for fid, ct in r.fields():
        if fid == 1:
            h["type"] = r.zigzag()
        elif fid == 2:
            h["uncomp_size"] = r.zigzag()
        elif fid == 3:
            h["comp_size"] = r.zigzag()
        elif fid in (5, 7):  # DataPageHeader / DictionaryPageHeader
            for f2, c2 in r.fields():
                if f2 == 1:
                    h["num_values"] = r.zigzag()
                elif f2 == 2:
                    h["encoding"] = r.zigzag()
                elif f2 == 3:
                    h["def_encoding"] = r.zigzag()
                else:
                    r.skip(c2)
        else:
            r.skip(ct)
    return h


def _decompress(codec: int, data: bytes, uncomp: int) -> bytes:
    """Page decompression: UNCOMPRESSED, SNAPPY (raw block format,
    utils/snappy.py) and GZIP — the codecs the reference's vendored
    parquet stack supports (pkg/s3select/internal/parquet-go; real-
    world parquet is nearly always snappy)."""
    if codec == CODEC_UNCOMPRESSED:
        return data
    if codec == CODEC_SNAPPY:
        from ..utils import snappy
        try:
            out = snappy.decompress(data)
        except snappy.SnappyError as e:
            raise ParquetError(f"bad snappy page: {e}")
    elif codec == CODEC_GZIP:
        import zlib
        try:
            out = zlib.decompress(data, 47)  # gzip or zlib wrapper
        except zlib.error as e:
            raise ParquetError(f"bad gzip page: {e}")
    else:
        raise ParquetError(f"unsupported parquet codec {codec}")
    if len(out) != uncomp:
        raise ParquetError(
            f"page inflated to {len(out)}, header says {uncomp}")
    return out


def read_parquet(data: bytes) -> tuple[list[Column], list[dict]]:
    """Full decode of a flat parquet file -> (schema columns, rows).
    Any malformed input surfaces as ParquetError."""
    try:
        return _read_parquet(data)
    except ParquetError:
        raise
    except (IndexError, ValueError, struct.error, KeyError,
            OverflowError, UnicodeDecodeError) as e:
        raise ParquetError(f"malformed parquet: "
                           f"{type(e).__name__}: {e}")


def _read_parquet(data: bytes) -> tuple[list[Column], list[dict]]:
    if data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ParquetError("not a parquet file")
    flen = struct.unpack("<I", data[-8:-4])[0]
    r = TReader(data, len(data) - 8 - flen)

    cols: list[Column] = []
    num_rows = 0
    row_groups: list[list[_Chunk]] = []
    for fid, ct in r.fields():
        if fid == 2:
            cols = _read_schema(r)
        elif fid == 3:
            num_rows = r.zigzag()
        elif fid == 4:
            size, _ = r.list_header()
            for _ in range(size):
                chunks: list[_Chunk] = []
                for f2, c2 in r.fields():
                    if f2 == 1:
                        n, _ = r.list_header()
                        for _ in range(n):
                            chunk = None
                            for f3, c3 in r.fields():
                                if f3 == 3:
                                    chunk = _read_column_meta(r)
                                else:
                                    r.skip(c3)
                            if chunk is not None:
                                chunks.append(chunk)
                    else:
                        r.skip(c2)
                row_groups.append(chunks)
        else:
            r.skip(ct)

    by_name = {c.name: c for c in cols}
    columns_data: dict[str, list] = {c.name: [] for c in cols}
    for chunks in row_groups:
        for ch in chunks:
            name = ch.path[-1] if ch.path else ""
            col = by_name.get(name)
            if col is None:
                continue
            columns_data[name].extend(
                _read_chunk_values(data, ch, col))
    rows = []
    for i in range(num_rows):
        rows.append({c.name: (columns_data[c.name][i]
                              if i < len(columns_data[c.name]) else None)
                     for c in cols})
    return cols, rows


def _read_chunk_values(data: bytes, ch: _Chunk, col: Column) -> list:
    out: list = []
    dictionary: list | None = None
    pos = ch.dict_off or ch.data_off
    remaining = ch.num_values
    while remaining > 0:
        r = TReader(data, pos)
        h = _read_page_header(r)
        body = _decompress(
            ch.codec, data[r.pos:r.pos + h["comp_size"]],
            h["uncomp_size"])
        pos = r.pos + h["comp_size"]
        if h["type"] == PAGE_DICT:
            dictionary, _ = _plain_decode(
                col.ptype, body, 0, h["num_values"], col.is_string)
            continue
        if h["type"] == PAGE_INDEX:
            continue  # index pages carry no values
        if h["type"] != PAGE_DATA:
            raise ParquetError(
                f"unsupported page type {h['type']} "
                "(data page v1 only)")
        n = h["num_values"]
        bpos = 0
        if col.optional:
            lv_len = struct.unpack_from("<I", body, 0)[0]
            levels = rle_decode(body[4:4 + lv_len], 1, n)
            bpos = 4 + lv_len
        else:
            levels = [1] * n
        present = sum(levels)
        if h["encoding"] in (ENC_RLE_DICT, ENC_PLAIN_DICT):
            if dictionary is None:
                raise ParquetError("dictionary page missing")
            bit_width = body[bpos]
            idx = rle_decode(body[bpos + 1:], bit_width, present)
            vals = [dictionary[i] for i in idx]
        else:
            vals, _ = _plain_decode(col.ptype, body, bpos, present,
                                    col.is_string)
        it = iter(vals)
        out.extend(next(it) if lv else None for lv in levels)
        remaining -= n
    return out


def parquet_records(data: bytes):
    """Yield dict records for the SQL engine (ref the parquet reader
    feeding pkg/s3select/select.go)."""
    _, rows = read_parquet(data)
    yield from rows
