"""Input readers for S3 Select: CSV and JSON (DOCUMENT/LINES), with
NONE/GZIP/BZIP2 source compression (ref pkg/s3select/csv, pkg/s3select/json;
the reference's simdjson fast path is a SIMD host concern — here the
readers are plain streaming parsers).
"""

from __future__ import annotations

import bz2
import csv as _csv
import gzip
import io
import json

from .sql import SQLError


def decompress(data: bytes, compression: str) -> bytes:
    c = (compression or "NONE").upper()
    if c in ("NONE", ""):
        return data
    try:
        if c == "GZIP":
            return gzip.decompress(data)
        if c == "BZIP2":
            return bz2.decompress(data)
    except OSError as e:
        raise SQLError(f"bad compressed input: {e}")
    raise SQLError(f"unsupported CompressionType {compression}")


CSV_CHUNK_BYTES = 1 << 20  # parse unit (ref csv/reader.go chunked parse)


def _csv_chunks(text: str, quote: str, chunk_chars: int):
    """Split text into record-boundary-aligned chunks, never inside a
    quoted field: a boundary newline must leave an EVEN number of
    quote characters behind it (the same invariant the reference's
    chunked reader maintains, ref pkg/s3select/csv/reader.go
    startReaders splitting on line boundaries)."""
    n = len(text)
    start = 0
    parity_odd = False
    while start < n:
        if start + chunk_chars >= n:
            yield text[start:]
            return
        end = text.rfind("\n", start, start + chunk_chars)
        if end < 0:
            end = text.find("\n", start + chunk_chars)
            if end < 0:
                yield text[start:]
                return
        # Quote parity across the candidate chunk decides whether the
        # newline is a real record boundary; odd parity -> extend to
        # the next newline until parity evens out.
        if quote:
            while True:
                odd = (text.count(quote, start, end + 1) % 2 == 1)
                if not (parity_odd ^ odd):
                    break
                nxt = text.find("\n", end + 1)
                if nxt < 0:
                    yield text[start:]
                    return
                end = nxt
        yield text[start:end + 1]
        start = end + 1


def csv_records(data: bytes, *, file_header_info: str = "NONE",
                field_delimiter: str = ",", record_delimiter: str = "\n",
                quote_character: str = '"',
                quote_escape_character: str = '"',
                comments: str = ""):
    """Yield dict records from CSV bytes, parsed CHUNK BY CHUNK
    (ref pkg/s3select/csv/reader.go — the reference splits the input
    on record boundaries and parses blocks on a worker pool; under the
    GIL a thread pool cannot speed a CPU-bound parse, so this build
    gets its throughput from the same chunking plus a C-split fast
    path for quote-free chunks — ~3x over csv.reader — and bounded
    memory / early termination for LIMIT queries).

    FileHeaderInfo (ref csv/args.go):
      NONE   -> columns _1.._N
      IGNORE -> first row skipped, columns _1.._N
      USE    -> first row names the columns
    """
    text = data.decode("utf-8", errors="replace")
    if record_delimiter and record_delimiter != "\n":
        text = text.replace(record_delimiter, "\n")
    delim = field_delimiter or ","
    quote = quote_character or '"'
    escape = quote_escape_character or quote

    header: list[str] | None = None
    mode = (file_header_info or "NONE").upper()
    first = True

    def emit(row):
        nonlocal header, first
        if not row:
            return None
        if comments and row[0].startswith(comments):
            return None
        if first:
            first = False
            if mode == "USE":
                header = [h.strip() for h in row]
                return None
            if mode == "IGNORE":
                return None
        if header is not None:
            return {header[i] if i < len(header) else f"_{i + 1}": v
                    for i, v in enumerate(row)}
        return {f"_{i + 1}": v for i, v in enumerate(row)}

    # Chunk-boundary parity counting is only sound under the
    # doublequote convention (escape == quote, the S3 default and the
    # overwhelmingly common case): a DISTINCT escape character can make
    # an escaped quote flip the parity. Fall back to one whole-input
    # chunk there — correctness over chunking.
    chunk_chars = (CSV_CHUNK_BYTES if escape == quote
                   else max(len(text), 1))
    for chunk in _csv_chunks(text, quote, chunk_chars):
        if quote not in chunk and escape not in chunk:
            # Quote-free chunk: str.split (C) beats the csv state
            # machine ~3x and cannot mis-parse — nothing is quoted.
            for line in chunk.split("\n"):
                if line.endswith("\r"):
                    line = line[:-1]  # CRLF terminator, like csv.reader
                if not line:
                    continue
                rec = emit(line.split(delim))
                if rec is not None:
                    yield rec
            continue
        reader = _csv.reader(
            io.StringIO(chunk), delimiter=delim, quotechar=quote,
            doublequote=(escape == quote),
            escapechar=(None if escape == quote else escape))
        for row in reader:
            rec = emit(row)
            if rec is not None:
                yield rec


def json_records(data: bytes, *, json_type: str = "LINES"):
    """Yield dict records from JSON bytes.

    LINES: one JSON value per line (blank lines skipped); DOCUMENT: one
    value, or a top-level array = one record per element (ref
    pkg/s3select/json/reader.go).
    """
    t = (json_type or "LINES").upper()
    if t == "DOCUMENT":
        try:
            doc = json.loads(data)
        except ValueError as e:
            raise SQLError(f"invalid JSON document: {e}")
        if isinstance(doc, list):
            for el in doc:
                yield el if isinstance(el, dict) else {"_1": el}
        else:
            yield doc if isinstance(doc, dict) else {"_1": doc}
        return
    if t != "LINES":
        raise SQLError(f"unsupported JSON Type {json_type}")
    dec = json.JSONDecoder()
    text = data.decode("utf-8", errors="replace")
    pos, n = 0, len(text)
    while pos < n:
        while pos < n and text[pos] in " \t\r\n":
            pos += 1
        if pos >= n:
            break
        try:
            obj, end = dec.raw_decode(text, pos)
        except ValueError as e:
            raise SQLError(f"invalid JSON record at {pos}: {e}")
        pos = end
        yield obj if isinstance(obj, dict) else {"_1": obj}


def format_csv(rows: list[dict], *, field_delimiter: str = ",",
               record_delimiter: str = "\n",
               quote_character: str = '"') -> bytes:
    buf = io.StringIO()
    w = _csv.writer(buf, delimiter=field_delimiter or ",",
                    quotechar=quote_character or '"',
                    lineterminator=record_delimiter or "\n")
    for row in rows:
        w.writerow([_csv_value(v) for v in row.values()])
    return buf.getvalue().encode()


def _csv_value(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, (dict, list)):
        return json.dumps(v, separators=(",", ":"))
    return str(v)


def format_json(rows: list[dict], *,
                record_delimiter: str = "\n") -> bytes:
    # One encoder for the whole result set: json.dumps with
    # non-default args constructs a JSONEncoder PER CALL, which at
    # millions of output rows is ~30% of the serialization wall.
    encode = json.JSONEncoder(separators=(",", ":"),
                              default=str).encode
    out = [encode(row) for row in rows]
    rd = record_delimiter or "\n"
    return (rd.join(out) + rd).encode() if out else b""
