"""Input readers for S3 Select: CSV and JSON (DOCUMENT/LINES), with
NONE/GZIP/BZIP2 source compression (ref pkg/s3select/csv, pkg/s3select/json;
the reference's simdjson fast path is a SIMD host concern — here the
readers are plain streaming parsers).
"""

from __future__ import annotations

import bz2
import csv as _csv
import gzip
import io
import json

from .sql import SQLError


def decompress(data: bytes, compression: str) -> bytes:
    c = (compression or "NONE").upper()
    if c in ("NONE", ""):
        return data
    try:
        if c == "GZIP":
            return gzip.decompress(data)
        if c == "BZIP2":
            return bz2.decompress(data)
    except OSError as e:
        raise SQLError(f"bad compressed input: {e}")
    raise SQLError(f"unsupported CompressionType {compression}")


def csv_records(data: bytes, *, file_header_info: str = "NONE",
                field_delimiter: str = ",", record_delimiter: str = "\n",
                quote_character: str = '"',
                quote_escape_character: str = '"',
                comments: str = ""):
    """Yield dict records from CSV bytes.

    FileHeaderInfo (ref csv/args.go):
      NONE   -> columns _1.._N
      IGNORE -> first row skipped, columns _1.._N
      USE    -> first row names the columns
    """
    text = data.decode("utf-8", errors="replace")
    if record_delimiter and record_delimiter != "\n":
        text = text.replace(record_delimiter, "\n")
    src = io.StringIO(text)
    reader = _csv.reader(
        src, delimiter=field_delimiter or ",",
        quotechar=quote_character or '"',
        doublequote=(quote_escape_character == quote_character),
        escapechar=(None if quote_escape_character == quote_character
                    else quote_escape_character))
    header: list[str] | None = None
    mode = (file_header_info or "NONE").upper()
    first = True
    for row in reader:
        if not row:
            continue
        if comments and row[0].startswith(comments):
            continue
        if first:
            first = False
            if mode == "USE":
                header = [h.strip() for h in row]
                continue
            if mode == "IGNORE":
                continue
        if header is not None:
            rec = {header[i] if i < len(header) else f"_{i + 1}": v
                   for i, v in enumerate(row)}
        else:
            rec = {f"_{i + 1}": v for i, v in enumerate(row)}
        yield rec


def json_records(data: bytes, *, json_type: str = "LINES"):
    """Yield dict records from JSON bytes.

    LINES: one JSON value per line (blank lines skipped); DOCUMENT: one
    value, or a top-level array = one record per element (ref
    pkg/s3select/json/reader.go).
    """
    t = (json_type or "LINES").upper()
    if t == "DOCUMENT":
        try:
            doc = json.loads(data)
        except ValueError as e:
            raise SQLError(f"invalid JSON document: {e}")
        if isinstance(doc, list):
            for el in doc:
                yield el if isinstance(el, dict) else {"_1": el}
        else:
            yield doc if isinstance(doc, dict) else {"_1": doc}
        return
    if t != "LINES":
        raise SQLError(f"unsupported JSON Type {json_type}")
    dec = json.JSONDecoder()
    text = data.decode("utf-8", errors="replace")
    pos, n = 0, len(text)
    while pos < n:
        while pos < n and text[pos] in " \t\r\n":
            pos += 1
        if pos >= n:
            break
        try:
            obj, end = dec.raw_decode(text, pos)
        except ValueError as e:
            raise SQLError(f"invalid JSON record at {pos}: {e}")
        pos = end
        yield obj if isinstance(obj, dict) else {"_1": obj}


def format_csv(rows: list[dict], *, field_delimiter: str = ",",
               record_delimiter: str = "\n",
               quote_character: str = '"') -> bytes:
    buf = io.StringIO()
    w = _csv.writer(buf, delimiter=field_delimiter or ",",
                    quotechar=quote_character or '"',
                    lineterminator=record_delimiter or "\n")
    for row in rows:
        w.writerow([_csv_value(v) for v in row.values()])
    return buf.getvalue().encode()


def _csv_value(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, (dict, list)):
        return json.dumps(v, separators=(",", ":"))
    return str(v)


def format_json(rows: list[dict], *,
                record_delimiter: str = "\n") -> bytes:
    out = []
    for row in rows:
        out.append(json.dumps(row, separators=(",", ":"),
                              default=str))
    rd = record_delimiter or "\n"
    return (rd.join(out) + rd).encode() if out else b""
