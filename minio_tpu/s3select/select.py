"""SelectObjectContent orchestrator: request XML -> readers -> SQL ->
event-stream response (ref S3Select, pkg/s3select/select.go:208,
Evaluate:398, NewS3Select:541)."""

from __future__ import annotations

import xml.etree.ElementTree as ET

from . import message, readers, sql


class S3SelectError(Exception):
    def __init__(self, code: str, desc: str):
        super().__init__(desc)
        self.code = code
        self.description = desc


def _strip_ns(root: ET.Element) -> ET.Element:
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def parse_request(body: bytes) -> dict:
    """Parse SelectObjectContentRequest XML into a plain dict
    (ref ParseSelectParameters)."""
    try:
        root = _strip_ns(ET.fromstring(body))
    except ET.ParseError as e:
        raise S3SelectError("MalformedXML", f"invalid request XML: {e}")
    if root.tag != "SelectObjectContentRequest":
        raise S3SelectError("MalformedXML",
                            f"unexpected root {root.tag}")
    expr = root.findtext("Expression") or ""
    etype = (root.findtext("ExpressionType") or "SQL").upper()
    if etype != "SQL":
        raise S3SelectError("InvalidExpressionType",
                            f"unsupported ExpressionType {etype}")
    req = {"expression": expr, "input": {}, "output": {},
           "progress": False}
    ins = root.find("InputSerialization")
    if ins is None:
        raise S3SelectError("MalformedXML", "missing InputSerialization")
    req["input"]["compression"] = ins.findtext("CompressionType") or "NONE"
    csv_el = ins.find("CSV")
    json_el = ins.find("JSON")
    parquet_el = ins.find("Parquet")
    if csv_el is not None:
        req["input"]["format"] = "CSV"
        req["input"]["csv"] = {
            "FileHeaderInfo": csv_el.findtext("FileHeaderInfo") or "NONE",
            "RecordDelimiter": csv_el.findtext("RecordDelimiter") or "\n",
            "FieldDelimiter": csv_el.findtext("FieldDelimiter") or ",",
            "QuoteCharacter": csv_el.findtext("QuoteCharacter") or '"',
            "QuoteEscapeCharacter":
                csv_el.findtext("QuoteEscapeCharacter") or '"',
            "Comments": csv_el.findtext("Comments") or "",
        }
    elif json_el is not None:
        req["input"]["format"] = "JSON"
        req["input"]["json"] = {
            "Type": json_el.findtext("Type") or "LINES"}
    elif parquet_el is not None:
        req["input"]["format"] = "Parquet"
        if req["input"]["compression"] not in ("", "NONE"):
            raise S3SelectError(
                "InvalidRequestParameter",
                "CompressionType must be NONE for Parquet input")
    else:
        raise S3SelectError(
            "MalformedXML",
            "InputSerialization needs CSV, JSON or Parquet")
    outs = root.find("OutputSerialization")
    if outs is None:
        raise S3SelectError("MalformedXML",
                            "missing OutputSerialization")
    ocsv = outs.find("CSV")
    ojson = outs.find("JSON")
    if ocsv is not None:
        req["output"]["format"] = "CSV"
        req["output"]["csv"] = {
            "RecordDelimiter": ocsv.findtext("RecordDelimiter") or "\n",
            "FieldDelimiter": ocsv.findtext("FieldDelimiter") or ",",
            "QuoteCharacter": ocsv.findtext("QuoteCharacter") or '"',
        }
    elif ojson is not None:
        req["output"]["format"] = "JSON"
        req["output"]["json"] = {
            "RecordDelimiter": ojson.findtext("RecordDelimiter") or "\n"}
    else:
        raise S3SelectError("MalformedXML",
                            "OutputSerialization needs CSV or JSON")
    prog = root.find("RequestProgress")
    if prog is not None and (prog.findtext("Enabled") or ""
                             ).lower() == "true":
        req["progress"] = True
    return req


def _execute(req: dict, data: bytes) -> tuple[list, int, str, int]:
    """Run the query -> (rows, processed_bytes, engine, fallback_rows).

    The columnar scan engine (s3select/engine.py) serves CSV/Parquet
    when it can lower the query EXACTLY; the row engine stays the
    oracle and the fallback.  processed_bytes is what the scan
    actually decoded (for a pruned Parquet scan: only the referenced
    columns' uncompressed pages) — the honest BytesProcessed."""
    from . import engine as scan_engine
    fmt = req["input"]["format"]
    if fmt == "Parquet":
        # Parquet is never additionally whole-object compressed
        # (pages carry their own codec, ref S3 API).
        import struct as _pstruct

        from .parquet import (ParquetError, parquet_records,
                              read_footer, uncompressed_size)
        try:
            query = sql.parse(req["expression"])
        except sql.SQLError:
            # Row-path error precedence: invalid DATA answers
            # InvalidDataSource before invalid SQL answers
            # InvalidQuery.  Footer-level validation only — a FULL
            # row decode here (what the row engine does) would burn
            # ~40s of CPU per bad query against a 256MiB object, an
            # error path any client can repeat; deep page corruption
            # paired with invalid SQL answers InvalidQuery instead,
            # a divergence only doubly-invalid requests can see.
            try:
                read_footer(data)
            except ParquetError as e:
                raise S3SelectError("InvalidDataSource", str(e))
            except (IndexError, ValueError, _pstruct.error, KeyError,
                    OverflowError, UnicodeDecodeError) as e:
                raise S3SelectError(
                    "InvalidDataSource",
                    f"malformed parquet: {type(e).__name__}: {e}")
            raise
        try:
            try:
                rows, info = scan_engine.scan(query, "Parquet", data,
                                              None)
                return (rows, info["processed"], info["engine"],
                        info["fallback_rows"])
            except scan_engine.Unsupported:
                pass
            except sql.SQLError:
                raise
            except (IndexError, ValueError, _pstruct.error, KeyError,
                    OverflowError, UnicodeDecodeError) as e:
                # The columnar decoder hits malformed input OUTSIDE
                # read_parquet's catch-all; same classification.
                raise S3SelectError(
                    "InvalidDataSource",
                    f"malformed parquet: {type(e).__name__}: {e}")
            records = list(parquet_records(data))
        except ParquetError as e:
            raise S3SelectError("InvalidDataSource", str(e))
        rows = sql.execute(query, records)
        try:
            processed = uncompressed_size(data)
        except ParquetError:
            processed = len(data)
        return rows, processed, "row", 0
    data = readers.decompress(data, req["input"].get("compression"))
    if fmt == "CSV":
        c = req["input"]["csv"]
        query = sql.parse(req["expression"])
        try:
            rows, info = scan_engine.scan(query, "CSV", data, c)
            return (rows, info["processed"], info["engine"],
                    info["fallback_rows"])
        except scan_engine.Unsupported:
            pass
        records = readers.csv_records(
            data,
            file_header_info=c["FileHeaderInfo"],
            field_delimiter=c["FieldDelimiter"],
            record_delimiter=c["RecordDelimiter"],
            quote_character=c["QuoteCharacter"],
            quote_escape_character=c["QuoteEscapeCharacter"],
            comments=c["Comments"])
    else:
        records = readers.json_records(
            data, json_type=req["input"]["json"]["Type"])
        query = sql.parse(req["expression"])
    rows = sql.execute(query, records)
    return rows, len(data), "row", 0


def _record_metrics(scanned: int, processed: int, returned: int,
                    engine: str, fallback_rows: int) -> None:
    from ..obs.metrics2 import METRICS2
    METRICS2.inc("minio_tpu_v2_select_scanned_bytes_total", None,
                 scanned)
    if processed:
        METRICS2.inc("minio_tpu_v2_select_processed_bytes_total",
                     None, processed)
    if returned:
        METRICS2.inc("minio_tpu_v2_select_returned_bytes_total",
                     None, returned)
    METRICS2.inc("minio_tpu_v2_select_requests_total",
                 {"engine": engine})
    if fallback_rows:
        METRICS2.inc("minio_tpu_v2_select_fallback_rows_total", None,
                     fallback_rows)


def run_select(req: dict, data: bytes) -> bytes:
    """Execute a parsed select request over object bytes; returns the
    full event-stream response body.  Progress/Stats events carry the
    REAL scan volume: BytesScanned = object bytes read, BytesProcessed
    = bytes the scan decoded (columnar Parquet scans prune to the
    referenced columns), BytesReturned = payload bytes."""
    from ..obs.span import TRACER
    raw_len = len(data)
    processed = 0
    engine_used = "row"
    fallback_rows = 0
    try:
        # The span covers scan AND output serialization: a big result
        # set's formatting is scan work product, and a scan-bound
        # request must blame `scan-kernel`, not client-stream.
        with TRACER.span("select.scan") as span:
            rows, processed, engine_used, fallback_rows = \
                _execute(req, data)
            if span is not None and getattr(span, "tags", None) \
                    is not None:
                span.tags["engine"] = engine_used
                span.tags["rows"] = len(rows)
            if req["output"]["format"] == "CSV":
                o = req["output"]["csv"]
                payload = readers.format_csv(
                    rows, field_delimiter=o["FieldDelimiter"],
                    record_delimiter=o["RecordDelimiter"],
                    quote_character=o["QuoteCharacter"])
            else:
                payload = readers.format_json(
                    rows, record_delimiter=req["output"]["json"][
                        "RecordDelimiter"])
    except sql.SQLError as e:
        _record_metrics(raw_len, processed, 0, "error", fallback_rows)
        return message.error_message("InvalidQuery", str(e))
    except S3SelectError as e:
        _record_metrics(raw_len, processed, 0, "error", fallback_rows)
        return message.error_message(e.code, e.description)

    _record_metrics(raw_len, processed, len(payload), engine_used,
                    fallback_rows)
    frames = []
    if req.get("progress"):
        frames.append(message.progress_message(raw_len, processed,
                                               len(payload)))
    for i in range(0, len(payload), 1 << 20):
        frames.append(message.records_message(payload[i:i + (1 << 20)]))
    frames.append(message.stats_message(raw_len, processed,
                                        len(payload)))
    frames.append(message.end_message())
    return b"".join(frames)
