"""SelectObjectContent orchestrator: request XML -> readers -> SQL ->
event-stream response (ref S3Select, pkg/s3select/select.go:208,
Evaluate:398, NewS3Select:541)."""

from __future__ import annotations

import xml.etree.ElementTree as ET

from . import message, readers, sql


class S3SelectError(Exception):
    def __init__(self, code: str, desc: str):
        super().__init__(desc)
        self.code = code
        self.description = desc


def _strip_ns(root: ET.Element) -> ET.Element:
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def parse_request(body: bytes) -> dict:
    """Parse SelectObjectContentRequest XML into a plain dict
    (ref ParseSelectParameters)."""
    try:
        root = _strip_ns(ET.fromstring(body))
    except ET.ParseError as e:
        raise S3SelectError("MalformedXML", f"invalid request XML: {e}")
    if root.tag != "SelectObjectContentRequest":
        raise S3SelectError("MalformedXML",
                            f"unexpected root {root.tag}")
    expr = root.findtext("Expression") or ""
    etype = (root.findtext("ExpressionType") or "SQL").upper()
    if etype != "SQL":
        raise S3SelectError("InvalidExpressionType",
                            f"unsupported ExpressionType {etype}")
    req = {"expression": expr, "input": {}, "output": {},
           "progress": False}
    ins = root.find("InputSerialization")
    if ins is None:
        raise S3SelectError("MalformedXML", "missing InputSerialization")
    req["input"]["compression"] = ins.findtext("CompressionType") or "NONE"
    csv_el = ins.find("CSV")
    json_el = ins.find("JSON")
    parquet_el = ins.find("Parquet")
    if csv_el is not None:
        req["input"]["format"] = "CSV"
        req["input"]["csv"] = {
            "FileHeaderInfo": csv_el.findtext("FileHeaderInfo") or "NONE",
            "RecordDelimiter": csv_el.findtext("RecordDelimiter") or "\n",
            "FieldDelimiter": csv_el.findtext("FieldDelimiter") or ",",
            "QuoteCharacter": csv_el.findtext("QuoteCharacter") or '"',
            "QuoteEscapeCharacter":
                csv_el.findtext("QuoteEscapeCharacter") or '"',
            "Comments": csv_el.findtext("Comments") or "",
        }
    elif json_el is not None:
        req["input"]["format"] = "JSON"
        req["input"]["json"] = {
            "Type": json_el.findtext("Type") or "LINES"}
    elif parquet_el is not None:
        req["input"]["format"] = "Parquet"
        if req["input"]["compression"] not in ("", "NONE"):
            raise S3SelectError(
                "InvalidRequestParameter",
                "CompressionType must be NONE for Parquet input")
    else:
        raise S3SelectError(
            "MalformedXML",
            "InputSerialization needs CSV, JSON or Parquet")
    outs = root.find("OutputSerialization")
    if outs is None:
        raise S3SelectError("MalformedXML",
                            "missing OutputSerialization")
    ocsv = outs.find("CSV")
    ojson = outs.find("JSON")
    if ocsv is not None:
        req["output"]["format"] = "CSV"
        req["output"]["csv"] = {
            "RecordDelimiter": ocsv.findtext("RecordDelimiter") or "\n",
            "FieldDelimiter": ocsv.findtext("FieldDelimiter") or ",",
            "QuoteCharacter": ocsv.findtext("QuoteCharacter") or '"',
        }
    elif ojson is not None:
        req["output"]["format"] = "JSON"
        req["output"]["json"] = {
            "RecordDelimiter": ojson.findtext("RecordDelimiter") or "\n"}
    else:
        raise S3SelectError("MalformedXML",
                            "OutputSerialization needs CSV or JSON")
    prog = root.find("RequestProgress")
    if prog is not None and (prog.findtext("Enabled") or ""
                             ).lower() == "true":
        req["progress"] = True
    return req


def run_select(req: dict, data: bytes) -> bytes:
    """Execute a parsed select request over object bytes; returns the
    full event-stream response body."""
    raw_len = len(data)
    try:
        fmt = req["input"]["format"]
        if fmt == "Parquet":
            # Parquet is never additionally whole-object compressed
            # (pages carry their own codec, ref S3 API).
            from .parquet import ParquetError, parquet_records
            try:
                records = list(parquet_records(data))
            except ParquetError as e:
                raise S3SelectError("InvalidDataSource", str(e))
        else:
            data = readers.decompress(data,
                                      req["input"].get("compression"))
        if fmt == "CSV":
            c = req["input"]["csv"]
            records = readers.csv_records(
                data,
                file_header_info=c["FileHeaderInfo"],
                field_delimiter=c["FieldDelimiter"],
                record_delimiter=c["RecordDelimiter"],
                quote_character=c["QuoteCharacter"],
                quote_escape_character=c["QuoteEscapeCharacter"],
                comments=c["Comments"])
        elif fmt == "JSON":
            records = readers.json_records(
                data, json_type=req["input"]["json"]["Type"])
        query = sql.parse(req["expression"])
        rows = sql.execute(query, records)
        if req["output"]["format"] == "CSV":
            o = req["output"]["csv"]
            payload = readers.format_csv(
                rows, field_delimiter=o["FieldDelimiter"],
                record_delimiter=o["RecordDelimiter"],
                quote_character=o["QuoteCharacter"])
        else:
            payload = readers.format_json(
                rows,
                record_delimiter=req["output"]["json"]["RecordDelimiter"])
    except sql.SQLError as e:
        return message.error_message("InvalidQuery", str(e))
    except S3SelectError as e:
        return message.error_message(e.code, e.description)

    frames = []
    if req.get("progress"):
        frames.append(message.progress_message(raw_len, len(data),
                                               len(payload)))
    for i in range(0, len(payload), 1 << 20):
        frames.append(message.records_message(payload[i:i + (1 << 20)]))
    frames.append(message.stats_message(raw_len, len(data),
                                        len(payload)))
    frames.append(message.end_message())
    return b"".join(frames)
