"""S3 Select SQL: tokenizer, recursive-descent parser, evaluator
(ref pkg/s3select/sql — the reference uses a participle grammar +
dynamic-typed evaluator; same language subset here).

Supported: SELECT projections (*, expressions, aliases), FROM
S3Object[.path] with alias, WHERE with AND/OR/NOT, comparisons,
BETWEEN, [NOT] LIKE (with ESCAPE), [NOT] IN, IS [NOT] NULL/MISSING,
arithmetic + - * / %, functions (LOWER UPPER TRIM LTRIM RTRIM
CHAR_LENGTH CHARACTER_LENGTH SUBSTRING COALESCE NULLIF CAST ABS),
aggregates (COUNT SUM AVG MIN MAX), LIMIT.

Dynamic typing mirrors the reference: CSV fields are strings; a
comparison against a numeric operand attempts numeric coercion, and
rows where coercion fails simply don't match (SQL null semantics).
"""

from __future__ import annotations

import re
from dataclasses import dataclass


class SQLError(Exception):
    """Parse or evaluation error -> S3 error InvalidQuery."""


MISSING = object()   # field absent (distinct from SQL NULL)


# -- tokenizer -------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<string>'(?:[^']|'')*')
  | (?P<op><>|<=|>=|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.|\[|\])
""", re.VERBOSE)

KEYWORDS = {
    "select", "from", "where", "limit", "as", "and", "or", "not",
    "between", "like", "escape", "in", "is", "null", "missing", "true",
    "false", "cast",
}


@dataclass
class Tok:
    kind: str   # number ident qident string op kw eof
    value: str


def tokenize(s: str) -> list[Tok]:
    out, pos = [], 0
    while pos < len(s):
        mo = _TOKEN_RE.match(s, pos)
        if not mo:
            raise SQLError(f"unexpected character {s[pos]!r} at {pos}")
        pos = mo.end()
        kind = mo.lastgroup
        if kind == "ws":
            continue
        val = mo.group()
        if kind == "ident" and val.lower() in KEYWORDS:
            out.append(Tok("kw", val.lower()))
        elif kind == "qident":
            out.append(Tok("ident", val[1:-1].replace('""', '"')))
        elif kind == "string":
            out.append(Tok("string", val[1:-1].replace("''", "'")))
        else:
            out.append(Tok(kind, val))
    out.append(Tok("eof", ""))
    return out


# -- AST -------------------------------------------------------------------

class Node:
    def eval(self, rec: dict):  # pragma: no cover - interface
        raise NotImplementedError


@dataclass
class Lit(Node):
    value: object

    def eval(self, rec):
        return self.value


@dataclass
class Col(Node):
    """Column/path reference, already stripped of the table alias.
    path items are str keys or int indexes."""
    path: tuple

    def eval(self, rec):
        cur = rec
        for p in self.path:
            if isinstance(p, int):
                if isinstance(cur, list) and 0 <= p < len(cur):
                    cur = cur[p]
                else:
                    return MISSING
            elif isinstance(cur, dict):
                if p in cur:
                    cur = cur[p]
                else:
                    # case-insensitive fallback (ref sql identifiers)
                    lowered = {k.lower(): v for k, v in cur.items()}
                    if p.lower() in lowered:
                        cur = lowered[p.lower()]
                    else:
                        return MISSING
            else:
                return MISSING
        return cur


@dataclass
class Star(Node):
    def eval(self, rec):
        return rec


def _num(v):
    """Best-effort numeric coercion; None on failure."""
    if v is MISSING or v is None or isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            f = float(v)
            return int(f) if f.is_integer() and ("." not in v
                                                 and "e" not in v.lower()
                                                 ) else f
        except ValueError:
            return None
    return None


def _is_null(v):
    return v is None or v is MISSING


@dataclass
class Arith(Node):
    op: str
    left: Node
    right: Node

    def eval(self, rec):
        a = _num(self.left.eval(rec))
        b = _num(self.right.eval(rec))
        if a is None or b is None:
            return None
        try:
            if self.op == "+":
                return a + b
            if self.op == "-":
                return a - b
            if self.op == "*":
                return a * b
            if self.op == "/":
                return a / b
            if self.op == "%":
                return a % b
        except ZeroDivisionError:
            raise SQLError("division by zero")
        raise SQLError(f"bad arith op {self.op}")


@dataclass
class Neg(Node):
    inner: Node

    def eval(self, rec):
        v = _num(self.inner.eval(rec))
        return None if v is None else -v


def _coerced_pair(a, b):
    """Dynamic typing: if either side is numeric, try numeric compare;
    else string compare; bools compare to bools."""
    if isinstance(a, bool) or isinstance(b, bool):
        if isinstance(a, bool) and isinstance(b, bool):
            return a, b
        return None
    if isinstance(a, (int, float)) or isinstance(b, (int, float)):
        na, nb = _num(a), _num(b)
        if na is None or nb is None:
            return None
        return na, nb
    if isinstance(a, str) and isinstance(b, str):
        return a, b
    return None


@dataclass
class Cmp(Node):
    op: str
    left: Node
    right: Node

    def eval(self, rec):
        a = self.left.eval(rec)
        b = self.right.eval(rec)
        if _is_null(a) or _is_null(b):
            return None
        pair = _coerced_pair(a, b)
        if pair is None:
            return False
        a, b = pair
        return {"=": a == b, "!=": a != b, "<>": a != b,
                "<": a < b, "<=": a <= b,
                ">": a > b, ">=": a >= b}[self.op]


@dataclass
class Between(Node):
    value: Node
    lo: Node
    hi: Node
    negate: bool

    def eval(self, rec):
        lo = Cmp(">=", self.value, self.lo).eval(rec)
        hi = Cmp("<=", self.value, self.hi).eval(rec)
        if lo is None or hi is None:
            return None
        r = lo and hi
        return (not r) if self.negate else r


def like_to_re(pattern: str, escape: str | None) -> re.Pattern:
    out, i = [], 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


@dataclass
class Like(Node):
    value: Node
    pattern: Node
    escape: str | None
    negate: bool

    def eval(self, rec):
        v = self.value.eval(rec)
        p = self.pattern.eval(rec)
        if _is_null(v) or _is_null(p):
            return None
        r = bool(like_to_re(str(p), self.escape).match(str(v)))
        return (not r) if self.negate else r


@dataclass
class In(Node):
    value: Node
    options: list
    negate: bool

    def eval(self, rec):
        v = self.value.eval(rec)
        if _is_null(v):
            return None
        hit = any(Cmp("=", Lit(v), o).eval(rec) is True
                  for o in self.options)
        return (not hit) if self.negate else hit


@dataclass
class IsNull(Node):
    value: Node
    negate: bool      # IS NOT NULL
    missing: bool     # IS [NOT] MISSING

    def eval(self, rec):
        v = self.value.eval(rec)
        r = (v is MISSING) if self.missing else _is_null(v)
        return (not r) if self.negate else r


@dataclass
class BoolOp(Node):
    op: str           # and | or
    left: Node
    right: Node

    def eval(self, rec):
        a = self.left.eval(rec)
        b = self.right.eval(rec)
        av = None if a is None else bool(a)
        bv = None if b is None else bool(b)
        if self.op == "and":
            if av is False or bv is False:
                return False
            if av is None or bv is None:
                return None
            return True
        if av is True or bv is True:
            return True
        if av is None or bv is None:
            return None
        return False


@dataclass
class Not(Node):
    inner: Node

    def eval(self, rec):
        v = self.inner.eval(rec)
        return None if v is None else (not bool(v))


def _cast(v, typ: str):
    if _is_null(v):
        return None
    t = typ.lower()
    try:
        if t in ("int", "integer", "bigint", "smallint"):
            return int(float(v))
        if t in ("float", "double", "decimal", "numeric", "real"):
            return float(v)
        if t in ("string", "varchar", "char", "text"):
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, float) and v.is_integer():
                return str(int(v))
            return str(v)
        if t in ("bool", "boolean"):
            if isinstance(v, str):
                if v.lower() in ("true", "1"):
                    return True
                if v.lower() in ("false", "0"):
                    return False
                raise ValueError(v)
            return bool(v)
    except (ValueError, TypeError):
        raise SQLError(f"cannot cast {v!r} to {typ}")
    raise SQLError(f"unsupported cast type {typ}")


@dataclass
class Func(Node):
    name: str
    args: list

    def eval(self, rec):
        n = self.name
        if n == "cast":
            return _cast(self.args[0].eval(rec), self.args[1].value)
        vals = [a.eval(rec) for a in self.args]
        if n == "coalesce":
            for v in vals:
                if not _is_null(v):
                    return v
            return None
        if n == "nullif":
            return None if Cmp("=", Lit(vals[0]),
                               Lit(vals[1])).eval(rec) is True else vals[0]
        if n in ("lower", "upper", "trim", "ltrim", "rtrim"):
            v = vals[0]
            if _is_null(v):
                return None
            s = str(v)
            return {"lower": s.lower, "upper": s.upper, "trim": s.strip,
                    "ltrim": s.lstrip, "rtrim": s.rstrip}[n]()
        if n in ("char_length", "character_length", "length"):
            v = vals[0]
            return None if _is_null(v) else len(str(v))
        if n == "abs":
            v = _num(vals[0])
            return None if v is None else abs(v)
        if n == "substring":
            v = vals[0]
            if _is_null(v):
                return None
            s = str(v)
            ns = _num(vals[1])
            start = int(ns) if ns is not None else 1
            ln = int(_num(vals[2])) if len(vals) > 2 else None
            # SQL SUBSTRING: 1-based; start below 1 clamps but the end
            # position start+len is computed from the ORIGINAL start.
            i0 = max(start - 1, 0)
            if ln is None:
                return s[i0:]
            end = max(start - 1 + ln, i0)
            return s[i0:end]
        raise SQLError(f"unknown function {n}")


AGG_FUNCS = {"count", "sum", "avg", "min", "max"}


@dataclass
class Agg(Node):
    """Aggregate placeholder; accumulated by the executor."""
    name: str
    arg: Node | None   # None = COUNT(*)
    index: int = -1    # slot in the accumulator array

    def eval(self, rec):  # only valid after finalize; executor swaps
        raise SQLError("aggregate outside aggregation context")


# -- parser ----------------------------------------------------------------

@dataclass
class Projection:
    expr: Node
    alias: str | None


@dataclass
class Query:
    projections: list[Projection] | None   # None = SELECT *
    where: Node | None
    limit: int | None
    aggregates: list[Agg]
    table_path: tuple   # path under S3Object, e.g. FROM S3Object.a.b


class Parser:
    def __init__(self, toks: list[Tok]):
        self.toks = toks
        self.i = 0
        self.alias = "s3object"
        self.aggregates: list[Agg] = []

    # token helpers
    def peek(self) -> Tok:
        return self.toks[self.i]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: str | None = None) -> Tok | None:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: str | None = None) -> Tok:
        t = self.accept(kind, value)
        if t is None:
            raise SQLError(
                f"expected {value or kind}, got {self.peek().value!r}")
        return t

    def _int_token(self, what: str) -> int:
        t = self.expect("number")
        try:
            return int(t.value)
        except ValueError:
            raise SQLError(f"{what} must be an integer, got {t.value!r}")

    # grammar
    def parse(self) -> Query:
        self.expect("kw", "select")
        # FROM clause first pass: find alias so column refs can strip it.
        save = self.i
        depth = 0
        table_path: tuple = ()
        while True:
            t = self.peek()
            if t.kind == "eof":
                break
            if t.kind == "op" and t.value == "(":
                depth += 1
            if t.kind == "op" and t.value == ")":
                depth -= 1
            if t.kind == "kw" and t.value == "from" and depth == 0:
                self.next()
                table_path = self._parse_from()
                break
            self.next()
        end_from = self.i
        self.i = save

        projections = self._parse_projections()
        if self.peek().kind == "kw" and self.peek().value == "from":
            self.i = end_from   # skip the FROM clause we already parsed
        where = None
        limit = None
        if self.accept("kw", "where"):
            where = self._expr()
        if self.accept("kw", "limit"):
            limit = self._int_token("LIMIT")
        self.expect("eof")
        return Query(projections, where, limit, self.aggregates,
                     table_path)

    def _parse_from(self) -> tuple:
        t = self.expect("ident")
        if t.value.lower() != "s3object":
            raise SQLError("FROM must reference S3Object")
        path = []
        while self.accept("op", "."):
            path.append(self.expect("ident").value)
        if self.accept("kw", "as"):
            self.alias = self.expect("ident").value.lower()
        elif self.peek().kind == "ident":
            self.alias = self.next().value.lower()
        return tuple(path)

    def _parse_projections(self) -> list[Projection] | None:
        if self.accept("op", "*"):
            return None
        projs = []
        while True:
            e = self._expr()
            alias = None
            if self.accept("kw", "as"):
                alias = self.expect("ident").value
            elif self.peek().kind == "ident":
                alias = self.next().value
            projs.append(Projection(e, alias))
            if not self.accept("op", ","):
                break
        return projs

    def _expr(self) -> Node:
        return self._or()

    def _or(self) -> Node:
        left = self._and()
        while self.accept("kw", "or"):
            left = BoolOp("or", left, self._and())
        return left

    def _and(self) -> Node:
        left = self._not()
        while self.accept("kw", "and"):
            left = BoolOp("and", left, self._not())
        return left

    def _not(self) -> Node:
        if self.accept("kw", "not"):
            return Not(self._not())
        return self._predicate()

    def _predicate(self) -> Node:
        left = self._additive()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=",
                                          ">", ">="):
            self.next()
            return Cmp(t.value, left, self._additive())
        negate = False
        if (t.kind == "kw" and t.value == "not"
                and self.toks[self.i + 1].kind == "kw"
                and self.toks[self.i + 1].value in ("between", "like",
                                                    "in")):
            self.next()
            negate = True
            t = self.peek()
        if t.kind == "kw" and t.value == "between":
            self.next()
            lo = self._additive()
            self.expect("kw", "and")
            return Between(left, lo, self._additive(), negate)
        if t.kind == "kw" and t.value == "like":
            self.next()
            pattern = self._additive()
            esc = None
            if self.accept("kw", "escape"):
                esc = str(self.expect("string").value)
            return Like(left, pattern, esc, negate)
        if t.kind == "kw" and t.value == "in":
            self.next()
            self.expect("op", "(")
            opts = [self._expr()]
            while self.accept("op", ","):
                opts.append(self._expr())
            self.expect("op", ")")
            return In(left, opts, negate)
        if t.kind == "kw" and t.value == "is":
            self.next()
            neg = bool(self.accept("kw", "not"))
            if self.accept("kw", "missing"):
                return IsNull(left, neg, missing=True)
            self.expect("kw", "null")
            return IsNull(left, neg, missing=False)
        return left

    def _additive(self) -> Node:
        left = self._multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                left = Arith(t.value, left, self._multiplicative())
            else:
                return left

    def _multiplicative(self) -> Node:
        left = self._unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = Arith(t.value, left, self._unary())
            else:
                return left

    def _unary(self) -> Node:
        if self.accept("op", "-"):
            return Neg(self._unary())
        if self.accept("op", "+"):
            return self._unary()
        return self._primary()

    def _primary(self) -> Node:
        t = self.peek()
        if t.kind == "number":
            self.next()
            v = float(t.value)
            return Lit(int(v) if v.is_integer() and "." not in t.value
                       and "e" not in t.value.lower() else v)
        if t.kind == "string":
            self.next()
            return Lit(t.value)
        if t.kind == "kw" and t.value in ("true", "false"):
            self.next()
            return Lit(t.value == "true")
        if t.kind == "kw" and t.value == "null":
            self.next()
            return Lit(None)
        if t.kind == "kw" and t.value == "cast":
            self.next()
            self.expect("op", "(")
            inner = self._expr()
            self.expect("kw", "as")
            typ = self.expect("ident").value
            self.expect("op", ")")
            return Func("cast", [inner, Lit(typ)])
        if t.kind == "op" and t.value == "(":
            self.next()
            e = self._expr()
            self.expect("op", ")")
            return e
        if t.kind == "ident":
            # function call?
            if self.toks[self.i + 1].kind == "op" and \
                    self.toks[self.i + 1].value == "(":
                name = self.next().value.lower()
                self.next()  # (
                if name in AGG_FUNCS:
                    return self._aggregate(name)
                args = []
                if not self.accept("op", ")"):
                    args.append(self._expr())
                    while self.accept("op", ","):
                        args.append(self._expr())
                    self.expect("op", ")")
                return Func(name, args)
            return self._column_ref()
        raise SQLError(f"unexpected token {t.value!r}")

    def _aggregate(self, name: str) -> Node:
        if name == "count" and self.accept("op", "*"):
            self.expect("op", ")")
            agg = Agg(name, None, len(self.aggregates))
        else:
            arg = self._expr()
            self.expect("op", ")")
            agg = Agg(name, arg, len(self.aggregates))
        self.aggregates.append(agg)
        return agg

    def _column_ref(self) -> Node:
        first = self.expect("ident").value
        path: list = []
        if first.lower() not in (self.alias, "s3object"):
            path.append(first)
        while True:
            if self.accept("op", "."):
                path.append(self.expect("ident").value)
            elif self.accept("op", "["):
                idx = self._int_token("array index")
                self.expect("op", "]")
                path.append(idx)
            else:
                break
        if not path:
            return Star()
        return Col(tuple(path))


def parse(sql: str) -> Query:
    return Parser(tokenize(sql)).parse()


# -- execution -------------------------------------------------------------

class _AggState:
    __slots__ = ("name", "count", "total", "minv", "maxv")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.minv = None
        self.maxv = None

    def update(self, v):
        if self.name == "count":
            if not _is_null(v):  # COUNT(expr) skips NULL/MISSING
                self.count += 1
            return
        n = _num(v)
        if n is None:
            return
        self.count += 1
        self.total += n
        self.minv = n if self.minv is None else min(self.minv, n)
        self.maxv = n if self.maxv is None else max(self.maxv, n)

    def result(self):
        if self.name == "count":
            return self.count
        if self.name == "sum":
            return self.total if self.count else None
        if self.name == "avg":
            return self.total / self.count if self.count else None
        if self.name == "min":
            return self.minv
        return self.maxv


class _AggValue(Node):
    def __init__(self, value):
        self.value = value

    def eval(self, rec):
        return self.value


def execute(query: Query, records) -> list:
    """Run the query over an iterable of dict records. Returns a list of
    output records: dicts (projected) or the raw record for SELECT *."""
    out = []
    limit = query.limit

    def project(rec) -> dict:
        if query.projections is None:
            return rec
        row = {}
        for i, p in enumerate(query.projections):
            v = p.expr.eval(rec)
            if v is MISSING:
                v = None
            name = p.alias or _projection_name(p.expr, i)
            row[name] = v
        return row

    if query.aggregates:
        states = [_AggState(a.name) for a in query.aggregates]
        n = 0
        for rec in records:
            rec = _descend(rec, query.table_path)
            if rec is None:
                continue
            if query.where is not None and \
                    query.where.eval(rec) is not True:
                continue
            n += 1
            for a, st in zip(query.aggregates, states):
                st.update(a.arg.eval(rec) if a.arg is not None else 1)
        # swap Agg nodes for computed values, then project once
        for a, st in zip(query.aggregates, states):
            a.eval = _AggValue(st.result()).eval  # type: ignore
        return [project({})]

    for rec in records:
        rec = _descend(rec, query.table_path)
        if rec is None:
            continue
        if query.where is not None and query.where.eval(rec) is not True:
            continue
        out.append(project(rec))
        if limit is not None and len(out) >= limit:
            break
    return out


def _descend(rec, path: tuple):
    for p in path:
        if isinstance(rec, dict) and p in rec:
            rec = rec[p]
        else:
            return None
    return rec


def _projection_name(expr: Node, i: int) -> str:
    if isinstance(expr, Col) and expr.path and \
            isinstance(expr.path[-1], str):
        return expr.path[-1]
    return f"_{i + 1}"
