"""Background data scanner: usage accounting, lifecycle enforcement,
heal sampling (ref cmd/data-crawler.go, cmd/data-usage-cache.go)."""
