"""The data crawler: a perpetual low-priority sweep over all buckets.

Per cycle it (ref cmd/data-crawler.go runDataCrawler/crawlDataFolder):
  1. walks every object version, building the data-usage tree
     (object/version counts, logical size, size histogram — ref
     cmd/data-usage-cache.go), persisted through the quorum config
     store so restarts resume with the last cycle's numbers;
  2. applies bucket LIFECYCLE rules, expiring versions in place
     (ref lifecycle application inside crawlDataFolder);
  3. samples objects for HEAL verification (1 in `heal_sample`,
     ref dataCrawlHealSample cmd/data-crawler.go:49-51) and queues
     repairs through the engine's healer.

The crawler is cooperative: `crawl_once()` is synchronous (tests,
admin-triggered sweeps); `start()` runs cycles on a timer thread.
"""

from __future__ import annotations

import threading
import time

from ..bucket.lifecycle import (DELETE, DELETE_MARKER, DELETE_VERSION,
                                TRANSITION,
                                Lifecycle, parse_tags)
from ..erasure.engine import MethodNotAllowed, ObjectNotFound

USAGE_PATH = "data-usage/usage.json"

# Size histogram buckets (ref cmd/data-usage-cache.go sizeHistogram).
_HISTOGRAM = (
    ("LESS_THAN_1024_B", 0, 1024),
    ("BETWEEN_1024_B_AND_1_MB", 1024, 1024 * 1024),
    ("BETWEEN_1_MB_AND_10_MB", 1024 * 1024, 10 * 1024 * 1024),
    ("BETWEEN_10_MB_AND_64_MB", 10 * 1024 * 1024, 64 * 1024 * 1024),
    ("BETWEEN_64_MB_AND_128_MB", 64 * 1024 * 1024, 128 * 1024 * 1024),
    ("GREATER_THAN_128_MB", 128 * 1024 * 1024, float("inf")),
)


def _bucket_for_size(size: int) -> str:
    for name, lo, hi in _HISTOGRAM:
        if lo <= size < hi:
            return name
    return _HISTOGRAM[-1][0]


class DataCrawler:
    def __init__(self, layer, bucket_meta, store=None, notifier=None,
                 interval: float = 60.0, heal_sample: int = 512,
                 tiers=None):
        """layer: ObjectLayer; bucket_meta: BucketMetadataSys; store:
        ConfigStore for persistence (defaults to bucket_meta's);
        heal_sample: sample 1-in-N objects for deep verification;
        tiers: TierManager enabling ILM transition."""
        self.layer = layer
        self.bucket_meta = bucket_meta
        self.store = store if store is not None else bucket_meta.store
        self.notifier = notifier
        self.tiers = tiers
        self.interval = interval
        self.heal_sample = max(1, heal_sample)
        self._counter = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._mu = threading.Lock()
        self.last_usage: dict = self._load_usage()
        self.cycles = 0
        self.healed: list[tuple[str, str]] = []
        # Change-tracking skip state (ref dataUpdateTracker bloom skip
        # of unchanged subtrees; full sweep every N cycles).
        self._last_counters: dict[str, int] = {}
        self.full_cycle_every = 16
        self.skipped_buckets = 0

    def _engines(self):
        layer = self.layer
        if hasattr(layer, "pools"):
            return [s for p in layer.pools for s in p.sets]
        if hasattr(layer, "sets"):
            return list(layer.sets)
        return [layer]

    def _bucket_counter(self, bucket: str) -> int | None:
        """Sum of change counters across engines; None when NO engine
        has a tracker (FS backend) — callers must then never skip."""
        total = None
        for eng in self._engines():
            t = getattr(eng, "update_tracker", None)
            if t is not None:
                total = (total or 0) + t.bucket_counter(bucket)
        return total

    # -- persistence ----------------------------------------------------

    def _load_usage(self) -> dict:
        try:
            return self.store.load(USAGE_PATH) or {}
        except Exception:
            return {}

    def _save_usage(self, usage: dict) -> None:
        try:
            self.store.save(USAGE_PATH, usage)
        except Exception:
            pass  # usage is advisory; never fail the sweep over it

    # -- one cycle ------------------------------------------------------

    def crawl_once(self, now: float | None = None) -> dict:
        # The whole cycle (usage walk, lifecycle rewrites, sampled heal
        # verification) is background work: its kernel dispatches yield
        # to foreground traffic via the QoS lanes (qos/scheduler.py).
        from ..qos.scheduler import background_lane
        with background_lane():
            return self._crawl_once_bg(time.time() if now is None
                                       else now)

    def _crawl_once_bg(self, now: float) -> dict:
        usage: dict = {"lastUpdate": now, "buckets": {}}
        full_sweep = (self.cycles % self.full_cycle_every == 0)
        for b in self.layer.list_buckets():
            bucket = b["name"]
            meta = self.bucket_meta.get(bucket)
            lc = Lifecycle.parse(meta.lifecycle_xml)
            versioned = meta.versioning_enabled()
            # Unchanged since last cycle + no time-driven lifecycle
            # rules -> keep previous usage, skip the walk (ref bloom
            # skip; lifecycle actions are time-based so those buckets
            # always rescan, as does every Nth full sweep).
            counter = self._bucket_counter(bucket)
            prev = self.last_usage.get("buckets", {}).get(bucket)
            if (not full_sweep and not lc and prev is not None
                    and counter is not None
                    and self._last_counters.get(bucket) == counter):
                usage["buckets"][bucket] = prev
                self.skipped_buckets += 1
                continue
            self._last_counters[bucket] = counter
            bu = {"objects": 0, "versions": 0, "size": 0,
                  "histogram": {}}
            versions = None
            try:
                versions = self.layer.list_object_versions(
                    bucket, max_keys=1_000_000)
            except MethodNotAllowed:
                pass  # FS backend has no version index
            except Exception:
                continue
            if versions is None:
                try:
                    versions = self.layer.list_objects(
                        bucket, max_keys=1_000_000)
                except Exception:
                    continue
            # Group per key, newest first (list order guarantees this).
            per_key: dict[str, list] = {}
            for v in versions:
                per_key.setdefault(v.name, []).append(v)
            for key, vers in per_key.items():
                self._apply_lifecycle(bucket, key, vers, lc, versioned,
                                      now)
            # Re-list only if lifecycle removed something? Cheap approach:
            # account on the surviving view.
            survivors = [v for vs in per_key.values() for v in vs
                         if not getattr(v, "_expired", False)]
            latest_seen: set[str] = set()
            for v in survivors:
                if v.delete_marker:
                    continue
                bu["versions"] += 1
                bu["size"] += v.size
                if v.name not in latest_seen:
                    latest_seen.add(v.name)
                    bu["objects"] += 1
                    h = _bucket_for_size(v.size)
                    bu["histogram"][h] = bu["histogram"].get(h, 0) + 1
                self._maybe_heal(bucket, v)
            usage["buckets"][bucket] = bu
        with self._mu:
            self.last_usage = usage
            self.cycles += 1
        self._save_usage(usage)
        # Cycle the per-engine change blooms + persist advisory tracker
        # state (ref CycleBloom fan-out; tracker saved per disk).
        for i, eng in enumerate(self._engines()):
            t = getattr(eng, "update_tracker", None)
            if t is not None:
                t.advance_cycle()
                t.save(self.store, f"tracker/state-{i}.json")
        return usage

    def _apply_lifecycle(self, bucket: str, key: str, vers: list,
                         lc: Lifecycle, versioned: bool,
                         now: float) -> None:
        if not lc:
            return
        # vers: newest first. A noncurrent version's age runs from when
        # it was REPLACED = its successor's mod_time.
        for i, v in enumerate(vers):
            is_latest = i == 0
            noncurrent_since = vers[i - 1].mod_time if i > 0 else v.mod_time
            tags = parse_tags(v.metadata.get("x-amz-tagging", ""))
            action, tier = lc.compute_with_tier(
                key, noncurrent_since if not is_latest else v.mod_time,
                is_latest=is_latest, delete_marker=v.delete_marker,
                tags=tags, sole_version=len(vers) == 1, now=now)
            try:
                from ..bucket import tiering as tier_mod
                if (self.tiers is not None and is_latest
                        and tier_mod.restub_if_restore_expired(
                            self.layer, bucket, key, v.metadata, now)):
                    pass  # expired restore collapsed back to a stub
                if action == TRANSITION:
                    if self.tiers is not None and is_latest:
                        tier_mod.transition_object(
                            self.layer, self.tiers, bucket, key, tier,
                            versioned=versioned)
                elif action == DELETE:
                    # Expire the current version: versioned buckets get
                    # a delete marker, unversioned delete outright — the
                    # outright delete destroys data, so WORM applies
                    # (ref enforceRetentionForDeletion gate on crawler
                    # expiry, cmd/data-crawler.go:924).
                    if not versioned and self._worm_protected(v, now):
                        continue
                    out = self.layer.delete_object(bucket, key,
                                                   versioned=versioned)
                    v._expired = not versioned
                    self._notify_removed(bucket, key, out)
                    if (not versioned and self.tiers is not None
                            and tier_mod.is_transitioned(v.metadata)):
                        self.tiers.delete_remote(v.metadata)
                elif action in (DELETE_VERSION, DELETE_MARKER):
                    # Version deletes always destroy data: skip any
                    # legal-hold/retention-protected version (markers
                    # carry no retention metadata and pass).
                    if self._worm_protected(v, now):
                        continue
                    out = self.layer.delete_object(bucket, key,
                                                   v.version_id or "")
                    v._expired = True
                    self._notify_removed(bucket, key, out)
                    if (self.tiers is not None
                            and tier_mod.is_transitioned(v.metadata)):
                        self.tiers.delete_remote(v.metadata)
            except ObjectNotFound:
                pass
            except Exception:
                continue

    @staticmethod
    def _worm_protected(v, now: float) -> bool:
        """True when deleting this version is forbidden by legal hold
        or active retention (ref enforceRetentionForDeletion,
        cmd/data-crawler.go:924). The crawler never bypasses
        GOVERNANCE. `now` is the same clock the lifecycle decision
        used, so expiry and WORM agree on what time it is."""
        from ..bucket import objectlock as ol
        try:
            ol.check_version_delete(v.metadata, bypass_governance=False,
                                    now=now)
        except ol.ObjectLockError:
            return True
        except Exception:
            return True  # unparseable lock metadata: fail safe, keep it
        return False

    def _notify_removed(self, bucket: str, key: str, deleted) -> None:
        """ILM expiry fires the same removal events an S3 DELETE would
        (ref sendEvent from applyLifecycle, cmd/data-crawler.go)."""
        if self.notifier is None:
            return
        from ..event import event as ev
        self.notifier.send(ev.Event(
            event_name=(ev.OBJECT_REMOVED_DELETE_MARKER
                        if deleted.delete_marker
                        else ev.OBJECT_REMOVED_DELETE),
            bucket=bucket, key=key,
            version_id=deleted.version_id))

    def _maybe_heal(self, bucket: str, v) -> None:
        """1-in-N sampled verification (ref data-crawler heal sampling,
        cmd/data-crawler.go:49-51 + healObject enqueue)."""
        self._counter += 1
        if self._counter % self.heal_sample:
            return
        # Sampled deep verify is the crawl's expensive step: pace it
        # against foreground traffic (ref waitForLowHTTPReq).
        from ..qos.scheduler import GATE
        GATE.throttle_background()
        healer = getattr(self.layer, "healer", None)
        if healer is None:
            return
        try:
            # Sweep-friendly helper: a lock-contended sample requeues
            # via MRF instead of being silently dropped until the next
            # random 1-in-N hit.
            heal = getattr(healer, "heal_object_or_queue",
                           healer.heal_object)
            heal(bucket, v.name)
            self.healed.append((bucket, v.name))
        except Exception:
            pass

    # -- background loop ------------------------------------------------

    def start(self) -> None:
        if self._thread:
            return
        # mtpu-lint: disable=R1 -- boot-time crawler daemon; tags its own bg lane per sweep step
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="data-crawler")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.crawl_once()
            except Exception:
                pass  # the sweep must survive any single-cycle error

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def data_usage(self) -> dict:
        with self._mu:
            return dict(self.last_usage)

    def bucket_sizes(self) -> dict[str, int]:
        """{bucket: logical at-rest bytes} from the last cycle — the
        stored-bytes half of admin /top's live-traffic + footprint
        join (obs/usage.py owns the live half)."""
        with self._mu:
            buckets = (self.last_usage or {}).get("buckets", {})
            return {name: int(v.get("size", 0) or 0)
                    for name, v in buckets.items()
                    if isinstance(v, dict)}
