"""Data update tracker: per-bucket change counters + a cycling bloom
filter of changed object paths (ref dataUpdateTracker,
cmd/data-update-tracker.go:64; bloom import :39).

Consumers:
- the metacache listing engine invalidates cached listings when a
  bucket's counter moved (read-after-write on the serving node);
- the data crawler skips buckets whose counter is unchanged since its
  last cycle, except on periodic full sweeps (ref bloom-filter skip of
  unchanged subtrees + `dataUpdateTrackerResetEvery` full cycles).
"""

from __future__ import annotations

import hashlib
import threading


class BloomFilter:
    """Fixed-size double-hashing bloom filter over path strings."""

    def __init__(self, bits: int = 1 << 16, hashes: int = 4,
                 data: bytearray | None = None):
        self.nbits = bits
        self.hashes = hashes
        self.bits = data if data is not None else bytearray(bits // 8)

    def _idx(self, key: str):
        h = hashlib.sha256(key.encode()).digest()
        a = int.from_bytes(h[:8], "little")
        b = int.from_bytes(h[8:16], "little") | 1
        for i in range(self.hashes):
            yield (a + i * b) % self.nbits

    def add(self, key: str) -> None:
        for i in self._idx(key):
            self.bits[i >> 3] |= 1 << (i & 7)

    def __contains__(self, key: str) -> bool:
        return all(self.bits[i >> 3] & (1 << (i & 7))
                   for i in self._idx(key))

    def merge(self, other: "BloomFilter") -> None:
        for i, b in enumerate(other.bits):
            self.bits[i] |= b

    def to_wire(self) -> dict:
        return {"bits": self.bits.hex(), "nbits": self.nbits,
                "hashes": self.hashes}

    @classmethod
    def from_wire(cls, d: dict) -> "BloomFilter":
        return cls(d["nbits"], d["hashes"], bytearray.fromhex(d["bits"]))


class DataUpdateTracker:
    """In-process registry of object mutations since process start."""

    def __init__(self):
        self._mu = threading.Lock()
        self._counters: dict[str, int] = {}
        self._cycle = 0
        self._current = BloomFilter()
        self._history: list[BloomFilter] = []  # newest first, capped

    def mark(self, bucket: str, path: str = "") -> None:
        """Record a mutation of bucket[/path]. Every path prefix is
        marked too so consumers can ask "did anything change under this
        prefix?" (ref dataUpdateTracker marking parent dirs)."""
        with self._mu:
            self._counters[bucket] = self._counters.get(bucket, 0) + 1
            self._current.add(bucket)
            if path:
                parts = path.split("/")
                for i in range(1, len(parts) + 1):
                    self._current.add(f"{bucket}/" + "/".join(parts[:i]))

    def bucket_counter(self, bucket: str) -> int:
        with self._mu:
            return self._counters.get(bucket, 0)

    @property
    def cycle(self) -> int:
        return self._cycle

    def advance_cycle(self) -> BloomFilter:
        """End the crawler cycle: returns the filter of paths changed
        during it and starts a fresh one (ref CycleBloom,
        cmd/peer-rest-common.go:53)."""
        with self._mu:
            done = self._current
            self._history.insert(0, done)
            del self._history[8:]
            self._current = BloomFilter()
            self._cycle += 1
            return done

    def changed_since(self, cycles_back: int, key: str) -> bool:
        """Conservative: True if `key` may have changed within the last
        `cycles_back` crawler cycles (or ever marked this cycle). Asking
        further back than retained history answers True — absence of
        evidence is not evidence of absence."""
        with self._mu:
            if key in self._current:
                return True
            if cycles_back > len(self._history):
                return True
            return any(key in f
                       for f in self._history[:max(0, cycles_back)])

    def changed_under(self, bucket: str, prefix_root: str,
                      cycles_back: int = 2) -> bool:
        """Conservative prefix query: True if anything may have changed
        under bucket/prefix_root recently (bloom false positives just
        cost a rescan). Empty root asks about the whole bucket."""
        key = f"{bucket}/{prefix_root}" if prefix_root else bucket
        return self.changed_since(cycles_back, key)

    def to_wire(self) -> dict:
        with self._mu:
            return {"cycle": self._cycle,
                    "counters": dict(self._counters),
                    "current": self._current.to_wire()}

    def save(self, store, path: str = "tracker/state.json") -> None:
        """Persist advisory state (the crawler calls this at cycle end;
        ref dataUpdateTracker saved per disk)."""
        try:
            store.save(path, self.to_wire())
        except Exception:
            pass  # advisory state

    @classmethod
    def load(cls, store, path: str = "tracker/state.json",
             ) -> "DataUpdateTracker":
        t = cls()
        try:
            d = store.load(path)
        except Exception:
            d = None
        if d:
            t._cycle = d.get("cycle", 0)
            t._counters = dict(d.get("counters", {}))
            if "current" in d:
                t._current = BloomFilter.from_wire(d["current"])
        return t
