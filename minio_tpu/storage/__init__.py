"""Per-disk storage: the StorageAPI contract, local POSIX implementation
(xl-storage analog), and on-disk metadata formats."""
