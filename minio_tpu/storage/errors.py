"""Storage error taxonomy (ref cmd/storage-errors.go)."""


class StorageError(Exception):
    """Base class for per-disk storage errors."""


class DiskNotFound(StorageError):
    """Disk is offline or gone (ref errDiskNotFound)."""


class FaultyDisk(StorageError):
    """Disk returned an unexpected I/O error (ref errFaultyDisk)."""


class VolumeNotFound(StorageError):
    """Bucket/volume does not exist (ref errVolumeNotFound)."""


class VolumeExists(StorageError):
    """Volume already exists (ref errVolumeExists)."""


class FileNotFound(StorageError):
    """Object/file does not exist (ref errFileNotFound)."""


class VersionNotFound(StorageError):
    """Requested version does not exist (ref errFileVersionNotFound)."""


class FileCorrupt(StorageError):
    """File failed bitrot/format validation (ref errFileCorrupt)."""


class RegenRepairFailed(StorageError):
    """Regenerating-code (REGEN) repair could not complete: the
    minimum-bandwidth helper collection fell short AND the conventional
    any-k fallback had fewer than k readable chunks.  Retryable — a
    flapping helper may answer the next heal pass."""


class DiskFull(StorageError):
    """No space left (ref errDiskFull)."""


class DriveQuarantined(StorageError):
    """Write/read skipped because the drive is quarantined by the
    health monitor (obs/drivemon.py) — a bookkeeping marker for the
    degraded-write path, not evidence from the drive itself."""
