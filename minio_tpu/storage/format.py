"""format.json — per-disk identity and cluster topology
(ref cmd/format-erasure.go:109 formatErasureV3: deployment id, per-disk
uuid `this`, `sets` matrix of drive uuids, distribution algorithm).

On first boot the coordinator writes a fresh format to every disk; on
restart formats are quorum-loaded, disks are matched to their set/slot by
uuid (surviving physical reordering), and blank replacement disks are
detected for healing (ref waitForFormatErasure, cmd/prepare-storage.go).
"""

from __future__ import annotations

import json
import uuid as uuidlib
from dataclasses import dataclass, field

from . import errors as serr
from .interface import StorageAPI
from .xl import MINIO_META_BUCKET

FORMAT_FILE = "format.json"
FORMAT_VERSION = "1"
FORMAT_BACKEND = "xl-tpu"
DISTRIBUTION_ALGO = "SIPMOD+PARITY"  # ref formatErasureVersionV3DistributionAlgoV3


@dataclass
class FormatErasure:
    """One disk's view of the topology."""
    deployment_id: str
    this: str                     # this disk's uuid
    sets: list[list[str]] = field(default_factory=list)
    distribution_algo: str = DISTRIBUTION_ALGO

    def to_bytes(self) -> bytes:
        return json.dumps({
            "version": FORMAT_VERSION,
            "format": FORMAT_BACKEND,
            "id": self.deployment_id,
            "xl": {
                "version": "3",
                "this": self.this,
                "sets": self.sets,
                "distributionAlgo": self.distribution_algo,
            },
        }, sort_keys=True).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "FormatErasure":
        doc = json.loads(raw)
        if doc.get("format") != FORMAT_BACKEND:
            raise serr.FileCorrupt(f"bad format: {doc.get('format')}")
        xl = doc["xl"]
        return cls(deployment_id=doc["id"], this=xl["this"],
                   sets=xl["sets"],
                   distribution_algo=xl.get("distributionAlgo",
                                            DISTRIBUTION_ALGO))

    def find(self, disk_uuid: str) -> tuple[int, int] | None:
        for si, s in enumerate(self.sets):
            for di, u in enumerate(s):
                if u == disk_uuid:
                    return si, di
        return None


def pick_set_layout(n_disks: int, set_size: int | None = None,
                    ) -> tuple[int, int]:
    """(num_sets, set_size) for n disks. The reference requires equal set
    sizes 4..16 chosen by GCD (ref getSetIndexes,
    cmd/endpoint-ellipses.go:132); small dev topologies (2..3 drives)
    form a single set."""
    if set_size is not None:
        if n_disks % set_size:
            raise ValueError(f"{n_disks} disks not divisible into "
                             f"sets of {set_size}")
        return n_disks // set_size, set_size
    if n_disks < 4:
        if n_disks < 2:
            raise ValueError("need at least 2 disks")
        return 1, n_disks
    for size in range(16, 3, -1):
        if n_disks % size == 0:
            return n_disks // size, size
    raise ValueError(
        f"cannot divide {n_disks} disks into equal sets of 4..16")


def load_format(disk: StorageAPI) -> FormatErasure | None:
    try:
        return FormatErasure.from_bytes(
            disk.read_all(MINIO_META_BUCKET, FORMAT_FILE))
    except serr.FileNotFound:
        return None
    except serr.StorageError:
        return None


def save_format(disk: StorageAPI, fmt: FormatErasure) -> None:
    disk.write_all(MINIO_META_BUCKET, FORMAT_FILE, fmt.to_bytes())


def init_or_load_formats(disks: list[StorageAPI],
                         set_size: int | None = None,
                         ) -> tuple[FormatErasure, list[StorageAPI],
                                    list[int]]:
    """Bootstrap the topology across a pool's disks.

    Returns (reference format, disks reordered to format slots,
    fresh_disk_indices needing heal). First boot: generate uuids and
    write formats everywhere. Restart: quorum-load, reorder disks by
    their format uuid, re-stamp blank replacements (fresh disks).
    """
    n = len(disks)
    n_sets, set_size_ = pick_set_layout(n, set_size)
    formats = [load_format(d) for d in disks]
    have = [f for f in formats if f is not None]

    if not have:
        # First boot: mint the topology.
        dep = str(uuidlib.uuid4())
        sets = [[str(uuidlib.uuid4()) for _ in range(set_size_)]
                for _ in range(n_sets)]
        flat = [u for s in sets for u in s]
        for disk, u in zip(disks, flat):
            save_format(disk, FormatErasure(dep, u, sets))
        return FormatErasure(dep, "", sets), list(disks), []

    # Quorum reference format: majority by (deployment, sets) shape.
    groups: dict[str, list[FormatErasure]] = {}
    for f in have:
        key = json.dumps([f.deployment_id, f.sets], sort_keys=True)
        groups.setdefault(key, []).append(f)
    ref = max(groups.values(), key=len)[0]
    flat = [u for s in ref.sets for u in s]
    if len(flat) != n:
        raise ValueError(
            f"format topology has {len(flat)} drives, {n} provided")

    # Place each disk at its format slot; only BLANK disks may fill
    # leftover slots — a disk carrying a foreign format (different
    # deployment or unknown uuid) is an operator error, never silently
    # re-stamped (the reference refuses to boot on deployment-id
    # mismatch, ref formatErasureV3Check).
    ordered: list[StorageAPI | None] = [None] * n
    unplaced: list[StorageAPI] = []
    for disk, f in zip(disks, formats):
        if f is None:
            unplaced.append(disk)
            continue
        if f.deployment_id != ref.deployment_id or f.this not in flat:
            raise ValueError(
                f"disk {disk.endpoint()} belongs to a different "
                f"deployment ({f.deployment_id}); refusing to re-stamp")
        slot = flat.index(f.this)
        if ordered[slot] is None:
            ordered[slot] = disk
        else:
            raise ValueError(
                f"duplicate drive uuid {f.this} "
                f"({disk.endpoint()} vs {ordered[slot].endpoint()})")
    fresh: list[int] = []
    for slot in range(n):
        if ordered[slot] is None:
            disk = unplaced.pop(0)
            ordered[slot] = disk
            # Re-stamp the replacement disk with the slot identity.
            save_format(disk, FormatErasure(ref.deployment_id, flat[slot],
                                            ref.sets))
            fresh.append(slot)
    return ref, ordered, fresh
