"""StorageAPI — the per-disk contract (ref cmd/storage-interface.go:25-82).

Every method has a local implementation (xl.XLStorage) and, in distributed
mode, a remote one (rpc.RemoteStorage) with identical semantics. This seam
is also the fault-injection point for tests (the reference's naughtyDisk
pattern, ref cmd/naughty-disk_test.go).

All data-plane payloads are bytes; erasure/bitrot logic lives above this
layer. Errors are storage.errors types.
"""

from __future__ import annotations

import abc
import re

from .metadata import FileInfo

# Version data dirs are uuid4 names (metadata.new_data_dir); the walk
# must not descend into them as if they were key prefixes.
DATA_DIR_RE = re.compile(
    r"^[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-"
    r"[0-9a-f]{4}-[0-9a-f]{12}$")


class StorageAPI(abc.ABC):
    """30-method per-disk contract, grown as layers land."""

    # --- identity / health ---

    @abc.abstractmethod
    def disk_info(self) -> dict:
        """Totals/frees/id (ref DiskInfo)."""

    def is_online(self) -> bool:
        return True

    def endpoint(self) -> str:
        return "local"

    def close(self) -> None:
        pass

    # --- volumes (buckets) ---

    @abc.abstractmethod
    def make_volume(self, volume: str) -> None: ...

    @abc.abstractmethod
    def list_volumes(self) -> list[str]: ...

    @abc.abstractmethod
    def stat_volume(self, volume: str) -> dict: ...

    @abc.abstractmethod
    def delete_volume(self, volume: str, force: bool = False) -> None: ...

    # --- flat files (config, tmp shards) ---

    @abc.abstractmethod
    def write_all(self, volume: str, path: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def read_all(self, volume: str, path: str) -> bytes: ...

    @abc.abstractmethod
    def read_file(self, volume: str, path: str, offset: int,
                  length: int) -> bytes:
        """Ranged read (ref ReadFileStream)."""

    def repair_project(self, volume: str, path: str,
                       ranges: list[tuple[int, int]]) -> bytes:
        """Minimum-bandwidth repair read (REGEN storage class): the
        concatenated bytes of [offset, offset+length) slices — one
        stored stripe row per block of a heal group
        (erasure/regen/repair.py computes the offsets).  The default
        composes ranged reads, so every local disk and test stub
        supports it; rpc.RemoteStorage overrides it with a SINGLE RPC
        so only the small projection crosses the wire — the whole
        point of the regenerating code."""
        return b"".join(self.read_file(volume, path, off, length)
                        for off, length in ranges)

    @abc.abstractmethod
    def create_file(self, volume: str, path: str, data) -> None:
        """Write a (shard) file, creating parents (ref CreateFile,
        cmd/xl-storage.go:1575 — a STREAMING write there). `data` is
        bytes or an iterable of byte chunks; iterable input must be
        written incrementally, never buffered whole."""

    @abc.abstractmethod
    def append_file(self, volume: str, path: str, data: bytes) -> None:
        """Append a chunk to a (staging) file, creating it and parents
        on first append (ref AppendFile, cmd/xl-storage.go). The
        engine's block pipeline writes one erasure batch per call."""

    @abc.abstractmethod
    def delete(self, volume: str, path: str, recursive: bool = False,
               ) -> None: ...

    @abc.abstractmethod
    def rename_file(self, src_volume: str, src_path: str, dst_volume: str,
                    dst_path: str) -> None: ...

    @abc.abstractmethod
    def list_dir(self, volume: str, path: str) -> list[str]:
        """Entries of a directory; dirs have a trailing '/'."""

    def walk_dir_iter(self, volume: str, prefix: str = "",
                      after: str = ""):
        """Ordered, RESUMABLE per-disk walk of a bucket — yields
        {"name": ..., "versions": [version-dict, ...]} entries in
        full-key BYTE order, one at a time, never materializing the
        listing (ref StorageAPI.WalkDir, cmd/metacache-walk.go — the
        per-disk feeder of the metacache listing engine; there the
        stream rides one chunked HTTP response, here it feeds the paged
        storage RPC in rpc/storage.py). Entries carry the full xl.meta
        versions array so the merger can resolve quorum without extra
        round trips.

        Ordering: a MIN-HEAP of pending directories, popped in path
        order. An object's key equals its directory's path, a
        directory's subtree only emits keys >= its path, and heap pops
        are monotonic — so emission is exact byte order even where
        depth-first sibling order disagrees with it ("a" < "a-b" <
        "a/b" although sibling dirs sort "a-b/" < "a/"). Memory is
        O(frontier), not O(listing). (The reference's walk emits
        subtree-contiguous order instead; OUR listing contract — the
        k-way merge, markers, golden listings — is byte order, so the
        walk must produce it.)

        `after` (exclusive) resumes a previous walk: directories whose
        whole subtree sorts <= after are pruned without descending, so
        a resumed page costs O(depth + page), not O(listing).
        """
        import heapq

        from . import errors as _serr

        heap: list[str] = [""]
        while heap:
            path = heapq.heappop(heap)
            try:
                entries = self.list_dir(volume, path)
            except _serr.StorageError:
                continue
            is_obj = "xl.meta" in entries
            if is_obj and path and (not prefix
                                    or path.startswith(prefix)) \
                    and path > after:
                try:
                    vers = [fi.to_version_dict()
                            for fi in self.read_versions(volume, path)]
                    yield {"name": path, "versions": vers}
                except _serr.StorageError:
                    pass
            for e in entries:
                if not e.endswith("/"):
                    continue
                name = e[:-1]
                if is_obj and DATA_DIR_RE.match(name):
                    continue  # version data dir, not a key prefix
                sub = f"{path}/{name}" if path else name
                # Prefix pruning: descend only when sub can still hold
                # matches (sub itself matches, or prefix lies below sub).
                if prefix and not (sub.startswith(prefix)
                                   or prefix.startswith(sub + "/")):
                    continue
                # Resume pruning: every key in the subtree is either
                # `sub` itself or starts with `sub + "/"`; skip unless
                # some of those can sort after `after`.
                if after and not (after < sub + "/"
                                  or after.startswith(sub + "/")):
                    continue
                heapq.heappush(heap, sub)

    def walk_dir(self, volume: str, prefix: str = "") -> list[dict]:
        """Materialized walk_dir_iter (compat surface for callers that
        want the whole listing; the sort is a no-op safety net — the
        iterator already emits byte order)."""
        return sorted(self.walk_dir_iter(volume, prefix),
                      key=lambda d: d["name"])

    # --- object versions (xl.meta) ---

    @abc.abstractmethod
    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str) -> None:
        """Atomic object commit: move tmp data dir + merge version into
        dst xl.meta (ref RenameData, cmd/xl-storage.go:1972)."""

    @abc.abstractmethod
    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        """Merge one version into xl.meta (ref WriteMetadata)."""

    @abc.abstractmethod
    def read_version(self, volume: str, path: str,
                     version_id: str = "") -> FileInfo:
        """Read one version's FileInfo ("" = latest)
        (ref ReadVersion)."""

    @abc.abstractmethod
    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        """Remove a version; drops data dir when last reference goes
        (ref DeleteVersion)."""

    @abc.abstractmethod
    def read_versions(self, volume: str, path: str) -> list[FileInfo]:
        """All versions of one object, newest first (ref ReadVersion on
        the full xlMetaV2 versions array, cmd/xl-storage-format-v2.go)."""

    @abc.abstractmethod
    def read_parts(self, volume: str, path: str, data_dir: str,
                   ) -> list[str]:
        """List part files of a version's data dir (ref CheckParts)."""

    @abc.abstractmethod
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        """Deep bitrot scan of this disk's shard for fi; raises
        FileCorrupt on mismatch (ref VerifyFile, cmd/xl-storage.go:2380)."""
