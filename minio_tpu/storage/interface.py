"""StorageAPI — the per-disk contract (ref cmd/storage-interface.go:25-82).

Every method has a local implementation (xl.XLStorage) and, in distributed
mode, a remote one (rpc.RemoteStorage) with identical semantics. This seam
is also the fault-injection point for tests (the reference's naughtyDisk
pattern, ref cmd/naughty-disk_test.go).

All data-plane payloads are bytes; erasure/bitrot logic lives above this
layer. Errors are storage.errors types.
"""

from __future__ import annotations

import abc

from .metadata import FileInfo


class StorageAPI(abc.ABC):
    """30-method per-disk contract, grown as layers land."""

    # --- identity / health ---

    @abc.abstractmethod
    def disk_info(self) -> dict:
        """Totals/frees/id (ref DiskInfo)."""

    def is_online(self) -> bool:
        return True

    def endpoint(self) -> str:
        return "local"

    def close(self) -> None:
        pass

    # --- volumes (buckets) ---

    @abc.abstractmethod
    def make_volume(self, volume: str) -> None: ...

    @abc.abstractmethod
    def list_volumes(self) -> list[str]: ...

    @abc.abstractmethod
    def stat_volume(self, volume: str) -> dict: ...

    @abc.abstractmethod
    def delete_volume(self, volume: str, force: bool = False) -> None: ...

    # --- flat files (config, tmp shards) ---

    @abc.abstractmethod
    def write_all(self, volume: str, path: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def read_all(self, volume: str, path: str) -> bytes: ...

    @abc.abstractmethod
    def read_file(self, volume: str, path: str, offset: int,
                  length: int) -> bytes:
        """Ranged read (ref ReadFileStream)."""

    @abc.abstractmethod
    def create_file(self, volume: str, path: str, data: bytes) -> None:
        """Write a (shard) file, creating parents (ref CreateFile)."""

    @abc.abstractmethod
    def delete(self, volume: str, path: str, recursive: bool = False,
               ) -> None: ...

    @abc.abstractmethod
    def rename_file(self, src_volume: str, src_path: str, dst_volume: str,
                    dst_path: str) -> None: ...

    @abc.abstractmethod
    def list_dir(self, volume: str, path: str) -> list[str]:
        """Entries of a directory; dirs have a trailing '/'."""

    # --- object versions (xl.meta) ---

    @abc.abstractmethod
    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str) -> None:
        """Atomic object commit: move tmp data dir + merge version into
        dst xl.meta (ref RenameData, cmd/xl-storage.go:1972)."""

    @abc.abstractmethod
    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        """Merge one version into xl.meta (ref WriteMetadata)."""

    @abc.abstractmethod
    def read_version(self, volume: str, path: str,
                     version_id: str = "") -> FileInfo:
        """Read one version's FileInfo ("" = latest)
        (ref ReadVersion)."""

    @abc.abstractmethod
    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        """Remove a version; drops data dir when last reference goes
        (ref DeleteVersion)."""

    @abc.abstractmethod
    def read_versions(self, volume: str, path: str) -> list[FileInfo]:
        """All versions of one object, newest first (ref ReadVersion on
        the full xlMetaV2 versions array, cmd/xl-storage-format-v2.go)."""

    @abc.abstractmethod
    def read_parts(self, volume: str, path: str, data_dir: str,
                   ) -> list[str]:
        """List part files of a version's data dir (ref CheckParts)."""

    @abc.abstractmethod
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        """Deep bitrot scan of this disk's shard for fi; raises
        FileCorrupt on mismatch (ref VerifyFile, cmd/xl-storage.go:2380)."""
