"""Object metadata: FileInfo and the on-disk xl.meta format.

The reference stores per-object metadata as msgpack `xl.meta` v2 files
(ref cmd/xl-storage-format-v2.go:34,200: a versions array where each
version holds erasure geometry, per-part sizes, bitrot checksums, and an
optional inline data blob). This rebuild keeps the same information model
but serializes as canonical JSON — debuggable, schema-stable, and not a
copy of the reference's codegen; a binary codec can slot in later behind
the same to_dict/from_dict seam.

FileInfo is the in-memory form handed across StorageAPI
(ref cmd/storage-datatypes.go FileInfo).
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field

XL_META_FORMAT = "xl-tpu/1"
XL_META_FILE = "xl.meta"

ERASURE_ALGORITHM = "rs-vandermonde"  # ref erasureAlgorithm "ReedSolomon"
# Regenerating code (REGEN storage class): repair-by-transfer
# product-matrix MBR (ops/rs_regen.py / erasure/regen/).
REGEN_ALGORITHM = "pm-mbr-rbt"


@dataclass
class ErasureInfo:
    """Erasure geometry + per-part bitrot checksums for one disk's shard
    (ref ErasureInfo, cmd/storage-datatypes.go / xl-storage-format-v2)."""
    algorithm: str = ERASURE_ALGORITHM
    data_blocks: int = 0
    parity_blocks: int = 0
    block_size: int = 0
    index: int = 0                 # 1-based shard index held by this disk
    distribution: list[int] = field(default_factory=list)
    checksums: list[dict] = field(default_factory=list)
    # each: {"part": int, "algorithm": str, "hash": hex str ("" = streaming)}

    def to_dict(self) -> dict:
        return {
            "algorithm": self.algorithm,
            "data": self.data_blocks,
            "parity": self.parity_blocks,
            "blockSize": self.block_size,
            "index": self.index,
            "distribution": list(self.distribution),
            "checksums": list(self.checksums),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ErasureInfo":
        return cls(algorithm=d.get("algorithm", ERASURE_ALGORITHM),
                   data_blocks=d.get("data", 0),
                   parity_blocks=d.get("parity", 0),
                   block_size=d.get("blockSize", 0),
                   index=d.get("index", 0),
                   distribution=list(d.get("distribution", [])),
                   checksums=list(d.get("checksums", [])))

    def shard_size(self) -> int:
        if self.algorithm == REGEN_ALGORITHM:
            # Regen nodes store alpha=d stripe rows of ceil(block/B)
            # bytes each — a different size family from RS's
            # ceil(block/k) (ops/rs_regen.py geometry).
            from ..ops.rs_regen import geometry
            g = geometry(self.data_blocks, self.parity_blocks)
            return g.d * (-(-self.block_size // g.B))
        return -(-self.block_size // self.data_blocks)


@dataclass
class ObjectPartInfo:
    number: int
    size: int           # on-wire (possibly compressed/encrypted) size
    actual_size: int    # original user-data size
    etag: str = ""

    def to_dict(self) -> dict:
        return {"number": self.number, "size": self.size,
                "actualSize": self.actual_size, "etag": self.etag}

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectPartInfo":
        return cls(number=d["number"], size=d["size"],
                   actual_size=d.get("actualSize", d["size"]),
                   etag=d.get("etag", ""))


@dataclass
class FileInfo:
    """Per-disk view of one object version (ref FileInfo,
    cmd/storage-datatypes.go)."""
    volume: str = ""
    name: str = ""
    version_id: str = ""           # "" = null version
    deleted: bool = False          # delete marker
    data_dir: str = ""
    size: int = 0
    mod_time: float = 0.0
    metadata: dict = field(default_factory=dict)
    parts: list[ObjectPartInfo] = field(default_factory=list)
    erasure: ErasureInfo = field(default_factory=ErasureInfo)
    fresh: bool = False            # first write of this object

    def to_version_dict(self) -> dict:
        return {
            "type": "delete-marker" if self.deleted else "object",
            "versionId": self.version_id,
            "dataDir": self.data_dir,
            "size": self.size,
            "modTime": self.mod_time,
            "meta": dict(self.metadata),
            "parts": [p.to_dict() for p in self.parts],
            "erasure": self.erasure.to_dict(),
        }

    @classmethod
    def from_version_dict(cls, volume: str, name: str, d: dict) -> "FileInfo":
        return cls(
            volume=volume, name=name,
            version_id=d.get("versionId", ""),
            deleted=d.get("type") == "delete-marker",
            data_dir=d.get("dataDir", ""),
            size=d.get("size", 0),
            mod_time=d.get("modTime", 0.0),
            metadata=dict(d.get("meta", {})),
            parts=[ObjectPartInfo.from_dict(p) for p in d.get("parts", [])],
            erasure=ErasureInfo.from_dict(d.get("erasure", {})),
        )

    def quorum_key(self) -> tuple:
        """Fields that must agree across disks for metadata quorum
        (ref findFileInfoInQuorum, cmd/erasure-metadata.go — groups by
        mod-time + version + erasure geometry + parts)."""
        return (
            self.version_id, self.deleted, self.data_dir, self.size,
            round(self.mod_time, 6),
            self.erasure.data_blocks, self.erasure.parity_blocks,
            self.erasure.block_size, tuple(self.erasure.distribution),
            tuple((p.number, p.size) for p in self.parts),
        )


def new_version_id() -> str:
    return str(uuid.uuid4())


def new_data_dir() -> str:
    return str(uuid.uuid4())


def now() -> float:
    return time.time()


class XLMeta:
    """The xl.meta versions container (newest first)."""

    def __init__(self, versions: list[dict] | None = None):
        self.versions: list[dict] = versions or []

    @classmethod
    def load(cls, raw: bytes) -> "XLMeta":
        doc = json.loads(raw.decode("utf-8"))
        if doc.get("format") != XL_META_FORMAT:
            raise ValueError(f"bad xl.meta format: {doc.get('format')}")
        return cls(doc.get("versions", []))

    def dump(self) -> bytes:
        return json.dumps({"format": XL_META_FORMAT,
                           "versions": self.versions},
                          sort_keys=True).encode("utf-8")

    def add_version(self, fi: FileInfo) -> None:
        """Insert/replace a version; newest first. A write with the same
        version_id replaces (ref xlMetaV2.AddVersion)."""
        vd = fi.to_version_dict()
        self.versions = [v for v in self.versions
                         if v.get("versionId", "") != fi.version_id]
        self.versions.insert(0, vd)
        self.versions.sort(key=lambda v: v.get("modTime", 0.0), reverse=True)

    def find_version(self, version_id: str) -> dict | None:
        if version_id == "":
            return self.versions[0] if self.versions else None
        for v in self.versions:
            if v.get("versionId", "") == version_id:
                return v
        return None

    def delete_version(self, version_id: str) -> dict | None:
        """Remove a version; returns the removed dict or None."""
        v = self.find_version(version_id)
        if v is not None:
            self.versions.remove(v)
        return v
