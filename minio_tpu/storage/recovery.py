"""Boot-time crash-recovery sweep.

A kill -9 (or power cut) anywhere on the commit path leaves three
kinds of residue on the set's local drives:

1. **Orphaned staging dirs** under ``.minio.sys/tmp`` — a PUT,
   multipart complete, or heal write-back that died before (or midway
   through) its per-disk ``rename_data`` commits. Before this sweep
   they leaked forever.
2. **Orphaned part stage files** (``part.N.<uuid>.stage``) under the
   multipart tree — a ``put_object_part`` that died between streaming
   and promote; the upload session itself stays (clients retry parts),
   only the torn stage is garbage.
3. **Quorum-committed-but-minority-missing objects** — the commit
   fan-out died after write quorum but before every disk committed.
   The object is durable and serves, but below full redundancy, and
   NOTHING would re-queue its repair (the crash also killed the
   in-memory MRF add). Each staging dir carries an ``intent.json``
   breadcrumb (bucket/object) written by the engine for exactly this:
   the sweep maps the orphan back to its object and requeues it
   through the MRF (which PR-11's durable journal now persists).

Everything is **age-gated** (``MINIO_RECOVERY_TMP_AGE`` seconds,
default 60): in distributed layouts a restarting node serves storage
RPC to its peers before its own boot finishes, so a freshly-mtimed
staging dir may be a LIVE remote write, not a crash orphan — recency
is the only signal that distinguishes them, and a leaked dir for one
more boot is cheaper than a torn live PUT.

The sweep runs synchronously at layer attach (S3Server.set_layer),
reports found/cleaned/requeued via metrics2
(``minio_tpu_v2_recovery_swept_total``), a console line, and the admin
``/recovery`` surface, and drives the durable MRF journal replay
(erasure/mrfjournal.py) in the same pass — one boot-time recovery
story, one report.
"""

from __future__ import annotations

import json
import os
import shutil
import time

from .xl import INTENT_FILE, MINIO_META_BUCKET, TMP_DIR


def tmp_gc_age_s() -> float:
    """Age gate for staging residue (seconds). Read per sweep so the
    crash harness can tighten it per process via env."""
    try:
        return float(os.environ.get("MINIO_RECOVERY_TMP_AGE", "60"))
    except ValueError:
        return 60.0


def _read_intent(stage_dir: str) -> tuple[str, str, str] | None:
    """Best-effort (bucket, object, dataDir) from a staging dir's
    breadcrumb. Torn/garbled intents (fsync-less crash window) yield
    None — the dir still GCs, only the requeue hint is lost."""
    try:
        with open(os.path.join(stage_dir, INTENT_FILE), "rb") as f:
            doc = json.loads(f.read())
        return (str(doc["bucket"]), str(doc["object"]),
                str(doc.get("dataDir", "")))
    except Exception:
        return None


def _object_presence(engine, bucket: str, object_name: str,
                     data_dir: str = "") -> tuple[int, int]:
    """(disks that committed the intent's version, disks that
    didn't). With a dataDir hint the check is VERSION-aware: a crash
    mid-OVERWRITE leaves every disk with *some* version (the old one),
    so 'any readable version' would classify the torn commit as fully
    present and never requeue it — the exact case the sweep exists
    for. Without a hint (torn intent, zero-byte objects) it degrades
    to any-version presence. Heal re-classifies under its own lock
    before acting either way."""
    present = absent = 0
    for disk in engine.disks:
        try:
            versions = disk.read_versions(bucket, object_name)
        except Exception:
            absent += 1
            continue
        if not versions:
            absent += 1
        elif not data_dir or any(
                getattr(v, "data_dir", "") == data_dir
                for v in versions):
            present += 1
        else:
            absent += 1
    return present, absent


def sweep_engine(engine, age_s: float | None = None) -> dict:
    """One erasure set's recovery sweep over its LOCAL disks (remote
    disks are their own node's job). Returns the report dict (also
    stashed on ``engine.recovery_report``)."""
    t0 = time.monotonic()
    if age_s is None:
        age_s = tmp_gc_age_s()
    now = time.time()
    found = cleaned = stage_files = 0
    intents: dict[tuple[str, str], str] = {}
    local_disks = 0
    from ..erasure.multipart import MPU_PATH
    for disk in getattr(engine, "disks", []):
        root = getattr(disk, "root", None)
        if root is None:
            continue
        local_disks += 1
        tmp = os.path.join(root, TMP_DIR)
        try:
            names = os.listdir(tmp)
        except OSError:
            names = []
        for name in names:
            path = os.path.join(tmp, name)
            try:
                st = os.lstat(path)
            except OSError:
                continue
            if now - st.st_mtime < age_s:
                continue  # possibly a live write on a shared disk
            found += 1
            if os.path.isdir(path):
                intent = _read_intent(path)
                if intent is not None:
                    b, o, dd = intent
                    # Keep a dataDir hint when any orphan carries one.
                    intents[(b, o)] = intents.get((b, o)) or dd
                shutil.rmtree(path, ignore_errors=True)
                if not os.path.isdir(path):
                    cleaned += 1
            else:
                # Loose tmp files (atomic-write staging, link staging).
                try:
                    os.remove(path)
                    cleaned += 1
                except OSError:
                    pass
        # Torn multipart part stages: the upload session survives (a
        # client retries the part), only `.stage` remnants are
        # garbage.
        mpu = os.path.join(root, MINIO_META_BUCKET, MPU_PATH)
        for dirpath, _dirs, files in os.walk(mpu):
            for fname in files:
                if not fname.endswith(".stage"):
                    continue
                p = os.path.join(dirpath, fname)
                try:
                    if now - os.lstat(p).st_mtime >= age_s:
                        os.remove(p)
                        stage_files += 1
                except OSError:
                    pass

    # Durable MRF journal replay rides the same boot pass: queued
    # repairs from before the crash re-enter the queue (and the
    # mrf_queue_depth gauge). Replay FIRST, so intent-driven requeues
    # below dedup against it instead of double-counting as "replayed".
    replayed = 0
    mrf = getattr(engine, "mrf", None)
    if mrf is not None and hasattr(mrf, "replay_journal"):
        replayed = mrf.replay_journal()

    # Requeue objects the orphans point at — but only the partially-
    # committed ones (present on SOME disks, missing on others): a
    # fully-absent intent was an uncommitted write (the GC above is
    # the whole recovery), a fully-present one lost only garbage
    # collection.
    requeued: list[str] = []
    for (bucket, object_name) in sorted(intents):
        present, absent = _object_presence(
            engine, bucket, object_name,
            data_dir=intents[(bucket, object_name)])
        if present > 0 and absent > 0:
            engine.mrf.add(bucket, object_name)
            requeued.append(f"{bucket}/{object_name}")

    report = {
        "localDisks": local_disks,
        "found": found, "cleaned": cleaned,
        "stageFiles": stage_files,
        "requeued": requeued, "journalReplayed": replayed,
        "ageGateS": age_s,
        "durationS": round(time.monotonic() - t0, 4),
    }
    engine.recovery_report = report

    if found or stage_files or requeued or replayed:
        from ..obs.metrics2 import METRICS2
        for what, n in (("found", found), ("cleaned", cleaned),
                        ("stage_files", stage_files),
                        ("requeued", len(requeued)),
                        ("journal_replayed", replayed)):
            if n:
                METRICS2.inc("minio_tpu_v2_recovery_swept_total",
                             {"what": what}, n)
    # Unconditional one-liner: a boot that swept NOTHING is itself
    # evidence (the crash left no residue / the gate spared it all).
    from ..logger import Logger
    Logger.get().info(
        f"recovery sweep: {found} orphaned staging dir(s) found, "
        f"{cleaned} cleaned, {stage_files} torn part stage(s) "
        f"removed, {len(requeued)} object(s) requeued for heal, "
        f"{replayed} journaled repair(s) replayed "
        f"({report['durationS'] * 1e3:.0f}ms)", "recovery")
    return report


def sweep_layer(layer, age_s: float | None = None) -> list[dict]:
    """Recovery-sweep every erasure set of a layer (server boot).
    Layers without erasure sets (FS backend, gateways) sweep
    nothing."""
    reports: list[dict] = []
    pools = getattr(layer, "pools", None)
    if pools is None:
        pools = [layer]
    for pool in pools:
        for es in getattr(pool, "sets", [pool]):
            if not hasattr(es, "disks") or not hasattr(es, "mrf"):
                continue
            try:
                reports.append(sweep_engine(es, age_s=age_s))
            except Exception:
                from ..logger import Logger
                Logger.get().log_once(
                    "recovery sweep failed for an erasure set",
                    "recovery")
    return reports
